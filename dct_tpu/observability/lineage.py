"""Content-addressed lineage ledger: the causal graph of the loop.

The event log (:mod:`dct_tpu.observability.events`) answers *when*: one
cycle's timeline, keyed by run-correlation ID. This module answers
*which* and *why* across cycles: every artifact the continuous loop
produces or consumes — ingest delta, frozen ETL basis, dataset
snapshot, checkpoint, eval report, gate verdict, deploy package,
serving model-load — becomes a **node** identified by the sha256 of its
content, and every producer/consumer relationship becomes a typed
**edge** (``consumed``, ``produced``, ``promoted``, ``deployed``,
``served_by``). Content addressing makes identity transitive for free:
the checkpoint the trainer saved, the tracking artifact copy, and the
``model.ckpt`` staged into a deploy package hash to the SAME node, so
the graph connects layers that never exchange an ID.

Ledger discipline is exactly the event log's: single-line JSON appended
``O_APPEND`` through one :class:`~dct_tpu.observability.buffered
.BufferedAppender` (atomic for lines under ``PIPE_BUF``; concurrent
ranks/processes can share one ``lineage.jsonl``), every record stamped
with ``run_id``/``trace_id`` so graph hops cross-link with events and
the Perfetto timeline, and any OS error kills the ledger for the rest
of the process — lineage degrades to silence, never a failed run.

Record schema::

    {"ts": ..., "run_id": "dct-...", "trace_id": "dct-...", "rank": ...,
     "type": "node", "kind": "checkpoint", "id": "checkpoint:ab12...",
     "sha256": "<full hex>", "path": "/abs/path", "attrs": {...}}
    {"ts": ..., "run_id": ..., "trace_id": ..., "rank": ...,
     "type": "edge", "edge": "consumed", "src": "<node id>",
     "dst": "<node id>", "attrs": {...}}

Edge direction contract (what the ancestry walk implements): for a
``consumed`` edge the *dst* is upstream of the *src* ("src consumed
dst"); for every other type the *src* is upstream of the *dst* ("src
produced/promoted/deployed/is-served-by dst").

Query CLI (``python -m dct_tpu.observability.lineage``):

- ``trace <node-id | id-prefix | path>`` — walk ancestry (and, with
  ``--down``, descendants) from any artifact;
- ``explain-serving`` — "why is this model serving?": the newest
  model-load node's full chain back to the ingest delta;
- ``audit`` — re-hash every on-disk artifact against the ledger and
  report tampered / missing / orphaned nodes (exit 1 on tampered or
  missing).

Env knobs: ``DCT_LINEAGE`` (default on, and subordinate to
``DCT_OBSERVABILITY``), ``DCT_LINEAGE_DIR`` (ledger directory; default
``DCT_EVENTS_DIR``) — registered in config.ENV_REGISTRY and policed by
dct-lint's env-registry rule like every other knob.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from dct_tpu.observability import events as _events

LEDGER_NAME = "lineage.jsonl"
AUDIT_NAME = "lineage_audit.json"

NODE_KINDS = (
    "ingest_delta",
    "etl_basis",
    "dataset_snapshot",
    "checkpoint",
    "eval_report",
    "gate_verdict",
    "deploy_package",
    "model_load",
)

EDGE_KINDS = ("consumed", "produced", "promoted", "deployed", "served_by")

#: Edge types whose *src* end is the upstream artifact ("src produced
#: dst"); ``consumed`` is the one inverted spelling ("src consumed dst"
#: puts dst upstream). The ancestry walk and the audit's orphan check
#: both read this table — one place to get direction right.
_SRC_IS_UPSTREAM = ("produced", "promoted", "deployed", "served_by")

_ID_HEX = 16  # sha256 prefix length in node ids — 64 bits, plenty


# ----------------------------------------------------------------------
# Content addressing


def sha256_file(path: str, *, chunk: int = 1 << 20) -> str:
    """Streaming sha256 of one file (the same digest discipline as the
    ETL's input fingerprint — constant memory whatever the size)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


#: Mutable annotations written INTO an artifact dir after publish; they
#: must not move the artifact's address. ``eval_report.json`` is the
#: promotion gate's cache, dropped into the challenger package it
#: judges — including it would give the same package a different id
#: before and after gating, severing the served-model -> checkpoint
#: chain.
_DIR_HASH_SKIP = ("eval_report.json",)


def sha256_dir(path: str) -> str:
    """Deterministic sha256 of a directory artifact (dataset snapshot,
    deploy package): sorted relative paths, each contributing its name
    and file digest. In-flight publish debris (``*.tmp.*`` siblings,
    ``.build.<pid>`` staging) and post-publish annotations
    (:data:`_DIR_HASH_SKIP`) are skipped — the address covers the
    published artifact itself."""
    h = hashlib.sha256()
    for root, dirs, files in sorted(os.walk(path)):
        dirs.sort()
        for name in sorted(files):
            if ".tmp" in name or name in _DIR_HASH_SKIP:
                continue
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            h.update(rel.encode())
            h.update(b"\0")
            h.update(sha256_file(full).encode())
            h.update(b"\n")
    return h.hexdigest()


def sha256_path(path: str) -> str:
    """File or directory -> content digest (dispatch on what's there)."""
    return sha256_dir(path) if os.path.isdir(path) else sha256_file(path)


def sha256_json(obj) -> str:
    """Canonical digest of a JSON-able value (gate verdicts, eval
    reports — artifacts whose identity is their content, not a file)."""
    payload = json.dumps(
        _events._jsonable(obj), sort_keys=True, separators=(",", ":")
    )
    return hashlib.sha256(payload.encode()).hexdigest()


def node_id(kind: str, sha: str) -> str:
    return f"{kind}:{sha[:_ID_HEX]}"


# ----------------------------------------------------------------------
# The ledger


class LineageLedger:
    """Append-only JSONL lineage writer; ``path=None`` disables (every
    record method no-ops and returns None). Same failure contract as
    :class:`~dct_tpu.observability.events.EventLog`: any OS error —
    full disk, unwritable ledger dir — kills the ledger for the rest of
    the process; provenance degrades to silence, the run continues."""

    def __init__(
        self,
        path: str | None,
        *,
        run_id: str,
        rank: int | None = None,
        clock=time.time,
        flush_interval: float = 0.0,
        max_records: int = 128,
    ):
        self.path = path
        self.run_id = run_id
        self.rank = rank
        self._clock = clock
        self._dead = False
        self._appender = None
        if path:
            from dct_tpu.observability.buffered import BufferedAppender

            self._appender = BufferedAppender(
                path, flush_interval=flush_interval, max_records=max_records
            )

    @property
    def enabled(self) -> bool:
        return bool(self.path) and not self._dead

    def _emit(self, rec: dict) -> bool:
        rec = {
            "ts": round(self._clock(), 6),
            "run_id": self.run_id,
            "trace_id": self.run_id,
            "rank": self.rank,
            **rec,
        }
        try:
            line = json.dumps(
                _events._jsonable(rec), allow_nan=False
            ) + "\n"
        except ValueError:
            self._dead = True
            return False
        if not self._appender.append(line):
            self._dead = True
            return False
        return True

    def node(
        self,
        kind: str,
        *,
        path: str | None = None,
        content=None,
        sha256: str | None = None,
        attrs: dict | None = None,
    ) -> str | None:
        """Record one artifact node; returns its content-addressed id
        (``"<kind>:<sha256 prefix>"``), or None when the ledger is
        disabled/dead or the artifact cannot be hashed (a racing delete
        is an absent fact, not an error).

        Identity source, in precedence order: an explicit ``sha256``
        (the ETL already digested its input — don't re-read gigabytes),
        ``content`` (a JSON-able value for file-less artifacts like
        gate verdicts), else ``path`` (file or directory re-hash).
        Re-recording the same content is idempotent at the graph level:
        readers merge records by id, so duplicate nodes only add a
        sighting (new path / new attrs), never a new vertex.
        """
        if not self.enabled:
            return None
        if sha256 is None:
            try:
                if content is not None:
                    sha256 = sha256_json(content)
                elif path is not None:
                    sha256 = sha256_path(path)
            except OSError:
                return None
        if sha256 is None:
            return None
        nid = node_id(kind, sha256)
        self._emit({
            "type": "node",
            "kind": kind,
            "id": nid,
            "sha256": sha256,
            "path": os.path.abspath(path) if path else None,
            "attrs": dict(attrs or {}),
        })
        return nid if self.enabled else None

    def edge(
        self, edge: str, src: str | None, dst: str | None, **attrs
    ) -> None:
        """Record one typed edge. None endpoints no-op: hook sites pass
        node() results straight through, and a node that could not be
        recorded must not fabricate half an edge."""
        if not self.enabled or not src or not dst:
            return
        self._emit({
            "type": "edge",
            "edge": edge,
            "src": src,
            "dst": dst,
            "attrs": dict(attrs),
        })

    def retire(self, path: str, **attrs) -> None:
        """Record that an artifact path was deliberately deleted
        (checkpoint retention pruning a superseded best). A tombstone,
        not a node: the audit stops expecting bytes at this path, while
        the retired content's node — and every edge through it — stays
        on the graph. A later publish at the same path re-arms the
        audit for it."""
        if not self.enabled or not path:
            return
        self._emit({
            "type": "retire",
            "path": os.path.abspath(path),
            "attrs": dict(attrs),
        })

    def flush(self) -> None:
        if self._appender is not None:
            self._appender.flush()

    def close(self) -> None:
        if self._appender is not None:
            self._appender.close()


# ----------------------------------------------------------------------
# Run-input context: the trainer declares which dataset snapshot (and
# restored trajectory) this process is learning from; the checkpoint
# manager — which has no data-layer plumbing — then stamps ``consumed``
# edges from every checkpoint it publishes. Process-local by design
# (one training run per process, like the run-correlation ID).

_run_inputs: list[str] = []
_run_inputs_lock = threading.Lock()


def set_run_inputs(ids: list[str | None]) -> None:
    """Replace the process's training-input node set (trainer start)."""
    with _run_inputs_lock:
        _run_inputs[:] = [i for i in ids if i]


def add_run_input(nid: str | None) -> None:
    """Append one input (e.g. the resume checkpoint a restore adopted)."""
    if not nid:
        return
    with _run_inputs_lock:
        if nid not in _run_inputs:
            _run_inputs.append(nid)


def run_inputs() -> list[str]:
    with _run_inputs_lock:
        return list(_run_inputs)


# ----------------------------------------------------------------------
# Process default (same shape as events.get_default: explicit install
# wins; otherwise env-built and rebuilt whenever the relevant env
# changes, so monkeypatched tests see their own sink).

_explicit: LineageLedger | None = None
_cached: tuple[tuple, LineageLedger] | None = None
_default_lock = threading.Lock()

_ENV_KEYS = (
    "DCT_OBSERVABILITY",
    "DCT_LINEAGE",
    "DCT_LINEAGE_DIR",
    "DCT_EVENTS_DIR",
    "DCT_RUN_ID",
    "DCT_PROCESS_ID",
    "NODE_RANK",
)


def lineage_enabled(env=None) -> bool:
    """THE parse of ``DCT_LINEAGE`` (default on), subordinate to the
    observability master switch — a rig that silenced telemetry must
    not keep paying artifact hashing."""
    if not _events.observability_enabled(env):
        return False
    raw = (env if env is not None else os.environ).get("DCT_LINEAGE")
    if raw is None:
        return True
    return raw.strip().lower() in ("1", "true", "yes", "on")


def ledger_dir(env=None) -> str:
    """The ledger directory: ``DCT_LINEAGE_DIR`` when set, else the
    event-log directory (one grep-able place per run by default)."""
    e = env if env is not None else os.environ
    return e.get("DCT_LINEAGE_DIR") or e.get("DCT_EVENTS_DIR", "logs/events")


def default_ledger_path(env=None) -> str:
    return os.path.join(ledger_dir(env), LEDGER_NAME)


def set_default(ledger: LineageLedger | None) -> None:
    global _explicit
    _explicit = ledger


def get_default() -> LineageLedger:
    global _cached
    if _explicit is not None:
        return _explicit
    with _default_lock:
        rid = _events.current_run_id()
        key = tuple(os.environ.get(k) for k in _ENV_KEYS)
        if _cached is not None and _cached[0] == key:
            return _cached[1]
        ledger = LineageLedger(
            default_ledger_path() if lineage_enabled() else None,
            run_id=rid,
            rank=_events._rank_from_env(),
        )
        _cached = (key, ledger)
        return ledger


def ledger_from_config(cfg, *, rank: int | None = None) -> LineageLedger:
    """Build the process ledger from an ``ObservabilityConfig`` and
    install it as the default — the trainer's analog of
    :func:`~dct_tpu.observability.events.event_log_from_config`, so
    layers without config plumbing (checkpoint manager) stamp the same
    run ID into the same file."""
    rid = cfg.run_id or _events.current_run_id()
    directory = os.environ.get("DCT_LINEAGE_DIR") or cfg.events_dir
    path = (
        os.path.join(directory, LEDGER_NAME)
        if cfg.enabled and lineage_enabled() and directory
        else None
    )
    ledger = LineageLedger(path, run_id=rid, rank=rank)
    set_default(ledger)
    return ledger


# ----------------------------------------------------------------------
# Reading + graph walks (the CLI, the inspector, and tests)


def read_ledger(path: str) -> list[dict]:
    """Every parseable record, in append order. A torn final line (a
    writer killed mid-append on a no-append-atomicity filesystem) is
    skipped, not fatal — same reader tolerance as the event log's."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return []
    return out


def build_graph(records: list[dict]) -> dict:
    """Records -> ``{"nodes": {id: [records]}, "edges": [records],
    "parents": {id: set}, "children": {id: set}}``. Node sightings
    merge by id (content addressing); direction per the module edge
    contract."""
    nodes: dict[str, list[dict]] = {}
    edges: list[dict] = []
    parents: dict[str, set] = {}
    children: dict[str, set] = {}
    for rec in records:
        if rec.get("type") == "node" and rec.get("id"):
            nodes.setdefault(rec["id"], []).append(rec)
        elif rec.get("type") == "edge" and rec.get("src") and rec.get("dst"):
            edges.append(rec)
            if rec.get("edge") in _SRC_IS_UPSTREAM:
                up, down = rec["src"], rec["dst"]
            else:  # consumed (and any unknown type reads as consumed)
                up, down = rec["dst"], rec["src"]
            parents.setdefault(down, set()).add(up)
            children.setdefault(up, set()).add(down)
    return {
        "nodes": nodes, "edges": edges,
        "parents": parents, "children": children,
    }


def _walk(start: str, link: dict[str, set]) -> list[str]:
    """BFS over one direction's adjacency; cycle-safe; excludes start."""
    seen = {start}
    order: list[str] = []
    frontier = [start]
    while frontier:
        nxt: list[str] = []
        for nid in frontier:
            for peer in sorted(link.get(nid, ())):
                if peer not in seen:
                    seen.add(peer)
                    order.append(peer)
                    nxt.append(peer)
        frontier = nxt
    return order


def ancestors(graph: dict, nid: str) -> list[str]:
    """Everything upstream of ``nid`` (BFS order, nearest first)."""
    return _walk(nid, graph["parents"])


def descendants(graph: dict, nid: str) -> list[str]:
    """Everything downstream of ``nid`` (BFS order, nearest first)."""
    return _walk(nid, graph["children"])


def resolve(graph: dict, artifact: str) -> str | None:
    """A CLI argument -> node id: exact id, unique id/sha prefix, or a
    filesystem path (re-hashed and matched by content)."""
    if artifact in graph["nodes"]:
        return artifact
    if os.path.exists(artifact):
        try:
            sha = sha256_path(artifact)
        except OSError:
            return None
        for nid, recs in graph["nodes"].items():
            if any(r.get("sha256") == sha for r in recs):
                return nid
        return None
    hits = [
        nid
        for nid, recs in graph["nodes"].items()
        if nid.startswith(artifact)
        or nid.split(":", 1)[-1].startswith(artifact)
        or any((r.get("sha256") or "").startswith(artifact) for r in recs)
    ]
    return hits[0] if len(hits) == 1 else None


def head_hash(path: str | None = None) -> str | None:
    """sha256 of the ledger's newest record line — the append-only
    log's "head", cheap to stamp into bench/trajectory records so they
    join the ledger at a known graph state. None when no ledger."""
    path = path or default_ledger_path()
    last = b""
    try:
        with open(path, "rb") as f:
            for line in f:
                if line.strip():
                    last = line
    except OSError:
        return None
    if not last:
        return None
    return hashlib.sha256(last.rstrip(b"\n")).hexdigest()


# ----------------------------------------------------------------------
# Metrics: exposition rendered from the ledger itself (the writers are
# short-lived DAG-task processes; the file is the durable aggregate, so
# the long-lived serving process can scrape totals the same way it
# scrapes the gate ledger).


def render_lineage_metrics(directory: str | None = None) -> str:
    """Prometheus text for ``dct_lineage_nodes_total`` (per node kind)
    and ``dct_lineage_audit_failures_total`` (from the last audit's
    published summary). Best-effort: no ledger -> empty string."""
    directory = directory or ledger_dir()
    try:
        records = read_ledger(os.path.join(directory, LEDGER_NAME))
        if not records:
            return ""
        by_kind: dict[str, int] = {}
        for rec in records:
            if rec.get("type") == "node":
                by_kind[rec.get("kind") or "unknown"] = (
                    by_kind.get(rec.get("kind") or "unknown", 0) + 1
                )
        lines = [
            "# HELP dct_lineage_nodes_total Lineage ledger artifact "
            "nodes recorded, by kind.",
            "# TYPE dct_lineage_nodes_total counter",
        ]
        for kind in sorted(by_kind):
            lines.append(
                f'dct_lineage_nodes_total{{kind="{kind}"}} {by_kind[kind]}'
            )
        failures = 0
        try:
            with open(os.path.join(directory, AUDIT_NAME)) as f:
                audit = json.load(f)
            failures = int(audit.get("tampered", 0)) + int(
                audit.get("missing", 0)
            )
        except (OSError, ValueError):
            pass
        lines += [
            "# HELP dct_lineage_audit_failures_total Tampered + missing "
            "artifacts found by the last lineage audit.",
            "# TYPE dct_lineage_audit_failures_total counter",
            f"dct_lineage_audit_failures_total {failures}",
        ]
        return "\n".join(lines) + "\n"
    except Exception:  # noqa: BLE001 — scrape surface, never a 500
        return ""


# ----------------------------------------------------------------------
# Integrity audit


def run_audit(ledger_path: str) -> dict:
    """Re-hash every on-disk artifact against the ledger.

    Per path, only the NEWEST node record is authoritative — mutable
    publish paths (``last.ckpt``, a growing dataset snapshot) are
    re-recorded on every publish, and history is history, not tamper.
    Nodes without a path (gate verdicts, in-memory eval reports) have
    no bytes to audit and are skipped. ``orphaned`` counts node ids no
    edge touches — recorded but causally disconnected, usually a hook
    that forgot its edge.

    Returns the summary dict (also published atomically beside the
    ledger for the metrics exposition):
    ``{checked, ok, tampered, missing, orphaned, failures: [...]}``.
    """
    records = read_ledger(ledger_path)
    graph = build_graph(records)
    newest_by_path: dict[str, dict] = {}
    for rec in records:
        if rec.get("type") == "node" and rec.get("path") and rec.get("sha256"):
            newest_by_path[rec["path"]] = rec
        elif rec.get("type") == "retire" and rec.get("path"):
            # Deliberate deletion (retention pruning): stop expecting
            # bytes here unless a later record re-publishes the path.
            newest_by_path.pop(rec["path"], None)
    failures: list[dict] = []
    ok = 0
    for path, rec in sorted(newest_by_path.items()):
        if not os.path.exists(path):
            failures.append(
                {"status": "missing", "id": rec["id"], "path": path}
            )
            continue
        try:
            sha = sha256_path(path)
        except OSError:
            failures.append(
                {"status": "missing", "id": rec["id"], "path": path}
            )
            continue
        if sha != rec["sha256"]:
            failures.append({
                "status": "tampered", "id": rec["id"], "path": path,
                "expected": rec["sha256"], "actual": sha,
            })
        else:
            ok += 1
    linked = set(graph["parents"]) | set(graph["children"])
    orphaned = sorted(set(graph["nodes"]) - linked)
    summary = {
        "checked": len(newest_by_path),
        "ok": ok,
        "tampered": sum(1 for f in failures if f["status"] == "tampered"),
        "missing": sum(1 for f in failures if f["status"] == "missing"),
        "orphaned": len(orphaned),
        "orphaned_ids": orphaned,
        "failures": failures,
    }
    # Publish beside the ledger (atomic: the serving scrape and later
    # audits must never read a torn summary). Best-effort like every
    # telemetry write.
    try:
        out = os.path.join(os.path.dirname(ledger_path) or ".", AUDIT_NAME)
        tmp = f"{out}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=2)
        os.replace(tmp, out)
    except OSError:
        pass
    _events.get_default().emit(
        "lineage", "lineage.audit",
        checked=summary["checked"], ok=ok,
        tampered=summary["tampered"], missing=summary["missing"],
        orphaned=summary["orphaned"],
    )
    return summary


# ----------------------------------------------------------------------
# CLI


def _describe(graph: dict, nid: str) -> str:
    recs = graph["nodes"].get(nid, [])
    path = next((r["path"] for r in reversed(recs) if r.get("path")), None)
    run = next((r["run_id"] for r in reversed(recs) if r.get("run_id")), None)
    bits = [nid]
    if path:
        bits.append(f"path={path}")
    if run:
        bits.append(f"run={run}")
    return "  ".join(bits)


def _cmd_trace(graph: dict, artifact: str, down: bool) -> int:
    nid = resolve(graph, artifact)
    if nid is None:
        print(f"lineage: no node matches {artifact!r}")
        return 2
    print(_describe(graph, nid))
    chain = descendants(graph, nid) if down else ancestors(graph, nid)
    arrow = "->" if down else "<-"
    for hop in chain:
        print(f"  {arrow} {_describe(graph, hop)}")
    _events.get_default().emit(
        "lineage", "lineage.trace", node=nid,
        direction="down" if down else "up", hops=len(chain),
    )
    return 0


def _cmd_explain_serving(graph: dict) -> int:
    loads = [
        rec
        for recs in graph["nodes"].values()
        for rec in recs
        if rec.get("kind") == "model_load"
    ]
    if not loads:
        print("lineage: no model_load node in the ledger — nothing serving")
        return 2
    newest = max(loads, key=lambda r: r.get("ts") or 0)
    nid = newest["id"]
    print(f"serving: {_describe(graph, nid)}")
    for k, v in sorted((newest.get("attrs") or {}).items()):
        print(f"  {k}: {v}")
    anc = ancestors(graph, nid)
    by_kind: dict[str, str] = {}
    for hop in anc:
        kind = hop.split(":", 1)[0]
        by_kind.setdefault(kind, hop)
    print("because:")
    for kind in (
        "deploy_package", "gate_verdict", "eval_report", "checkpoint",
        "dataset_snapshot", "etl_basis", "ingest_delta",
    ):
        if kind in by_kind:
            print(f"  {kind:<17} {_describe(graph, by_kind[kind])}")
    _events.get_default().emit(
        "lineage", "lineage.trace", node=nid, direction="up",
        hops=len(anc),
    )
    return 0


def _cmd_audit(ledger_path: str) -> int:
    summary = run_audit(ledger_path)
    print(
        f"lineage audit: {summary['checked']} artifacts checked, "
        f"{summary['ok']} ok, {summary['tampered']} tampered, "
        f"{summary['missing']} missing, {summary['orphaned']} orphaned"
    )
    for f in summary["failures"]:
        print(f"  {f['status'].upper()}: {f['id']}  {f['path']}")
    for nid in summary["orphaned_ids"]:
        print(f"  ORPHANED: {nid}")
    return 1 if summary["tampered"] or summary["missing"] else 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    # --ledger is accepted both before and after the subcommand.
    # SUPPRESS keeps the subparser from clobbering a pre-subcommand
    # value with its own default (subparsers copy their whole
    # namespace over the parent's).
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument(
        "--ledger", default=argparse.SUPPRESS,
        help=f"ledger path (default <DCT_LINEAGE_DIR>/{LEDGER_NAME})",
    )
    parser = argparse.ArgumentParser(
        prog="python -m dct_tpu.observability.lineage",
        description="Query the content-addressed lineage ledger.",
        parents=[common],
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_trace = sub.add_parser(
        "trace", parents=[common],
        help="walk ancestry (default) or descendants of an artifact",
    )
    p_trace.add_argument("artifact", help="node id, id/sha prefix, or path")
    p_trace.add_argument(
        "--down", action="store_true",
        help="walk descendants instead of ancestors",
    )
    sub.add_parser(
        "explain-serving", parents=[common],
        help="why is this model serving? (newest model-load's ancestry)",
    )
    sub.add_parser(
        "audit", parents=[common],
        help="re-hash on-disk artifacts against the ledger "
        "(exit 1 on tampered/missing)",
    )
    args = parser.parse_args(argv)
    ledger_path = getattr(args, "ledger", None) or default_ledger_path()
    if args.cmd == "audit":
        return _cmd_audit(ledger_path)
    graph = build_graph(read_ledger(ledger_path))
    if not graph["nodes"]:
        print(f"lineage: no records in {ledger_path}")
        return 2
    if args.cmd == "trace":
        return _cmd_trace(graph, args.artifact, args.down)
    return _cmd_explain_serving(graph)


if __name__ == "__main__":
    raise SystemExit(main())
