"""Bench-trajectory regression sentinel.

The repo checks in one ``BENCH_r0N.json`` per growth round — a
trajectory nobody was watching: r05's scaled MFU went stale on a dead
relay and the stdout record overflowed to ``parsed: null`` without any
tooling noticing. This CLI reads the trajectory and FLAGS it::

    python -m dct_tpu.observability.report BENCH_r0*.json
    python -m dct_tpu.observability.report            # globs ./BENCH_r*.json

Per round it extracts the comparable series (headline samples/s/chip,
trainer-loop throughput, serving single-row p50, serving-load saturated
qps), then compares CONSECUTIVE comparable rounds:

- a throughput metric dropping more than ``--threshold`` (default 10%)
  is a REGRESSION finding;
- a latency metric rising more than ``--latency-threshold`` (default
  25%) likewise;
- a round whose record is unparsable (``parsed: null`` — the stdout
  tail overflowed) or whose headline metric NAME changed is reported
  and excluded from deltas (comparing different metrics is noise, not
  signal).

Exit code 0 by default (the sentinel reports; CI decides) — ``--strict``
exits 1 when any regression is flagged. Read-only over the records.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: (label, path into parsed record, direction) — direction "up" means
#: bigger is better (drops regress), "down" means smaller is better
#: (rises regress).
SERIES = (
    ("headline", ("value",), "up"),
    ("trainer_loop", ("trainer_loop_samples_per_sec_per_chip",), "up"),
    ("serving_p50_ms", ("serving", "single_row", "numpy_p50_ms"), "down"),
    ("serving_load_qps", ("serving_load", "saturated_qps"), "up"),
    # Restart/spin-up debt (the restart_spinup bench leg): warm
    # time-from-SIGKILL-to-first-step and warm endpoint
    # time-to-first-score — cold-start latencies gated at the same
    # >25% rise threshold as the serving latency series.
    ("warm_step_s", ("restart_spinup", "warm_step_s"), "down"),
    ("warm_score_s", ("restart_spinup", "warm_score_s"), "down"),
    # Always-on loop (the cycle_freshness bench leg): data-arrival ->
    # deployed-model latency through the overlapped loop, and its
    # advantage over the serial episodic cycle. The latency gates at
    # the >25% rise threshold; the speedup at the >10% drop threshold.
    ("loop_freshness_s", ("cycle_freshness", "loop_mean_freshness_s"),
     "down"),
    ("freshness_speedup", ("cycle_freshness", "freshness_speedup"), "up"),
    # Sharded continuous training (the model_sharded bench leg):
    # partition-rule sharded throughput as a fraction of pure DP at
    # matched config — a drop past the >10% threshold means the sharded
    # layouts started paying for collectives they previously amortized.
    ("sharded_sps_ratio", ("model_sharded", "sharded_sps_ratio"), "up"),
    # Multi-tenant scheduler (the multi_tenant bench leg): the WORST
    # tenant's goodput fraction over its granted leases (a drop past
    # the >10% threshold means arbitration overhead started eating
    # lease time) and the roster's mean round-lease wait (gated like a
    # latency — a >25% rise means tenants queue longer for chips).
    ("tenant_goodput_fraction",
     ("multi_tenant", "min_goodput_fraction"), "up"),
    ("tenant_round_wait_s", ("multi_tenant", "mean_round_wait_s"), "down"),
    # MPMD pipeline trainer (the mpmd_pipeline bench leg): the
    # 1F1B steady-state bubble fraction — gated like a latency (a >25%
    # rise means the per-stage saturation regressed: transfer waits or
    # schedule skew crept into the steady window) — and MPMD throughput
    # as a fraction of the SPMD-GPipe comparator at matched config
    # (a >10% drop means the explicit transfer plane started costing
    # what the lockstep collectives used to).
    ("mpmd_bubble_fraction", ("mpmd_pipeline", "mpmd_steady_bubble"),
     "down"),
    ("mpmd_sps_ratio", ("mpmd_pipeline", "mpmd_sps_ratio"), "up"),
    # Roofline introspection (the roofline bench leg): locally-computed
    # cost-model MFU — the headline efficiency series that can never go
    # stale on a dead relay (flags at the >10% drop threshold) — and
    # the MPMD step's transfer-wait fraction, gated like a latency (a
    # >25% rise means inter-stage comms started eating the step).
    ("program_mfu", ("roofline", "mfu"), "up"),
    ("transfer_wait_frac",
     ("mpmd_pipeline", "mpmd_transfer_wait_frac"), "down"),
    # Elastic serving (the elastic_serving bench leg): p99 of ADMITTED
    # traffic during the 4x overload spike with the controls armed —
    # gated like a latency (a >25% rise means the admission budget or
    # the autoscaler's time-to-capacity regressed) — and the fraction
    # of the spike's offered load shed to keep it bounded (a >25% rise
    # means capacity or scale-up responsiveness dropped, pushing more
    # of the burden onto shedding).
    ("overload_p99_s", ("elastic_serving", "overload_p99_s"), "down"),
    ("shed_fraction", ("elastic_serving", "shed_fraction"), "down"),
    # Telemetry history (the telemetry_history bench leg): seconds from
    # planting a slow_score fault to the detector flagging queue depth
    # anomalous FROM THE ON-DISK HISTORY (a rise means the store/flush/
    # poll pipeline got slower at its one job), and the armed-vs-plain
    # snapshot-publish overhead (a rise means the history hook crept
    # onto the hot path — the bound the buffered flush design exists
    # to hold).
    ("anomaly_detect_latency_s",
     ("telemetry_history", "detect_latency_s"), "down"),
    ("history_publish_overhead_ms",
     ("telemetry_history", "publish_overhead_ms"), "down"),
    # Streaming ingest (the stream_ingest bench leg): events made
    # trainable WITHIN the configured arrival->trainable bound per
    # second of wall through the deployed stream watcher (a >10% drop
    # means the log/consumer/ETL path stopped keeping events fresh at
    # rate), and the stream side's arrival->trainable lag p99 — gated
    # like a latency (a >25% rise means the bounded-lag contract the
    # plane exists for started slipping).
    ("stream_events_per_s", ("stream_ingest", "stream_events_per_s"), "up"),
    ("stream_lag_p99_s", ("stream_ingest", "stream_lag_p99_s"), "down"),
    # Low precision (the low_precision bench leg): the int8 scorer's
    # batch-64 throughput over the f32 twin (a drop means the
    # integer-exact GEMM stopped paying for its quantize overhead),
    # and the bf16-dtype-rules train step's lowered bytes_accessed
    # over f32 at matched config (a rise means the mixed-precision
    # rules stopped shrinking the program's memory traffic — gated
    # like a latency, down = better).
    ("quant_serving_speedup",
     ("low_precision", "quant_serving_speedup"), "up"),
    ("bf16_bytes_ratio", ("low_precision", "bf16_bytes_ratio"), "down"),
)


def _dig(rec: dict, path: tuple):
    cur = rec
    for k in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(k)
    return cur if isinstance(cur, (int, float)) else None


def load_round(path: str) -> dict:
    """One record -> {name, parsable, metric, series: {label: value}}."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        return {"name": name, "parsable": False, "error": str(e)}
    parsed = rec.get("parsed") if isinstance(rec, dict) else None
    if not isinstance(parsed, dict):
        return {
            "name": name, "parsable": False,
            "error": "parsed: null (stdout record overflowed the "
                     "driver tail)",
        }
    out = {
        "name": name,
        "parsable": True,
        "metric": parsed.get("metric"),
        "series": {},
    }
    for label, path_keys, _direction in SERIES:
        v = _dig(parsed, path_keys)
        if v is not None:
            out["series"][label] = float(v)
    if parsed.get("scaled_mfu_stale") and parsed.get("mfu") is None:
        # A dead relay staled the SCALED stanza's on-chip MFU. Since the
        # roofline leg computes the headline MFU locally, staleness only
        # matters when the round has NO local number either (the
        # pre-roofline record shape, e.g. r05) — a round carrying a live
        # local MFU retires the finding.
        out["mfu_stale_reason"] = parsed.get("scaled_mfu_stale_reason")
    return out


def compare_rounds(
    rounds: list[dict],
    *,
    threshold: float = 0.10,
    latency_threshold: float = 0.25,
) -> list[dict]:
    """Consecutive-round deltas -> regression findings."""
    findings: list[dict] = []
    prev = None
    for rnd in rounds:
        if not rnd.get("parsable"):
            findings.append({
                "kind": "unparsable", "round": rnd["name"],
                "detail": rnd.get("error", ""),
            })
            continue
        if prev is not None:
            for label, _path, direction in SERIES:
                a = prev["series"].get(label)
                b = rnd["series"].get(label)
                if a is None or b is None or a <= 0:
                    continue
                if label == "headline" and (
                    prev.get("metric") != rnd.get("metric")
                ):
                    # The headline metric was redefined between rounds:
                    # the numbers are not comparable.
                    continue
                if direction == "up":
                    drop = (a - b) / a
                    if drop > threshold:
                        findings.append({
                            "kind": "regression", "round": rnd["name"],
                            "series": label, "prev": a, "cur": b,
                            "delta_pct": round(-100.0 * drop, 1),
                            "vs": prev["name"],
                        })
                else:
                    rise = (b - a) / a
                    if rise > latency_threshold:
                        findings.append({
                            "kind": "regression", "round": rnd["name"],
                            "series": label, "prev": a, "cur": b,
                            "delta_pct": round(100.0 * rise, 1),
                            "vs": prev["name"],
                        })
        if "mfu_stale_reason" in rnd:
            findings.append({
                "kind": "mfu_stale", "round": rnd["name"],
                "detail": rnd.get("mfu_stale_reason") or "",
            })
        prev = rnd
    return findings


def render_report(rounds: list[dict], findings: list[dict]) -> str:
    lines = ["=" * 72, "dct_tpu bench trajectory", "=" * 72]
    labels = [label for label, _p, _d in SERIES]
    header = f"{'round':18s}" + "".join(f"{h:>18s}" for h in labels)
    lines.append(header)
    for rnd in rounds:
        if not rnd.get("parsable"):
            lines.append(f"{rnd['name']:18s}{'(unparsable)':>18s}")
            continue
        row = f"{rnd['name']:18s}"
        for label in labels:
            v = rnd["series"].get(label)
            row += f"{v:>18.4g}" if v is not None else f"{'-':>18s}"
        lines.append(row)
    lines.append("")
    if findings:
        lines.append(f"Findings ({len(findings)}):")
        for f in findings:
            if f["kind"] == "regression":
                lines.append(
                    f"  REGRESSION {f['round']} {f['series']}: "
                    f"{f['prev']:.4g} -> {f['cur']:.4g} "
                    f"({f['delta_pct']:+.1f}% vs {f['vs']})"
                )
            elif f["kind"] == "unparsable":
                lines.append(
                    f"  UNPARSABLE {f['round']}: {f['detail']}"
                )
            else:
                lines.append(
                    f"  MFU-STALE  {f['round']}: {f['detail']}"
                )
    else:
        lines.append("Findings: none — trajectory holds.")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dct_tpu.observability.report",
        description=(
            "Regression sentinel over the checked-in BENCH_r*.json "
            "trajectory: flags throughput drops, latency rises, "
            "unparsable records and stale MFU between rounds."
        ),
    )
    parser.add_argument(
        "records", nargs="*",
        help="bench record paths (default: ./BENCH_r*.json, sorted)",
    )
    parser.add_argument(
        "--threshold", type=float, default=0.10,
        help="throughput drop fraction that flags (default 0.10)",
    )
    parser.add_argument(
        "--latency-threshold", type=float, default=0.25,
        help="latency rise fraction that flags (default 0.25)",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="exit 1 when any regression is flagged (CI gate mode)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output",
    )
    args = parser.parse_args(argv)
    paths = args.records or sorted(glob.glob("BENCH_r*.json"))
    if not paths:
        print("error: no bench records found", file=sys.stderr)
        return 2
    rounds = [load_round(p) for p in sorted(paths)]
    findings = compare_rounds(
        rounds,
        threshold=args.threshold,
        latency_threshold=args.latency_threshold,
    )
    if args.as_json:
        print(json.dumps(
            {"rounds": rounds, "findings": findings}, indent=2
        ))
    else:
        print(render_report(rounds, findings))
    regressions = [f for f in findings if f["kind"] == "regression"]
    return 1 if (args.strict and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
