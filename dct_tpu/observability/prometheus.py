"""Prometheus text exposition (format 0.0.4), dependency-free.

The serving server's ``GET /metrics`` and the trainer's end-of-run dump
both speak the plain-text exposition format every Prometheus-compatible
scraper (Prometheus, VictoriaMetrics, Grafana Agent, promtool) parses::

    # HELP dct_requests_total Requests served per slot.
    # TYPE dct_requests_total counter
    dct_requests_total{slot="blue"} 42

Only the subset the platform needs is implemented: counter / gauge /
histogram families, label escaping per the spec (backslash, double
quote, newline), and ``+Inf`` bucket handling. No client library, no
registry singletons — families are built from plain data at render
time, which keeps the server handlers stateless over the metrics they
already hold.
"""

from __future__ import annotations

import math

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Default request-latency buckets (seconds) — sub-ms to 10 s, the span
#: from a cached numpy forward to a cold package load.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def _escape_label_value(value) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def format_labels(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


def format_value(value: float) -> str:
    v = float(value)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class MetricFamily:
    """One named metric with HELP/TYPE lines and its samples.

    ``add(value, labels, suffix)`` appends a sample; histogram families
    use suffixes ``_bucket`` / ``_sum`` / ``_count`` (see
    :class:`HistogramAccumulator.samples_into`).
    """

    def __init__(self, name: str, mtype: str, help_text: str):
        if mtype not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unsupported metric type {mtype!r}")
        self.name = name
        self.mtype = mtype
        self.help_text = help_text
        self.samples: list[tuple[str, dict | None, float]] = []

    def add(
        self, value: float, labels: dict | None = None, suffix: str = ""
    ) -> "MetricFamily":
        self.samples.append((suffix, labels, value))
        return self

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help_text}",
            f"# TYPE {self.name} {self.mtype}",
        ]
        for suffix, labels, value in self.samples:
            lines.append(
                f"{self.name}{suffix}{format_labels(labels)} "
                f"{format_value(value)}"
            )
        return "\n".join(lines)


def render(families: list[MetricFamily]) -> str:
    """Full exposition body (trailing newline included, as scrapers
    expect)."""
    return "\n".join(f.render() for f in families) + "\n"


class HistogramAccumulator:
    """Cumulative-bucket histogram (the Prometheus layout: ``le``
    buckets are CUMULATIVE counts, plus ``_sum`` and ``_count``)."""

    def __init__(self, buckets: tuple[float, ...] = LATENCY_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        # counts[i] = observations <= buckets[i]; the +Inf bucket is
        # implicit (== count).
        self.counts = [0] * len(self.buckets)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        for i, le in enumerate(self.buckets):
            if v <= le:
                for j in range(i, len(self.counts)):
                    self.counts[j] += 1
                break

    def samples_into(
        self, family: MetricFamily, labels: dict | None = None
    ) -> None:
        base = dict(labels or {})
        for le, c in zip(self.buckets, self.counts):
            family.add(c, {**base, "le": format_value(le)}, "_bucket")
        family.add(self.count, {**base, "le": "+Inf"}, "_bucket")
        family.add(self.sum, base or None, "_sum")
        family.add(self.count, base or None, "_count")
