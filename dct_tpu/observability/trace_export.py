"""Chrome-trace-event export: merge all ranks' span files into one
Perfetto-loadable ``trace.json``.

The span runtime (:mod:`spans`) leaves one JSONL file per process in a
spans directory; this module joins them into the Chrome Trace Event
format (the JSON object form, ``{"traceEvents": [...]}``) that
``ui.perfetto.dev`` and ``chrome://tracing`` load directly — the
platform-level timeline (launcher / per-rank epochs / checkpoints /
deploy) that complements the per-device ``jax.profiler`` trace.

Mapping:

- every completed span becomes one complete event (``"ph": "X"``) with
  microsecond ``ts``/``dur``;
- ``pid`` is the *track group*: rank processes map to ``pid = rank``,
  orchestrator-side processes (launcher, DAG tasks, serving) to stable
  ids above ``ORCHESTRATOR_PID_BASE``, each named by a ``process_name``
  metadata event ("rank 0", "launcher/host pid 4242");
- ``tid`` is the recorder's small per-thread id;
- span/parent IDs and attrs ride in ``args``, so the parent/child tree
  is recoverable from the exported file alone.

The merge is DETERMINISTIC: events are ordered by (start time, span id)
and metadata by pid, so exporting the same span files twice yields
byte-identical JSON — diffable artifacts, stable test fixtures.
"""

from __future__ import annotations

import glob
import json
import os

#: Orchestrator-side (rank-less) processes get pids from here upward so
#: they can never collide with rank pids.
ORCHESTRATOR_PID_BASE = 100000


def find_span_files(root: str) -> list[str]:
    """Span JSONL files under ``root``: the directory itself if it holds
    ``*.jsonl``, else any ``spans/*.jsonl`` found by a bounded walk
    (run dirs nest the spans dir under the events dir)."""
    direct = sorted(glob.glob(os.path.join(root, "*.jsonl")))
    if os.path.basename(os.path.normpath(root)) == "spans" and direct:
        return direct
    out = []
    for dirpath, dirnames, _ in os.walk(root):
        dirnames.sort()
        if os.path.basename(dirpath) == "spans":
            out.extend(sorted(glob.glob(os.path.join(dirpath, "*.jsonl"))))
            dirnames[:] = []
    if not out and direct:
        # A bare directory of span files (no spans/ nesting).
        return direct
    return out


def read_jsonl(path: str, *, require_key: str) -> list[dict]:
    """Tolerant JSONL read shared by the exporter and the inspector:
    torn lines (a crash mid-append) and non-dict/foreign records are
    skipped — one bad line must not poison the whole artifact."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    out = []
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and require_key in rec:
            out.append(rec)
    return out


def read_spans(root: str, *, trace_id: str | None = None) -> list[dict]:
    """All span records under ``root`` (optionally filtered to one
    trace), sorted by (t0, span_id) for deterministic downstream use."""
    recs: list[dict] = []
    for path in find_span_files(root):
        for rec in read_jsonl(path, require_key="span_id"):
            if trace_id and rec.get("trace_id") != trace_id:
                continue
            recs.append(rec)
    recs.sort(key=lambda r: (r.get("t0", 0.0), r.get("span_id", "")))
    return recs


def _pid_for(rec: dict, orch_pids: dict) -> int:
    rank = rec.get("rank")
    if rank is not None:
        return int(rank)
    pid = rec.get("pid", 0)
    if pid not in orch_pids:
        orch_pids[pid] = ORCHESTRATOR_PID_BASE + len(orch_pids)
    return orch_pids[pid]


def to_chrome_trace(spans: list[dict]) -> dict:
    """Span records -> Chrome Trace Event JSON object (Perfetto-ready).

    ``spans`` need not be pre-sorted; the output event order (and
    therefore the serialized bytes) depends only on the record set.
    """
    spans = sorted(
        spans, key=lambda r: (r.get("t0", 0.0), r.get("span_id", ""))
    )
    orch_pids: dict = {}
    events: list[dict] = []
    seen_pids: dict[int, str] = {}
    for rec in spans:
        pid = _pid_for(rec, orch_pids)
        if pid not in seen_pids:
            rank = rec.get("rank")
            seen_pids[pid] = (
                f"rank {rank}"
                if rank is not None
                else f"{rec.get('component', 'host')}/host pid "
                f"{rec.get('pid', '?')}"
            )
        t0 = float(rec.get("t0", 0.0))
        t1 = float(rec.get("t1", t0))
        args = {
            "span_id": rec.get("span_id"),
            "parent_id": rec.get("parent_id"),
            "trace_id": rec.get("trace_id"),
        }
        attrs = rec.get("attrs")
        if isinstance(attrs, dict):
            args.update(attrs)
        events.append(
            {
                "name": rec.get("name", "span"),
                "cat": rec.get("component", "span"),
                "ph": "X",
                "ts": round(t0 * 1e6, 3),
                "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                "pid": pid,
                "tid": int(rec.get("tid", 0)),
                "args": args,
            }
        )
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": seen_pids[pid]},
        }
        for pid in sorted(seen_pids)
    ]
    trace_ids = sorted({r.get("trace_id") for r in spans if r.get("trace_id")})
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_ids": trace_ids},
    }


def write_trace(trace: dict, out_path: str) -> str:
    """Serialize (strict JSON, stable key order) with tmp+rename so a
    concurrent reader never sees a torn file."""
    parent = os.path.dirname(out_path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f, allow_nan=False, sort_keys=True)
    os.replace(tmp, out_path)
    return out_path


def export_run(
    run_dir: str,
    *,
    out_path: str | None = None,
    trace_id: str | None = None,
) -> tuple[str, list[dict]]:
    """Merge every span file under ``run_dir`` into
    ``<run_dir>/trace.json`` (or ``out_path``). Returns (path, spans)."""
    spans = read_spans(run_dir, trace_id=trace_id)
    path = out_path or os.path.join(run_dir, "trace.json")
    write_trace(to_chrome_trace(spans), path)
    return path, spans
