"""Online anomaly detection over the on-disk metric history.

The SLO monitor answers "is the service meeting its stated objective";
this module answers the softer operational question "does this signal
look like itself" — EWMA/z-score change detection over the
:mod:`~dct_tpu.observability.timeseries` store, so a queue-depth ramp,
a step-time regression or a loss spike is flagged without anyone
having written a threshold for it (ISSUE 17).

Semantics, deliberately mirroring the SLO monitor's edge-triggering:

- every poll, each :class:`Watch` is reduced to ONE scalar from the
  history store (gauge combined-last / counter rate / histogram window
  mean — *never* from in-process state, so a detector in the pool
  parent sees the whole fleet and survives worker restarts);
- the scalar feeds an exponentially-weighted mean/variance baseline;
  once ``min_points`` samples are in, a deviation of ``z`` sigmas in
  the watched direction flips the signal anomalous — the baseline then
  FREEZES (an anomaly must not teach the detector that anomalous is
  normal) until the value re-enters ``z/2`` sigmas, which resolves it;
- edges emit ``anomaly.detected`` / ``anomaly.resolved`` events and
  drive ``dct_anomaly_active{signal}`` / ``dct_anomaly_total{signal}``
  on the supplied registry, and the ``on_anomaly`` callback hands the
  record to the incident assembler.

:func:`arm_from_env` is the one-call wiring used by the serving
server, the scheduler and the launcher: reader + detector + incident
manager + poll thread, or None when ``DCT_TS_DIR`` is unset.
"""

from __future__ import annotations

import math
import threading
import time

from dct_tpu.observability.timeseries import HistoryReader

#: Variance floor: 5% of the baseline mean (squared), so a perfectly
#: flat healthy signal does not alert on measurement noise, while a
#: zero-mean signal (shed rate) still alerts on its first real burst.
_REL_VAR_FLOOR = 0.05
_ABS_VAR_FLOOR = 1e-12


class Watch:
    """One watched signal: how to reduce a family to a scalar, and
    which direction of departure is trouble."""

    __slots__ = ("name", "metric", "kind", "direction", "window_s")

    def __init__(
        self,
        name: str,
        metric: str,
        *,
        kind: str = "gauge",
        direction: str = "both",
        window_s: float = 30.0,
    ):
        if kind not in ("gauge", "rate", "hist_mean"):
            raise ValueError(f"unknown watch kind: {kind!r}")
        if direction not in ("high", "low", "both"):
            raise ValueError(f"unknown watch direction: {direction!r}")
        self.name = name
        self.metric = metric
        self.kind = kind
        self.direction = direction
        self.window_s = float(window_s)


def default_watches(*, window_s: float = 30.0) -> list[Watch]:
    """The ISSUE 17 signal set: step time, goodput, queue depth, shed
    rate, program MFU, grad norm — plus val-loss (the loss-spike
    detector's fleet-visible twin) and stream consumer lag (a stalled
    or slow consumer shows up as a lag level shift long before the
    freshness SLO budget burns)."""
    w = window_s
    return [
        Watch("step_time", "dct_train_step_seconds",
              direction="high", window_s=w),
        Watch("goodput_fraction", "dct_train_goodput_fraction",
              direction="low", window_s=w),
        Watch("queue_depth", "dct_serve_queue_depth",
              kind="hist_mean", direction="high", window_s=w),
        Watch("shed_rate", "dct_serve_shed_total",
              kind="rate", direction="high", window_s=w),
        Watch("program_mfu", "dct_program_mfu",
              direction="low", window_s=w),
        Watch("grad_norm", "dct_train_grad_norm",
              direction="high", window_s=w),
        Watch("val_loss", "dct_train_val_loss",
              direction="high", window_s=w),
        Watch("stream_lag", "dct_stream_lag_seconds",
              direction="high", window_s=w),
    ]


class _WatchState:
    __slots__ = ("mean", "var", "n", "active", "since", "last")

    def __init__(self):
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.active = False
        self.since = 0.0
        self.last = None


class AnomalyDetector:
    """EWMA/z-score change detection over a :class:`HistoryReader`."""

    def __init__(
        self,
        reader: HistoryReader,
        *,
        watches: list[Watch] | None = None,
        z: float = 4.0,
        alpha: float = 0.3,
        min_points: int = 8,
        registry=None,
        emit=None,
        on_anomaly=None,
        clock=time.time,
    ):
        self.reader = reader
        self.watches = list(watches) if watches is not None else (
            default_watches()
        )
        self.z = float(z)
        self.alpha = min(1.0, max(0.001, float(alpha)))
        self.min_points = max(1, int(min_points))
        self._emit = emit
        self._on_anomaly = on_anomaly
        self._clock = clock
        self._states = {w.name: _WatchState() for w in self.watches}
        self._active_g = self._total_c = None
        if registry is not None:
            self._active_g = registry.gauge(
                "dct_anomaly_active",
                "1 while the named signal is anomalous (EWMA z-score "
                "over the telemetry history store), else 0.",
                agg="max",
            )
            self._total_c = registry.counter(
                "dct_anomaly_total",
                "Anomaly episodes detected per signal since start.",
            )
            for w in self.watches:
                self._active_g.set(0.0, {"signal": w.name})

    # -- one watch, one sample ------------------------------------------

    def _zscore(self, st: _WatchState, value: float) -> float:
        floor = max(
            _ABS_VAR_FLOOR, (abs(st.mean) * _REL_VAR_FLOOR) ** 2
        )
        return (value - st.mean) / math.sqrt(max(st.var, floor))

    def observe(self, watch: Watch, value: float, *, now: float) -> None:
        """Feed one scalar; fires/resolves on edges. Exposed for unit
        tests — :meth:`poll` is the production entry."""
        st = self._states[watch.name]
        st.last = value
        zs = self._zscore(st, value) if st.n >= self.min_points else 0.0
        directed = (
            zs if watch.direction == "high"
            else -zs if watch.direction == "low"
            else abs(zs)
        )
        if st.active:
            if abs(zs) <= self.z / 2.0:
                st.active = False
                self._edge(watch, st, "anomaly.resolved", value, zs, now)
            else:
                return  # baseline frozen while anomalous
        elif st.n >= self.min_points and directed >= self.z:
            st.active = True
            st.since = now
            if self._total_c is not None:
                self._total_c.inc(1, {"signal": watch.name})
            self._edge(watch, st, "anomaly.detected", value, zs, now)
            return  # the anomalous sample must not enter the baseline
        diff = value - st.mean
        incr = self.alpha * diff
        st.mean += incr
        st.var = (1.0 - self.alpha) * (st.var + diff * incr)
        st.n += 1

    def _edge(
        self, watch: Watch, st: _WatchState, event: str,
        value: float, zs: float, now: float,
    ) -> None:
        if self._active_g is not None:
            self._active_g.set(
                1.0 if st.active else 0.0, {"signal": watch.name}
            )
        rec = {
            "signal": watch.name,
            "metric": watch.metric,
            "kind": watch.kind,
            "direction": watch.direction,
            "value": round(float(value), 6),
            "zscore": round(float(zs), 3),
            "baseline_mean": round(float(st.mean), 6),
            "ts": now,
        }
        if event == "anomaly.resolved":
            rec["duration_s"] = round(max(0.0, now - st.since), 3)
        if self._emit is not None:
            try:
                self._emit("anomaly", event, **rec)
            except Exception:  # noqa: BLE001 — telemetry never fails the run
                pass
        if event == "anomaly.detected" and self._on_anomaly is not None:
            try:
                self._on_anomaly(rec)
            except Exception:  # noqa: BLE001
                pass

    # -- store-driven polling -------------------------------------------

    def _read(self, watch: Watch, now: float) -> float | None:
        if watch.kind == "rate":
            return self.reader.counter_rate(
                watch.metric, window_s=watch.window_s, now=now
            )
        if watch.kind == "hist_mean":
            return self.reader.hist_mean(
                watch.metric, window_s=watch.window_s, now=now
            )
        return self.reader.gauge_last(
            watch.metric, window_s=watch.window_s, now=now
        )

    def poll(self, *, now: float | None = None) -> list[dict]:
        """One detection pass over every watch; returns the signals
        currently anomalous (the monitor thread discards this; tests
        and the incident CLI use it)."""
        if now is None:
            now = self._clock()
        for watch in self.watches:
            try:
                value = self._read(watch, now)
            except Exception:  # noqa: BLE001 — a torn segment or racing
                continue  # compaction must not kill the poll loop
            if value is None or not math.isfinite(value):
                continue
            self.observe(watch, value, now=now)
        return self.active()

    def active(self) -> list[dict]:
        out = []
        for w in self.watches:
            st = self._states[w.name]
            if st.active:
                out.append({
                    "signal": w.name, "metric": w.metric,
                    "since": st.since, "value": st.last,
                })
        return out


class HistoryMonitor:
    """Daemon poll loop around a detector (and, via ``on_anomaly``,
    the incident assembler). One per arming process."""

    def __init__(
        self,
        detector: AnomalyDetector,
        *,
        poll_s: float = 2.0,
        incidents=None,
        reader: HistoryReader | None = None,
    ):
        self.detector = detector
        self.incidents = incidents
        self.reader = reader if reader is not None else detector.reader
        self.poll_s = max(0.1, float(poll_s))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "HistoryMonitor":
        self._thread = threading.Thread(
            target=self._loop, name="dct-anomaly-monitor", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.detector.poll()
            except Exception:  # noqa: BLE001 — detection never kills a proc
                continue

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self.incidents is not None:
            self.incidents.close()


def arm_from_env(
    *,
    registry=None,
    emit=None,
    watches: list[Watch] | None = None,
    clock=time.time,
) -> HistoryMonitor | None:
    """Build the whole detection plane from env: history reader +
    anomaly detector (``DCT_ANOMALY``) + incident assembler
    (``DCT_INCIDENT``) + started poll thread. None when ``DCT_TS_DIR``
    is unset or detection is disabled — callers treat None as 'plane
    off' and keep their in-memory paths."""
    from dct_tpu.config import ObservabilityConfig

    obs = ObservabilityConfig.from_env()
    if not obs.ts_dir or not obs.anomaly:
        return None
    try:
        reader = HistoryReader(obs.ts_dir, clock=clock)
        incidents = None
        if obs.incident:
            from dct_tpu.observability.incident import IncidentManager

            incidents = IncidentManager.from_env(
                obs, reader=reader, emit=emit, clock=clock
            )
        detector = AnomalyDetector(
            reader,
            watches=watches if watches is not None else default_watches(
                window_s=obs.anomaly_window_s
            ),
            z=obs.anomaly_z,
            alpha=obs.anomaly_alpha,
            min_points=obs.anomaly_min_points,
            registry=registry,
            emit=emit,
            on_anomaly=(
                incidents.on_anomaly if incidents is not None else None
            ),
            clock=clock,
        )
        return HistoryMonitor(
            detector, poll_s=obs.anomaly_poll_s,
            incidents=incidents, reader=reader,
        ).start()
    except Exception:  # noqa: BLE001 — telemetry never fails the run
        return None
