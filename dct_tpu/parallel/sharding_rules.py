"""Declarative partition rules: regex path patterns -> PartitionSpecs.

The scaling-book recipe, made first-class for the CONTINUOUS-training
path (ROADMAP item 1): modules carry load-bearing NAMES (``qkv_proj``/
``ffn_in`` = column-parallel, ``o_proj``/``ffn_out`` = row-parallel), a
per-family RULE TABLE maps ``/``-joined parameter paths to
``PartitionSpec``s over the ``data``/``model``/``seq``/``pipe`` mesh
axes, and ``jit`` inserts the collectives. No imperative communication
anywhere — the analog of the reference's gloo all-reduce is a compiler
decision.

Applied to the WHOLE TrainState: Adam's ``mu``/``nu`` mirror the param
tree, so the same path-pattern match shards optimizer state identically
— giving tensor-parallel training a fully sharded optimizer for free;
``shard_opt``/``shard_params`` additionally split the unmatched leaves'
leading dim over ``data`` (ZeRO-1 / FSDP, per "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training").

The rule surface (docs/PARALLELISM.md §partition rules):

- :data:`FAMILY_RULES` — the per-family default tables (regex, spec);
- ``DCT_SHARD_RULES`` — operator overrides prepended to the family
  table: ``pattern=axes[;pattern=axes...]`` where ``axes`` is a
  comma-separated per-dimension axis list (``data``/``model``/``seq``/
  ``pipe``; ``-`` = replicated dim; the empty string = fully
  replicated leaf). First match wins.
- :func:`match_partition_rules` / :func:`make_shard_and_gather_fns` —
  the snippet-style primitives: a spec tree from the rules, and paired
  place/gather callables per leaf (gather is what the publish path —
  checkpoint deploy tier, package export — runs so serving artifacts
  stay dense).
"""

from __future__ import annotations

import hashlib
import os
import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_NAMES = ("data", "model", "seq", "pipe")

# The transformer-family name rules (column-parallel shards the OUTPUT
# dim, row-parallel the INPUT dim; a row-parallel bias stays replicated
# — it is added after the row all-reduce), plus expert parallelism:
# MoE expert weights are [E, ...] stacks whose leading expert dim
# shards over ``model`` (each shard owns whole experts; the dispatch
# einsum's token exchange compiles to an all-to-all over the same
# axis). The router stays replicated (no rule matches it). Patterns
# are regexes over the ``/``-joined path (params AND their opt_state
# moment mirrors — the moments embed the same path tail).
_TENSOR_PARALLEL_RULES = (
    (r"(^|/)experts_in_kernel$", P("model", None, None)),
    (r"(^|/)experts_in_bias$", P("model", None)),
    (r"(^|/)experts_out_kernel$", P("model", None, None)),
    (r"(^|/)experts_out_bias$", P("model", None)),
    (r"(qkv_proj|ffn_in).*/kernel$", P(None, "model")),
    (r"(qkv_proj|ffn_in).*/bias$", P("model")),
    (r"(o_proj|ffn_out).*/kernel$", P("model", None)),
    (r"(o_proj|ffn_out).*/bias$", P()),
)

#: Per-family default rule tables. Families without an entry use
#: ``None``'s table (the tensor-parallel name rules — a family whose
#: params match no pattern, like the MLP, replicates everywhere, which
#: is exactly pure DP). Override or extend via ``DCT_SHARD_RULES``.
FAMILY_RULES: dict = {
    None: _TENSOR_PARALLEL_RULES,
    "weather_mlp": _TENSOR_PARALLEL_RULES,
    "weather_gru": _TENSOR_PARALLEL_RULES,
    "weather_transformer": _TENSOR_PARALLEL_RULES,
    "weather_transformer_causal": _TENSOR_PARALLEL_RULES,
    "weather_transformer_pp": _TENSOR_PARALLEL_RULES,
    "weather_moe": _TENSOR_PARALLEL_RULES,
}


def parse_rules(text: str):
    """``DCT_SHARD_RULES`` grammar -> tuple of (regex, PartitionSpec).

    ``pattern=axes[;pattern=axes...]``: ``pattern`` is a regex matched
    (``re.search``) against the leaf's ``/``-joined path; ``axes`` is a
    comma-separated per-dimension list of mesh axis names (``-`` for a
    replicated dimension, the empty string for a fully replicated
    leaf). Examples::

        .*dense.*/kernel$=-,model      # shard the output dim
        head/kernel$=                  # force-replicate
    Malformed specs raise ``ValueError`` naming the offending clause —
    a typo'd layout must never silently train replicated.
    """
    rules = []
    for clause in (text or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(
                f"DCT_SHARD_RULES clause {clause!r} has no '=': expected "
                "pattern=axis,axis,..."
            )
        pattern, _, axes = clause.rpartition("=")
        pattern = pattern.strip()
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(
                f"DCT_SHARD_RULES pattern {pattern!r} is not a valid "
                f"regex: {e}"
            ) from e
        dims = []
        if axes.strip():
            for tok in axes.split(","):
                tok = tok.strip()
                if tok in ("-", "", "none", "None"):
                    dims.append(None)
                elif tok in AXIS_NAMES:
                    dims.append(tok)
                else:
                    raise ValueError(
                        f"DCT_SHARD_RULES clause {clause!r}: unknown mesh "
                        f"axis {tok!r} (valid: {', '.join(AXIS_NAMES)}, "
                        "'-' for a replicated dim)"
                    )
        rules.append((pattern, P(*dims)))
    return tuple(rules)


#: parse_rules memo keyed by the raw env string: rule resolution runs
#: once per TREE LEAF (spec_for_path inside the sharding tree-map), and
#: re-validating every regex clause per leaf is pure waste — the env
#: string is invariant within a placement pass.
_PARSE_CACHE: dict[str, tuple] = {}


def rules_for_family(family: str | None = None):
    """The ACTIVE rule table for ``family``: any ``DCT_SHARD_RULES``
    overrides first (first match wins), then the family's defaults."""
    base = FAMILY_RULES.get(family, FAMILY_RULES[None])
    env = os.environ.get("DCT_SHARD_RULES")
    if not env:
        return tuple(base)
    cached = _PARSE_CACHE.get(env)
    if cached is None:
        cached = parse_rules(env)
        if len(_PARSE_CACHE) > 8:  # bound: env strings are few
            _PARSE_CACHE.clear()
        _PARSE_CACHE[env] = cached
    return cached + tuple(base)


def rules_digest(family: str | None = None) -> str:
    """Content digest of the active rule table — part of the AOT
    executable identity (a layout change recompiles; the same layout
    warm-relaunches) and the checkpoint layout manifest."""
    blob = "|".join(
        f"{pat}={','.join(str(a) for a in spec)}"
        for pat, spec in rules_for_family(family)
    )
    return hashlib.sha1(blob.encode()).hexdigest()[:10]


def path_str(path) -> str:
    """A tree path -> the ``/``-joined string the rule regexes match."""
    return "/".join(
        str(getattr(k, "key", getattr(k, "name", k))) for k in path
    )


# ----------------------------------------------------------------------
# Dtype rules: the precision analog of the partition rules. The SAME
# regex-over-param-path grammar as DCT_SHARD_RULES selects which param
# leaves run the forward/backward in low precision
# (``DCT_DTYPE_RULES='.*=bf16'`` = bf16 compute everywhere), while the
# MASTER params, gradients-as-accumulated, and optimizer state stay
# f32: the cast happens INSIDE the traced loss body (train/steps.py),
# so autodiff's cast-vjp routes the bf16 gradients back into f32
# accumulation and nothing below the loss ever sees the low-precision
# copy. Rules off (the default) is the bitwise status quo.

#: Accepted dtype tokens (right-hand side of a clause) -> canonical
#: jax dtype name.
DTYPE_ALIASES = {
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "f16": "float16", "float16": "float16",
    "f32": "float32", "float32": "float32",
}


def parse_dtype_rules(text: str):
    """``DCT_DTYPE_RULES`` grammar -> tuple of (regex, dtype name).

    ``pattern=dtype[;pattern=dtype...]`` — the clause grammar of
    :func:`parse_rules` with a dtype token (bf16/bfloat16, f16/float16,
    f32/float32) where the axis list would be. Malformed specs raise
    ``ValueError`` naming the offending clause — a typo'd precision
    must never silently train full-width."""
    rules = []
    for clause in (text or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(
                f"DCT_DTYPE_RULES clause {clause!r} has no '=': expected "
                "pattern=dtype"
            )
        pattern, _, dname = clause.rpartition("=")
        pattern = pattern.strip()
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(
                f"DCT_DTYPE_RULES pattern {pattern!r} is not a valid "
                f"regex: {e}"
            ) from e
        canonical = DTYPE_ALIASES.get(dname.strip().lower())
        if canonical is None:
            raise ValueError(
                f"DCT_DTYPE_RULES clause {clause!r}: unknown dtype "
                f"{dname.strip()!r} (valid: "
                f"{', '.join(sorted(set(DTYPE_ALIASES)))})"
            )
        rules.append((pattern, canonical))
    return tuple(rules)


_DTYPE_PARSE_CACHE: dict[str, tuple] = {}


def dtype_rules():
    """The active ``DCT_DTYPE_RULES`` table (empty tuple when unset) —
    memoized per env string like the partition-rule cache."""
    env = os.environ.get("DCT_DTYPE_RULES")
    if not env:
        return ()
    cached = _DTYPE_PARSE_CACHE.get(env)
    if cached is None:
        cached = parse_dtype_rules(env)
        if len(_DTYPE_PARSE_CACHE) > 8:
            _DTYPE_PARSE_CACHE.clear()
        _DTYPE_PARSE_CACHE[env] = cached
    return cached


def dtype_rules_digest() -> str:
    """Content digest of the active dtype rules, joined into the AOT
    program identity (trainer) and the checkpoint layout manifest: a
    precision change is a LOUD cache miss, never a stale executable.
    ``"off"`` when no rules are set, so every pre-rules artifact and
    manifest keys identically."""
    rules = dtype_rules()
    if not rules:
        return "off"
    blob = "|".join(f"{pat}={dname}" for pat, dname in rules)
    return hashlib.sha1(blob.encode()).hexdigest()[:10]


def cast_params_by_rules(params):
    """Cast float param leaves whose ``/``-joined path matches a dtype
    rule (first match wins; unmatched and non-float leaves untouched).

    Called INSIDE the jitted loss/eval bodies on the f32 master params:
    under ``jax.value_and_grad`` the cast's vjp widens the incoming
    bf16 cotangents back to f32, so gradient ACCUMULATION and the
    optimizer update run full-width — the mixed-precision
    master-weight contract (docs/PARALLELISM.md §dtype rules)."""
    rules = dtype_rules()
    if not rules:
        return params
    import jax.numpy as jnp

    def one(path, leaf):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            return leaf
        name = path_str(path)
        for pattern, dname in rules:
            if re.search(pattern, name):
                return leaf.astype(getattr(jnp, dname))
        return leaf

    return jax.tree_util.tree_map_with_path(one, params)


def match_partition_rules(rules, tree):
    """Spec tree for ``tree`` under ``rules`` (the snippet-style
    primitive): scalars and unmatched leaves replicate (``P()`` — the
    pure-DP MLP matches nothing and fully replicates), first matching
    rule wins. Works over params alone or a whole TrainState tree
    (optimizer-state moment mirrors embed the same path tails)."""

    def one(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        name = path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(one, tree)


def spec_for_path(path, ndim: int | None = None, family: str | None = None) -> P:
    names = [str(getattr(k, "key", k)) for k in path]
    leaf = names[-1] if names else ""
    if "pp_stages" in names:
        # Pipeline stages: stacked [n_stages, ...] leaves, stage dim on
        # ``pipe`` — one stage per pipeline device. The INNER dims keep
        # their tensor-parallel rule placement (PP x TP compose:
        # pipeline_apply's shard_map is manual only over pipe/data, so
        # the model-axis sharding survives into the stage compute).
        # Structural, not regex: the pad depends on the leaf's ndim.
        inner_names = names[names.index("pp_stages") + 1:]
        inner_path = "/".join(inner_names)
        inner = P()
        for pattern, spec in rules_for_family(family):
            if re.search(pattern, inner_path):
                inner = spec
                break
        n = ndim if ndim is not None else 2
        pad = n - 1 - len(inner)
        return P("pipe", *inner, *([None] * max(pad, 0)))
    name = "/".join(names)
    for pattern, spec in rules_for_family(family):
        if re.search(pattern, name):
            return spec
    return P()


def _data_shard_spec(leaf, mesh: Mesh) -> P | None:
    """Data-axis leading-dim sharding for a leaf that divides evenly.

    Applied to optimizer-state leaves this is ZeRO-1 weight-update
    sharding (XLA reduce-scatters gradients into the sharded Adam
    moments and all-gathers the updates back); applied to param leaves
    too it is FSDP/ZeRO-3 — each data rank stores 1/N of every weight,
    and XLA inserts the all-gather-on-use in forward/backward. Both are
    pure layout annotations: no imperative communication."""
    shape = getattr(leaf, "shape", ())
    data = mesh.shape["data"]
    if data > 1 and len(shape) >= 1 and shape[0] % data == 0 and shape[0] >= data:
        return P("data", *([None] * (len(shape) - 1)))
    return None


def state_shardings(
    state, mesh: Mesh, *, shard_opt: bool = False, shard_params: bool = False,
    family: str | None = None,
):
    """NamedSharding tree for a TrainState under the family rule table.
    Scalars/rngs/unmatched params replicate; matched params (and their
    mirrored Adam moments) shard over ``model``. With ``shard_opt``,
    otherwise-replicated optimizer-state leaves additionally shard their
    leading dim over ``data`` (ZeRO-1); with ``shard_params``, the params
    themselves (and their moment mirrors) do too — FSDP/ZeRO-3, where
    params, gradients, and optimizer state all live 1/N-sharded and XLA
    all-gathers weights on use (see :func:`_data_shard_spec`).
    Tensor-parallel matches keep their ``model``-axis placement — TP and
    FSDP compose axis-wise, the scaling-book combined recipe."""

    def one(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return NamedSharding(mesh, P())
        spec = spec_for_path(
            path, ndim=getattr(leaf, "ndim", None), family=family
        )
        if spec == P():
            names = {
                str(getattr(k, "key", getattr(k, "name", k))) for k in path
            }
            eligible = (
                (shard_opt and "opt_state" in names)
                or (shard_params and ("opt_state" in names or "params" in names))
            )
            if eligible:
                data_spec = _data_shard_spec(leaf, mesh)
                if data_spec is not None:
                    spec = data_spec
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, state)


def shard_state_with_rules(
    state, mesh: Mesh, *, shard_opt: bool = False, shard_params: bool = False,
    family: str | None = None,
):
    """Place a TrainState: tensor-parallel where rules match, replicated
    elsewhere (the pure-DP MLP matches nothing and fully replicates,
    keeping :func:`dct_tpu.parallel.mesh.shard_state` semantics).
    ``shard_opt`` opts optimizer state into data-axis weight-update
    sharding (ZeRO-1); ``shard_params`` additionally shards the params
    (FSDP/ZeRO-3)."""
    return jax.device_put(
        state,
        state_shardings(
            state, mesh, shard_opt=shard_opt, shard_params=shard_params,
            family=family,
        ),
    )


# ----------------------------------------------------------------------
# Shard/gather fns: the paired place/publish callables (snippet [1]/[2]
# idiom). ``gather`` is the publish contract: every path that exports
# TrainState params out of the mesh (checkpoint deploy tier, package
# export, serving) must produce DENSE host arrays — a sharded jax.Array
# leaking into a package would serve one shard's weights as the model.
# dct-lint rule ``gather-on-publish`` enforces the call sites.


def gather_leaf(leaf) -> np.ndarray:
    """One leaf -> a dense host ndarray, whatever its placement.

    Arrays sharded across processes (TP/SP spanning hosts) are not
    fully addressable and cannot be ``device_get``; they are assembled
    with a cross-process allgather instead. NB: the allgather is a
    COLLECTIVE — when any leaf is non-addressable, every process must
    run the gather (the Trainer does: it gathers on all ranks, then
    gates the file write on the coordinator)."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def gather_tree(tree):
    """Device tree -> dense host numpy tree via :func:`gather_leaf`
    (the gather half of :func:`make_shard_and_gather_fns`, applied
    uniformly — what ``checkpoint.manager.to_host`` delegates to)."""
    return jax.tree.map(gather_leaf, tree)


def _as_dtype(spec) -> np.dtype:
    """A dtype-like (np/jnp dtype, scalar type, or alias string like
    ``'bf16'``) -> concrete ``np.dtype`` (bfloat16 resolves through
    jax's extended-dtype registry)."""
    if isinstance(spec, str):
        import jax.numpy as jnp

        name = DTYPE_ALIASES.get(spec.strip().lower(), spec)
        return np.dtype(getattr(jnp, name, name))
    return np.dtype(spec)


def _is_dtype_like(x) -> bool:
    """True for anything ``_as_dtype`` accepts as ONE dtype (a string,
    dtype, or scalar type) — i.e. NOT a per-leaf pytree of specs."""
    if isinstance(x, str):
        return True
    if isinstance(x, (dict, list, tuple)):
        # Containers are per-leaf spec trees (np.dtype would try to
        # parse a dict as a STRUCTURED dtype and raise ValueError).
        return False
    try:
        np.dtype(x)
        return True
    except (TypeError, ValueError):
        return False


def make_shard_and_gather_fns(shardings, dtype_specs=None):
    """(shard_fns, gather_fns) trees from a tree of NamedShardings.

    ``shard_fn(host_array)`` places a leaf under its declared sharding
    (``jax.device_put`` — XLA splits/replicates as the spec says);
    ``gather_fn(device_array)`` brings it back as a dense host ndarray
    (cross-process allgather where the layout spans hosts). The pair is
    the checkpoint/publish contract: save/restore and package export go
    through these, never through raw per-leaf copies.

    ``dtype_specs`` optionally casts float leaves on the way through:
    either ONE dtype-like applied tree-wide, or a pytree shaped like
    ``shardings`` carrying a per-leaf dtype (``None`` = leave alone).
    The upstream snippet's ``dtype_specs in float_dtypes`` membership
    test only ever worked for the scalar case (a pytree on the left of
    ``in`` compares elementwise and crashes); per-leaf specs are
    first-class here. Non-float leaves (step counters, int stats) are
    never cast."""
    is_sharding = lambda x: isinstance(x, NamedSharding)  # noqa: E731
    if dtype_specs is None:
        spec_tree = jax.tree.map(lambda _s: None, shardings,
                                 is_leaf=is_sharding)
    elif _is_dtype_like(dtype_specs):
        dt = _as_dtype(dtype_specs)
        spec_tree = jax.tree.map(lambda _s: dt, shardings,
                                 is_leaf=is_sharding)
    else:
        spec_tree = jax.tree.map(
            lambda d: None if d is None else _as_dtype(d), dtype_specs,
            is_leaf=lambda x: x is None or _is_dtype_like(x),
        )

    def _cast(x, dt):
        if dt is None:
            return x
        src = getattr(x, "dtype", None)
        if src is None or not jnp_issubdtype_floating(src):
            return x
        return x.astype(dt) if hasattr(x, "astype") else np.asarray(x, dt)

    def make_shard_fn(s, dt):
        return lambda x: jax.device_put(_cast(x, dt), s)

    def make_gather_fn(_s, dt):
        return lambda x: _cast(gather_leaf(x), dt)

    shard_fns = jax.tree.map(
        make_shard_fn, shardings, spec_tree, is_leaf=is_sharding,
    )
    gather_fns = jax.tree.map(
        make_gather_fn, shardings, spec_tree, is_leaf=is_sharding,
    )
    return shard_fns, gather_fns


def jnp_issubdtype_floating(dt) -> bool:
    """Float check that also covers jax extended dtypes (bfloat16 is
    not an ``np.floating`` subtype under plain numpy)."""
    import jax.numpy as jnp

    return bool(jnp.issubdtype(dt, jnp.floating))


# ----------------------------------------------------------------------
# Layout introspection: the declared-vs-actual reconciliation surface
# (trainer fit start) and the checkpoint layout manifest.


def spec_to_json(spec) -> list:
    """PartitionSpec -> JSON-able per-dim axis list (nested tuples —
    multiple axes on one dim — become lists)."""
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    return out


def leaf_spec(leaf):
    """The PartitionSpec a jax.Array leaf actually carries (None for
    host arrays / non-named shardings)."""
    sharding = getattr(leaf, "sharding", None)
    if isinstance(sharding, NamedSharding):
        return sharding.spec
    return None


def layout_mismatches(state, declared) -> list[dict]:
    """Where the live state's layout drifted from the DECLARED rule
    layout: [{path, actual, declared}] per mismatched leaf. The jitted
    step's OUTPUT shardings can legitimately drift (under ZeRO-1 XLA
    keeps the weight update — and therefore the output params — sharded
    over ``data`` instead of all-gathering); the trainer reconciles by
    re-pinning to the declared layout before checkpointing, and emits
    ``shard.layout_mismatch`` so the drift is on the record instead of
    silently checkpointed."""
    out: list[dict] = []

    def one(path, leaf, want):
        actual = leaf_spec(leaf)
        if actual is None:
            return
        want_spec = want.spec if isinstance(want, NamedSharding) else want
        # Compare normalized: trailing Nones are layout-equivalent.
        def norm(s):
            dims = list(tuple(s))
            while dims and dims[-1] is None:
                dims.pop()
            return tuple(dims)

        if norm(actual) != norm(want_spec):
            out.append({
                "path": path_str(path),
                "actual": spec_to_json(actual),
                "declared": spec_to_json(want_spec),
            })

    jax.tree_util.tree_map_with_path(
        lambda p, a, b: one(p, a, b), state, declared
    )
    return out
