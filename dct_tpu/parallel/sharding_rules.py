"""Param-sharding rules: name patterns -> PartitionSpecs over the mesh.

The scaling-book recipe, made concrete: modules carry load-bearing NAMES
(``qkv_proj``/``ffn_in`` = column-parallel, ``o_proj``/``ffn_out`` =
row-parallel), this module maps names to ``PartitionSpec``s, and ``jit``
inserts the collectives. No imperative communication anywhere — the analog
of the reference's gloo all-reduce is a compiler decision.

Applied to the WHOLE TrainState: Adam's ``mu``/``nu`` mirror the param tree,
so the same path-pattern match shards optimizer state identically — giving
tensor-parallel training a fully sharded optimizer for free.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (pattern, kernel spec, bias spec): column-parallel shards the OUTPUT dim,
# row-parallel shards the INPUT dim (its bias stays replicated — it is added
# after the row all-reduce).
_RULES = (
    ("qkv_proj", P(None, "model"), P("model")),
    ("ffn_in", P(None, "model"), P("model")),
    ("o_proj", P("model", None), P()),
    ("ffn_out", P("model", None), P()),
)

# Expert parallelism: MoE expert weights are [E, ...] stacks; sharding the
# leading expert dim over ``model`` gives each shard whole experts (the
# dispatch einsum's token exchange compiles to an all-to-all over the same
# axis). The router stays replicated (no rule matches it).
_EXPERT_RULES = {
    "experts_in_kernel": P("model", None, None),
    "experts_in_bias": P("model", None),
    "experts_out_kernel": P("model", None, None),
    "experts_out_bias": P("model", None),
}


def spec_for_path(path, ndim: int | None = None) -> P:
    names = [str(getattr(k, "key", k)) for k in path]
    leaf = names[-1] if names else ""
    if "pp_stages" in names:
        # Pipeline stages: stacked [n_stages, ...] leaves, stage dim on
        # ``pipe`` — one stage per pipeline device. The INNER dims keep
        # their tensor-parallel name-rule placement (PP x TP compose:
        # pipeline_apply's shard_map is manual only over pipe/data, so
        # the model-axis sharding survives into the stage compute).
        inner_names = names[names.index("pp_stages") + 1:]
        inner = P()
        for pattern, kernel_spec, bias_spec in _RULES:
            if any(pattern in n for n in inner_names):
                if leaf == "kernel":
                    inner = kernel_spec
                elif leaf == "bias":
                    inner = bias_spec
                break
        n = ndim if ndim is not None else 2
        pad = n - 1 - len(inner)
        return P("pipe", *inner, *([None] * max(pad, 0)))
    if leaf in _EXPERT_RULES:
        return _EXPERT_RULES[leaf]
    for pattern, kernel_spec, bias_spec in _RULES:
        if any(pattern in n for n in names):
            if leaf == "kernel":
                return kernel_spec
            if leaf == "bias":
                return bias_spec
    return P()


def _data_shard_spec(leaf, mesh: Mesh) -> P | None:
    """Data-axis leading-dim sharding for a leaf that divides evenly.

    Applied to optimizer-state leaves this is ZeRO-1 weight-update
    sharding (XLA reduce-scatters gradients into the sharded Adam
    moments and all-gathers the updates back); applied to param leaves
    too it is FSDP/ZeRO-3 — each data rank stores 1/N of every weight,
    and XLA inserts the all-gather-on-use in forward/backward. Both are
    pure layout annotations: no imperative communication."""
    shape = getattr(leaf, "shape", ())
    data = mesh.shape["data"]
    if data > 1 and len(shape) >= 1 and shape[0] % data == 0 and shape[0] >= data:
        return P("data", *([None] * (len(shape) - 1)))
    return None


def state_shardings(
    state, mesh: Mesh, *, shard_opt: bool = False, shard_params: bool = False
):
    """NamedSharding tree for a TrainState under the name-pattern rules.
    Scalars/rngs/unmatched params replicate; matched params (and their
    mirrored Adam moments) shard over ``model``. With ``shard_opt``,
    otherwise-replicated optimizer-state leaves additionally shard their
    leading dim over ``data`` (ZeRO-1); with ``shard_params``, the params
    themselves (and their moment mirrors) do too — FSDP/ZeRO-3, where
    params, gradients, and optimizer state all live 1/N-sharded and XLA
    all-gathers weights on use (see :func:`_data_shard_spec`).
    Tensor-parallel matches keep their ``model``-axis placement — TP and
    FSDP compose axis-wise, the scaling-book combined recipe."""

    def one(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return NamedSharding(mesh, P())
        spec = spec_for_path(path, ndim=getattr(leaf, "ndim", None))
        if spec == P():
            names = {
                str(getattr(k, "key", getattr(k, "name", k))) for k in path
            }
            eligible = (
                (shard_opt and "opt_state" in names)
                or (shard_params and ("opt_state" in names or "params" in names))
            )
            if eligible:
                data_spec = _data_shard_spec(leaf, mesh)
                if data_spec is not None:
                    spec = data_spec
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, state)


def shard_state_with_rules(
    state, mesh: Mesh, *, shard_opt: bool = False, shard_params: bool = False
):
    """Place a TrainState: tensor-parallel where rules match, replicated
    elsewhere (the pure-DP MLP matches nothing and fully replicates,
    keeping :func:`dct_tpu.parallel.mesh.shard_state` semantics).
    ``shard_opt`` opts optimizer state into data-axis weight-update
    sharding (ZeRO-1); ``shard_params`` additionally shards the params
    (FSDP/ZeRO-3)."""
    return jax.device_put(
        state,
        state_shardings(
            state, mesh, shard_opt=shard_opt, shard_params=shard_params
        ),
    )
