"""Param-sharding rules: name patterns -> PartitionSpecs over the mesh.

The scaling-book recipe, made concrete: modules carry load-bearing NAMES
(``qkv_proj``/``ffn_in`` = column-parallel, ``o_proj``/``ffn_out`` =
row-parallel), this module maps names to ``PartitionSpec``s, and ``jit``
inserts the collectives. No imperative communication anywhere — the analog
of the reference's gloo all-reduce is a compiler decision.

Applied to the WHOLE TrainState: Adam's ``mu``/``nu`` mirror the param tree,
so the same path-pattern match shards optimizer state identically — giving
tensor-parallel training a fully sharded optimizer for free.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (pattern, kernel spec, bias spec): column-parallel shards the OUTPUT dim,
# row-parallel shards the INPUT dim (its bias stays replicated — it is added
# after the row all-reduce).
_RULES = (
    ("qkv_proj", P(None, "model"), P("model")),
    ("ffn_in", P(None, "model"), P("model")),
    ("o_proj", P("model", None), P()),
    ("ffn_out", P("model", None), P()),
)

# Expert parallelism: MoE expert weights are [E, ...] stacks; sharding the
# leading expert dim over ``model`` gives each shard whole experts (the
# dispatch einsum's token exchange compiles to an all-to-all over the same
# axis). The router stays replicated (no rule matches it).
_EXPERT_RULES = {
    "experts_in_kernel": P("model", None, None),
    "experts_in_bias": P("model", None),
    "experts_out_kernel": P("model", None, None),
    "experts_out_bias": P("model", None),
}


def spec_for_path(path) -> P:
    names = [str(getattr(k, "key", k)) for k in path]
    leaf = names[-1] if names else ""
    if leaf in _EXPERT_RULES:
        return _EXPERT_RULES[leaf]
    for pattern, kernel_spec, bias_spec in _RULES:
        if any(pattern in n for n in names):
            if leaf == "kernel":
                return kernel_spec
            if leaf == "bias":
                return bias_spec
    return P()


def state_shardings(state, mesh: Mesh):
    """NamedSharding tree for a TrainState under the name-pattern rules.
    Scalars/rngs/unmatched params replicate; matched params (and their
    mirrored Adam moments) shard over ``model``."""

    def one(path, leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, spec_for_path(path))

    return jax.tree_util.tree_map_with_path(one, state)


def shard_state_with_rules(state, mesh: Mesh):
    """Place a TrainState: tensor-parallel where rules match, replicated
    elsewhere (the pure-DP MLP matches nothing and fully replicates,
    keeping :func:`dct_tpu.parallel.mesh.shard_state` semantics)."""
    return jax.device_put(state, state_shardings(state, mesh))
