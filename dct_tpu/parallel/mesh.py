"""Device mesh + sharding helpers: the DDP/TP/SP substrate.

The reference's process topology is fixed at deploy time: two containers,
one rank each, gradients all-reduced by gloo (docker-compose.yml:115-151,
jobs/train_lightning_ddp.py:136). The TPU-native topology is a named
``jax.sharding.Mesh`` over all addressable devices:

- ``data``  — batch-sharded axis (the DDP analog; grads all-reduce over ICI),
- ``model`` — tensor-parallel axis (extension; used by the transformer family),
- ``seq``   — sequence/context-parallel axis (ring attention).

Everything downstream is declarative: annotate the batch as sharded over
``data`` and params as replicated (or sharded over ``model``), and XLA
inserts the collectives. No NCCL/gloo calls to translate.
"""

from __future__ import annotations

import math
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dct_tpu.config import MeshConfig

AXES = ("data", "model", "seq", "pipe")


def make_mesh(
    cfg: MeshConfig | None = None, devices=None, *, allow_subset: bool = False
) -> Mesh:
    """Build the 4-axis (data, model, seq, pipe) mesh; axis size -1
    absorbs all remaining devices.

    The mesh must cover every device: silently training on a subset would
    idle chips (or, multi-host, exclude another process's devices from the
    collectives). Test rigs that want a small mesh on a big device pool opt
    in explicitly with ``allow_subset=True``.
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = {
        "data": cfg.data, "model": cfg.model, "seq": cfg.seq,
        "pipe": cfg.pipe,
    }
    fixed = math.prod(s for s in sizes.values() if s != -1)
    free = [a for a, s in sizes.items() if s == -1]
    if len(free) > 1:
        raise ValueError("At most one mesh axis may be -1")
    if free:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes {sizes}")
        sizes[free[0]] = n // fixed
    need = math.prod(sizes.values())
    if need > n:
        raise ValueError(f"Mesh {sizes} needs {need} devices, have {n}")
    if need != n and not allow_subset:
        raise ValueError(
            f"Mesh {sizes} covers {need} of {n} devices; pass "
            "allow_subset=True if a partial mesh is intended (test rigs)"
        )
    return Mesh(_device_grid([sizes[a] for a in AXES], devices), AXES)


def _grid_blocks_contiguous(grid) -> bool:
    """True when every process's data-axis rows form a contiguous aligned
    block — the layout :func:`process_data_block` requires to feed each
    host the rows its devices own."""
    by_pid: dict[int, set] = {}
    for idx in np.ndindex(grid.shape):
        by_pid.setdefault(grid[idx].process_index, set()).add(idx[0])
    data_size = grid.shape[0]
    for rows_set in by_pid.values():
        rows = sorted(rows_set)
        n = len(rows)
        if (
            rows != list(range(rows[0], rows[0] + n))
            or rows[0] % n
            or data_size % n
        ):
            return False
    return True


def _device_grid(shape: list, devices: list):
    """Device layout for the mesh grid.

    On real TPU devices covering the whole mesh, defer to
    ``mesh_utils.create_device_mesh``: it maps the logical axes onto the
    physical ICI torus so each axis's collectives ride neighbor links
    (naive enumeration order can put a ring's neighbors on opposite
    corners of the slice — the scaling-book layout rule). Disable with
    ``DCT_ICI_MESH=0``.

    The ICI layout is only kept when every process's data-axis rows stay
    a contiguous aligned block (the input-pipeline contract
    :func:`process_data_block` enforces) — a torus mapping that
    interleaves a host's rows falls back to enumeration order instead of
    aborting training at startup. CPU rigs and explicit subsets always
    use enumeration order, which tests rely on.
    """
    import sys

    need = math.prod(shape)
    want_ici = os.environ.get("DCT_ICI_MESH", "1").strip().lower() not in (
        "0", "false", "no", "off"
    )
    if (
        want_ici
        and getattr(devices[0], "platform", "") == "tpu"
        and need == len(devices)
    ):
        try:
            from jax.experimental import mesh_utils

            grid = mesh_utils.create_device_mesh(shape, devices=devices)
            if _grid_blocks_contiguous(grid):
                return grid
            sys.stderr.write(
                "[dct_tpu] ICI-aware layout interleaves a process's "
                "data-axis rows; falling back to enumeration order\n"
            )
        except Exception as e:  # noqa: BLE001 — odd shapes/topologies:
            sys.stderr.write(
                f"[dct_tpu] create_device_mesh failed ({e}); falling back "
                "to enumeration-order layout\n"
            )
    return np.array(devices[:need]).reshape(shape)


def process_data_block(mesh: Mesh) -> tuple[int, int]:
    """How the global batch splits across PROCESSES: (num_blocks, my_block).

    The data loader must feed each process exactly the rows its addressable
    devices own under :func:`batch_sharding`. For pure DP every process owns
    distinct data-axis rows -> (process_count, process_index) semantics. For
    tensor/sequence parallelism spanning processes, several processes share
    the same data rows (the batch is replicated across them), so they share
    a block and each must supply the identical full block.
    """
    pid = jax.process_index()
    grid = mesh.devices  # [data, model, seq, pipe]
    my_rows = sorted(
        {
            idx[0]
            for idx in np.ndindex(grid.shape)
            if grid[idx].process_index == pid
        }
    )
    if not my_rows:
        raise ValueError(f"process {pid} owns no devices in mesh {mesh}")
    rows = len(my_rows)
    data_size = grid.shape[0]
    if (
        my_rows != list(range(my_rows[0], my_rows[0] + rows))
        or my_rows[0] % rows
        or data_size % rows
    ):
        raise ValueError(
            f"process {pid}'s data-axis rows {my_rows} are not a contiguous "
            f"aligned block of the {data_size}-row data axis; reorder the "
            "mesh devices so each process's rows are contiguous"
        )
    return data_size // rows, my_rows[0] // rows


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dim sharded over ``data``; feature dims replicated."""
    return NamedSharding(mesh, P("data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_state(state, mesh: Mesh):
    """Replicate the train state across the mesh (pure DP).

    Model/optimizer sharding (FSDP-style) would swap the spec here; for the
    flagship MLP full replication is optimal — params are tiny, batch math
    dominates.
    """
    return jax.device_put(state, replicated_sharding(mesh))


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """For [S, B, ...] epoch stacks: steps replicated, batch dim sharded."""
    return NamedSharding(mesh, P(None, "data"))


def _make_global(sharding: NamedSharding, host_arrays):
    """Per-process host arrays -> global device arrays under ``sharding``.

    Single-process: a straight ``device_put``. Multi-process
    (``jax.distributed``): each process contributes its local shard via
    ``make_array_from_process_local_data`` — the explicit version of what
    torch DDP does implicitly with one-rank-one-batch.
    """
    if jax.process_count() > 1:
        return tuple(
            jax.make_array_from_process_local_data(sharding, a) for a in host_arrays
        )
    return tuple(jax.device_put(a, sharding) for a in host_arrays)


def make_global_batch(mesh: Mesh, *host_arrays):
    """[B_local, ...] per-process arrays -> global [B, ...] sharded on
    ``data``."""
    return _make_global(batch_sharding(mesh), host_arrays)


def make_global_epoch(mesh: Mesh, *host_arrays):
    """[S, B_local, ...] per-process stacks -> global [S, B, ...] arrays
    sharded over ``data`` on the batch dim."""
    return _make_global(stacked_batch_sharding(mesh), host_arrays)


def make_global_epoch_chunk(mesh: Mesh, *host_arrays):
    """[K, S, B_local, ...] per-process epoch-chunk stacks -> global
    [K, S, B, ...] arrays sharded over ``data`` on the batch dim
    (epoch and step dims replicated) — the multi-epoch dispatch's input
    layout (train.steps.make_multi_epoch_train_eval_step)."""
    return _make_global(NamedSharding(mesh, P(None, None, "data")), host_arrays)
