"""``shard_map`` / VMA typing across the JAX API move.

The kernels are written against the stable ``jax.shard_map`` API
(JAX >= 0.5: per-output varying-manual-axes checking spelled
``check_vma``, explicit ``lax.pcast(..., to="varying")`` to type scan
accumulators); this rig's JAX 0.4.x only ships the experimental
``jax.experimental.shard_map.shard_map``. One resolver so every call
site stays written in the modern spelling and older rigs keep working
(the same degrade-gracefully idiom as orchestration.compat).

Old-JAX translation:

- ``check_vma`` maps to the pre-rename ``check_rep``; when the caller
  did not ask for checking, it is FORCED off — the 0.4.x replication
  checker predates VMA typing and rejects modern programs whose scan
  carries are deliberately pcast-to-varying. The checker is a static
  verifier only; disabling it changes no numerics.
- ``pcast_varying`` becomes a no-op: without VMA typing there is no
  accumulator type to pin, plain values are already valid carries.
"""

from __future__ import annotations

import jax
from jax import lax

_HAS_STABLE = hasattr(jax, "shard_map")

#: Partial-manual (``axis_names``) shard_map capability: the 0.4.x
#: experimental API's ``auto=`` translation exists but its lowering
#: rejects the pipeline's programs (NotImplementedError for several
#: collectives under partial-auto). Tests that REQUIRE partial-auto
#: gate on this instead of failing on old rigs.
PARTIAL_AUTO_SHARD_MAP = _HAS_STABLE


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: bool | None = None,
    axis_names=None,
):
    """Modern-signature ``shard_map``; ``check_vma`` and partial-manual
    ``axis_names`` are translated to the installed API's knobs
    (``check_rep`` and the complementary ``auto=`` set on experimental
    builds: modern code names the axes that ARE manual, 0.4.x names the
    ones that are NOT)."""
    if _HAS_STABLE:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as sm

    kw = {}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma) if check_vma is not None else False,
        **kw,
    )


def pcast_varying(x, axes):
    """``lax.pcast(x, axes, to="varying")`` where VMA typing exists;
    identity elsewhere (pre-VMA JAX has no value typing to adjust)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    if hasattr(lax, "pvary"):  # the 0.5.x-era spelling
        return lax.pvary(x, tuple(axes))
    return x
