"""Pipeline parallelism: GPipe-style microbatch streaming over ``pipe``.

The reference has no pipeline parallelism (SURVEY §2.3 lists PP as an
extension point); this module supplies it TPU-natively, completing the
mesh's DP x TP x SP x EP x PP matrix:

- stages are a STACKED pytree (leading dim = stage) sharded
  ``P("pipe", ...)`` — each pipeline device holds one stage's params;
- the batch is split into microbatches that stream through the stages
  inside one ``shard_map``: every tick, each stage applies its params to
  its current activation and ``lax.ppermute``s the result to the next
  stage (a neighbor hop over ICI), while stage 0 ingests the next
  microbatch and the last stage banks its finished one;
- the schedule is the classic GPipe fill/drain: ``M + P - 1`` ticks for
  ``M`` microbatches over ``P`` stages, bubble fraction ``(P-1)/(M+P-1)``;
- the BACKWARD schedule is not hand-written: ``jax.grad`` through the
  scan+ppermute forward yields the reverse pipeline automatically
  (ppermute transposes to the reverse permutation), so the same jitted
  train step machinery works unchanged.

Stages must share one param structure (e.g. equal groups of identical
blocks) — that is what makes the stacked-pytree layout expressible as a
single sharded array per leaf.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dct_tpu.parallel.shard_map_compat import pcast_varying, shard_map
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(stage_params: list):
    """[per-stage pytrees with identical structure] -> stacked pytree
    (leading dim = n_stages), ready to shard ``P('pipe', ...)``."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *stage_params)


def stage_params_sharding(stacked, mesh: Mesh, axis: str = "pipe"):
    """NamedSharding tree placing the stage dim on the ``pipe`` axis."""
    def one(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return NamedSharding(mesh, spec)

    return jax.tree.map(one, stacked)


def _pipeline_body(params, xs, *, stage_fn, axis: str, n_stages: int):
    """Runs inside shard_map: params [1, ...] local stage slice; xs
    [M, mb, ...] microbatches (replicated). Returns [M, mb, ...] outputs
    (replicated via a final psum broadcast from the last stage)."""
    stage = lax.axis_index(axis)
    local = jax.tree.map(lambda a: a[0], params)
    m = xs.shape[0]
    ticks = m + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # The carry becomes device-varying over the pipe axis from the first
    # tick (stage-dependent compute); type the initial carry that way so
    # the scan carry type is fixed (same recipe as ring attention).
    act0 = pcast_varying(jnp.zeros_like(xs[0]), (axis,))
    ys0 = pcast_varying(jnp.zeros_like(xs), (axis,))

    def tick(carry, t):
        act, ys = carry
        # Stage 0 ingests microbatch t (index clamps past the end during
        # the drain ticks; the result is never banked then).
        mb = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        inp = jnp.where(stage == 0, mb, act)
        out = stage_fn(local, inp)
        # The last stage finished microbatch t-(P-1) this tick.
        done_idx = t - (n_stages - 1)
        banked = lax.dynamic_update_index_in_dim(
            ys, out, jnp.clip(done_idx, 0, m - 1), axis=0
        )
        take = jnp.logical_and(stage == n_stages - 1, done_idx >= 0)
        ys = jnp.where(take, banked, ys)
        # Rotate activations one stage forward (ICI neighbor hop).
        act = lax.ppermute(out, axis, perm)
        return (act, ys), None

    (_, ys), _ = lax.scan(tick, (act0, ys0), jnp.arange(ticks))
    # Replicate the last stage's banked outputs to every pipe device.
    ys = lax.psum(jnp.where(stage == n_stages - 1, ys, jnp.zeros_like(ys)), axis)
    return ys


def gpipe_tick_apply(
    stage_fn,
    stacked_params,
    x,
    *,
    n_microbatches: int | None = None,
):
    """GPipe microbatch streaming WITHOUT shard_map: the tick loop as a
    plain vmapped scan under GSPMD.

    Semantically identical to :func:`pipeline_apply` (same ``M + P - 1``
    tick schedule, same bubble ``(P-1)/(M+P-1)``), but the stage axis is
    an ordinary array dimension: every tick vmaps ``stage_fn`` over the
    stacked stage dim and rotates activations with ``jnp.roll`` — when
    the stacked params/activations are sharded ``P('pipe', ...)`` the
    partitioner turns the vmap into per-shard stage compute and the roll
    into the neighbor collective-permute, with no shard_map involved.
    This is the pipeline path on jax 0.4.x rigs where partial-manual
    shard_map cannot lower (shard_map_compat.PARTIAL_AUTO_SHARD_MAP is
    False), and the SPMD-GPipe comparator for the MPMD bubble bench
    (bench.py ``mpmd_pipeline``); the tick structure — and therefore the
    measured bubble — is the same either way.

    Differentiable: ``jax.grad`` through the scan+roll yields the
    reverse tick schedule, exactly as with ppermute.
    """
    first = jax.tree.leaves(stacked_params)[0]
    n_stages = first.shape[0]
    b = x.shape[0]
    m = n_microbatches or n_stages
    if b % m:
        raise ValueError(f"batch {b} not divisible by n_microbatches {m}")
    xs = x.reshape(m, b // m, *x.shape[1:])
    ticks = m + n_stages - 1

    def tick(carry, t):
        act, ys = carry
        mb = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        # Stage 0 ingests microbatch t; other stages keep their carry.
        inp = act.at[0].set(mb)
        out = jax.vmap(stage_fn)(stacked_params, inp)
        done_idx = t - (n_stages - 1)
        banked = lax.dynamic_update_index_in_dim(
            ys, out[n_stages - 1], jnp.clip(done_idx, 0, m - 1), axis=0
        )
        ys = jnp.where(done_idx >= 0, banked, ys)
        act = jnp.roll(out, 1, axis=0)
        return (act, ys), None

    act0 = jnp.zeros((n_stages, b // m, *x.shape[1:]), x.dtype)
    ys0 = jnp.zeros_like(xs)
    (_, ys), _ = lax.scan(tick, (act0, ys0), jnp.arange(ticks))
    return ys.reshape(b, *x.shape[1:])


def pipeline_apply(
    stage_fn,
    stacked_params,
    x,
    *,
    mesh: Mesh,
    axis: str = "pipe",
    n_microbatches: int | None = None,
    data_axis: str | None = None,
):
    """Apply ``n_stages`` chained stages to ``x`` [B, ...] with GPipe
    microbatch streaming over ``mesh[axis]``.

    ``stage_fn(params_one_stage, activation) -> activation`` must preserve
    the activation shape (stages are homogeneous). ``n_microbatches``
    defaults to the pipeline depth (bubble fraction ~1/2; raise it to
    amortize the bubble). Differentiable: jax.grad produces the reverse
    pipeline schedule.

    ``data_axis``: compose DP x PP — the within-microbatch batch dim
    shards over that mesh axis (each data-parallel group runs its own
    pipeline over its rows); None replicates the batch over the mesh.
    """
    n_stages = mesh.shape[axis]
    first = jax.tree.leaves(stacked_params)[0]
    if first.shape[0] != n_stages:
        raise ValueError(
            f"stacked params have {first.shape[0]} stages but mesh axis "
            f"'{axis}' has {n_stages} devices"
        )
    b = x.shape[0]
    m = n_microbatches or n_stages
    if b % m:
        raise ValueError(f"batch {b} not divisible by n_microbatches {m}")
    if data_axis is not None and (b // m) % mesh.shape[data_axis]:
        raise ValueError(
            f"microbatch {b // m} not divisible by mesh axis "
            f"'{data_axis}' ({mesh.shape[data_axis]})"
        )
    xs = x.reshape(m, b // m, *x.shape[1:])

    body = functools.partial(
        _pipeline_body, stage_fn=stage_fn, axis=axis, n_stages=n_stages
    )
    param_specs = jax.tree.map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params
    )
    xs_spec = P(None, data_axis, *([None] * (x.ndim - 1)))
    # PARTIAL-manual shard_map: only the pipe (and data) axes are manual;
    # every other mesh axis (model/seq) stays AUTO, so tensor-parallel
    # shardings on the stage params' inner dims survive into the body and
    # the compiler inserts the TP collectives inside each stage — PP x TP
    # compose without hand-written stage communication.
    manual = {axis} | ({data_axis} if data_axis is not None else set())
    ys = shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, xs_spec),
        out_specs=xs_spec,
        axis_names=frozenset(manual),
    )(stacked_params, xs)
    return ys.reshape(b, *x.shape[1:])
