"""MPMD inter-stage transfer plane: explicit cross-process send/recv.

Inside one process, stage slices exchange activations/gradients by
``jax.device_put`` between disjoint device sets
(:class:`dct_tpu.parallel.mpmd.QueueChannel` + the runner's placing
wrapper). Across PROCESSES — the multi-controller deployment, one
process per stage — there is no shared jax world to route through
(deliberately: an MPMD stage must never join a global SPMD collective),
so the transfer plane is an explicit framed-array protocol over TCP:

    frame := MAGIC(4) | header_len(u32 be) | header json | raw bytes
    header := {"dtype": str, "shape": [..], "tag": str}

On a real pod the same wire carries host-staged DCN transfers between
hosts of different slices (the MPMD paper's transfer layer); on the CPU
rig it is the loopback. Timeouts are LOUD
(:class:`dct_tpu.parallel.mpmd.MpmdTransferTimeout` naming the link),
never a silent hang: a dead neighbor stage must surface within
``DCT_MPMD_TRANSFER_TIMEOUT_S`` so the supervised launcher's exit-code
classifier can heal the world.
"""

from __future__ import annotations

import json
import socket
import struct
import time

import numpy as np

from dct_tpu.parallel.mpmd import MpmdTransferTimeout

_MAGIC = b"DCTX"

# ----------------------------------------------------------------------
# Transfer accounting (ISSUE 14): byte/latency histograms per link
# direction, so inter-stage comms show up on /metrics next to the
# bubble gauges instead of hiding inside transfer_wait_s. Armed by the
# worker (arm_transfer_metrics with its metrics-plane registry);
# unarmed, every note is one None check — nothing on the wire path.

#: Frame-size buckets, bytes: 1 KB .. 256 MB in decades + the
#: activation-sized middle. Part of the metric identity (aggregate.py
#: merges bucket-wise), so changing them is a schema change.
TRANSFER_BYTE_BUCKETS = (
    1e3, 1e4, 1e5, 1e6, 4e6, 1.6e7, 6.4e7, 2.56e8,
)
#: Per-frame wall buckets, seconds: loopback microseconds up to the
#: loud-timeout regime.
TRANSFER_LATENCY_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0,
)

_transfer_metrics: dict | None = None


def arm_transfer_metrics(registry) -> None:
    """Install the transfer histograms/counters on ``registry`` (a
    :class:`~dct_tpu.observability.metrics.MetricsRegistry`) and start
    recording every frame this process sends/receives. Call once per
    process (the MPMD worker does, when ``DCT_METRICS_DIR`` arms the
    plane); re-arming swaps the sink."""
    global _transfer_metrics
    _transfer_metrics = {
        "bytes_h": registry.histogram(
            "dct_mpmd_transfer_bytes",
            "Framed bytes per inter-stage transfer, by direction.",
            buckets=TRANSFER_BYTE_BUCKETS,
        ),
        "seconds_h": registry.histogram(
            "dct_mpmd_transfer_seconds",
            "Wall seconds per inter-stage transfer frame, by "
            "direction (recv includes the wait for the peer's send).",
            buckets=TRANSFER_LATENCY_BUCKETS,
        ),
        "frames_c": registry.counter(
            "dct_mpmd_transfer_frames_total",
            "Inter-stage transfer frames, by direction.",
        ),
        "bytes_c": registry.counter(
            "dct_mpmd_transfer_bytes_total",
            "Cumulative inter-stage transfer bytes, by direction.",
        ),
    }


def disarm_transfer_metrics() -> None:
    global _transfer_metrics
    _transfer_metrics = None


def _note_transfer(direction: str, nbytes: int, seconds: float) -> None:
    m = _transfer_metrics
    if m is None:
        return
    try:
        labels = {"direction": direction}
        m["bytes_h"].observe(nbytes, labels)
        m["seconds_h"].observe(seconds, labels)
        m["frames_c"].inc(1.0, labels)
        m["bytes_c"].inc(float(nbytes), labels)
    except Exception:  # noqa: BLE001 — telemetry never fails a transfer
        pass


def _send_all(sock: socket.socket, data: bytes) -> None:
    sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int, timeout: float) -> bytes:
    deadline = time.monotonic() + timeout
    chunks = []
    remaining = n
    while remaining > 0:
        left = deadline - time.monotonic()
        if left <= 0:
            raise MpmdTransferTimeout(
                f"socket recv starved: {remaining}/{n} bytes outstanding"
            )
        sock.settimeout(min(left, 5.0))
        try:
            chunk = sock.recv(min(remaining, 1 << 20))
        except socket.timeout:
            continue
        if not chunk:
            raise MpmdTransferTimeout(
                "peer closed the transfer link mid-frame"
            )
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def send_array(sock: socket.socket, arr: np.ndarray, tag: str = "") -> None:
    arr = np.ascontiguousarray(arr)
    header = json.dumps(
        {"dtype": str(arr.dtype), "shape": list(arr.shape), "tag": tag}
    ).encode()
    _send_all(
        sock,
        _MAGIC + struct.pack(">I", len(header)) + header + arr.tobytes(),
    )


def recv_array(sock: socket.socket, timeout: float) -> np.ndarray:
    magic = _recv_exact(sock, 4, timeout)
    if magic != _MAGIC:
        raise MpmdTransferTimeout(
            f"bad frame magic {magic!r} on the transfer link "
            "(foreign traffic or a torn stream)"
        )
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4, timeout))
    header = json.loads(_recv_exact(sock, hlen, timeout).decode())
    dtype = np.dtype(header["dtype"])
    shape = tuple(int(s) for s in header["shape"])
    n = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
    raw = _recv_exact(sock, n, timeout)
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


class SocketChannel:
    """One directed inter-stage link carrying framed host arrays.

    Satisfies the :class:`dct_tpu.parallel.mpmd.StageExecutor` channel
    protocol (``send`` / ``recv``); payloads cross as dense numpy — the
    executor's ``place_in`` re-places them onto the stage's sub-mesh.
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        try:
            self._sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        except OSError:
            pass  # non-TCP transports (AF_UNIX test rigs) have no Nagle

    def send(self, payload) -> None:
        # recv_array leaves a short poll timeout installed on the
        # shared socket; restore blocking mode so a large frame's
        # sendall never spuriously times out mid-write (a torn frame
        # would corrupt the peer's stream). A genuinely dead peer
        # surfaces through ITS recv timeout / the launcher's stall
        # monitor; any send-side failure is still loud here.
        arr = np.asarray(payload)
        t0 = time.monotonic()
        try:
            self._sock.settimeout(None)
            send_array(self._sock, arr)
        except OSError as e:
            raise MpmdTransferTimeout(
                f"send on the transfer link failed: {e}"
            ) from e
        _note_transfer("send", arr.nbytes, time.monotonic() - t0)

    def recv(self, timeout: float):
        t0 = time.monotonic()
        arr = recv_array(self._sock, timeout)
        _note_transfer("recv", arr.nbytes, time.monotonic() - t0)
        return arr

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def connect_stage_links(
    stage: int, n_stages: int, *, port_base: int,
    host: str = "127.0.0.1", timeout: float = 120.0,
) -> dict:
    """Establish stage ``stage``'s neighbor links.

    Topology: each neighbor pair shares ONE TCP connection, opened by
    the lower-numbered stage toward the higher one's listener on
    ``port_base + k+1``, then used bidirectionally — activations flow
    down the socket, gradients flow back up it (the two directions are
    independent TCP byte streams, and each stage drives its side
    single-threaded, so frames never interleave). Stage k's links:

    - ``up``: to stage k+1 (send activations, recv gradients) — k
      connects as the client;
    - ``down``: from stage k-1 (recv activations, send gradients) — k
      accepts as the server on ``port_base + k``.

    Returns ``{"act_out"/"grad_in": SocketChannel, "act_in"/"grad_out":
    SocketChannel}`` entries as applicable. Loud
    :class:`MpmdTransferTimeout` when a neighbor never shows up.
    """
    links: dict = {}
    server = None
    if stage > 0:
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((host, port_base + stage))
        server.listen(1)
        server.settimeout(timeout)
    # Listener up BEFORE dialing upward, so the ring establishes in any
    # start order.
    if stage < n_stages - 1:
        deadline = time.monotonic() + timeout
        last_err = None
        while time.monotonic() < deadline:
            try:
                up = socket.create_connection(
                    (host, port_base + stage + 1), timeout=2.0
                )
                break
            except OSError as e:
                last_err = e
                time.sleep(0.2)
        else:
            raise MpmdTransferTimeout(
                f"stage {stage} could not reach stage {stage + 1} on "
                f"port {port_base + stage + 1} within {timeout}s "
                f"({last_err})"
            )
        ch = SocketChannel(up)
        links["act_out"] = ch
        links["grad_in"] = ch
    if server is not None:
        try:
            conn, _addr = server.accept()
        except socket.timeout:
            server.close()
            raise MpmdTransferTimeout(
                f"stage {stage} never heard from stage {stage - 1} on "
                f"port {port_base + stage} within {timeout}s"
            ) from None
        server.close()
        ch = SocketChannel(conn)
        links["act_in"] = ch
        links["grad_out"] = ch
    return links


def close_links(links: dict) -> None:
    seen = set()
    for ch in links.values():
        if id(ch) in seen:
            continue
        seen.add(id(ch))
        ch.close()
