from dct_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    batch_sharding,
    replicated_sharding,
    make_global_batch,
    shard_state,
)
from dct_tpu.parallel.distributed import (  # noqa: F401
    initialize_from_env,
    process_index,
    process_count,
    is_coordinator,
)
