"""Multi-host rendezvous: the TPU-native replacement for gloo's TCP store.

Reference behavior being replaced: Lightning reads MASTER_ADDR / MASTER_PORT
/ NODE_RANK / WORLD_SIZE from container env (docker-compose.yml:121-124,
140-143) and calls ``torch.distributed.init_process_group("gloo")`` with a
TCP store at pytorch-master:29500 during ``trainer.fit``
(jobs/train_lightning_ddp.py:136,143).

TPU-native: ``jax.distributed.initialize(coordinator_address, num_processes,
process_id)``. After it returns, ``jax.devices()`` spans every host's chips
and jitted collectives ride ICI/DCN. We accept the reference's env names so
the same compose files / DAG launch blocks work unchanged.
"""

from __future__ import annotations

import jax

from dct_tpu.config import DistributedConfig


def initialize_from_env(cfg: DistributedConfig | None = None) -> DistributedConfig:
    """Initialize jax.distributed when WORLD_SIZE > 1; no-op otherwise.

    Idempotent: safe to call twice (the zombie-cleanup concern the reference
    handles with pkill, dags/2_pytorch_training.py:29-38, does not arise —
    there is no long-lived port-bound store to leak; the coordinator dies
    with process 0).
    """
    cfg = cfg or DistributedConfig.from_env()
    if cfg.num_processes <= 1:
        return cfg
    if cfg.coordinator_address is None:
        raise ValueError(
            "WORLD_SIZE > 1 but no coordinator address: set MASTER_ADDR "
            "(+ MASTER_PORT) or DCT_COORDINATOR_ADDRESS"
        )
    # Multi-process CPU rigs (the two-container test bed, CI) need the
    # gloo cross-host collective backend; the default CPU backend
    # refuses multiprocess computations outright. Must be set BEFORE
    # initialize — config.update is authoritative where the env var is
    # not reliably honored. Platform is read from config/env, NOT
    # jax.default_backend(): that call would initialize the backends
    # ahead of jax.distributed.initialize.
    import os as _os

    platforms = (
        getattr(jax.config, "jax_platforms", None)
        or _os.environ.get("JAX_PLATFORMS", "")
        or ""
    )
    if platforms.split(",")[0].strip().lower() == "cpu":
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo"
            )
        except (AttributeError, ValueError):
            # jax without the flag (or without gloo built in): keep the
            # historical behavior rather than failing the launch.
            pass
    try:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
    except RuntimeError as e:  # already initialized
        if "already" not in str(e).lower():
            raise
    return cfg


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """The rank-0 gate for side effects (checkpoint writes, MLflow upload),
    the analog of ``trainer.global_rank == 0``
    (jobs/train_lightning_ddp.py:146)."""
    return jax.process_index() == 0
