"""MPMD pipeline parallelism: distinct per-stage programs on disjoint
device slices (ROADMAP item 3 / ISSUE 13).

Everything else in the platform is single-program SPMD over one mesh —
the GPipe path in :mod:`dct_tpu.parallel.pipeline` forces all stages
into one stacked-pytree program (identical shapes, one precision, a
lockstep tick schedule whose bubble is ``(P-1)/(M+P-1)``). Per "Scaling
Deep Learning Training with MPMD Pipeline Parallelism" (PAPERS.md),
this module runs each stage as its OWN compiled program owning a
disjoint slice of the pod's devices, with explicit inter-stage
activation/gradient transfers and a 1F1B (PipeDream-flush) steady-state
schedule:

- :func:`parse_stage_spec` — the ``DCT_MPMD_STAGES`` grammar (stage
  count or per-stage device counts), loud ``ValueError`` on any
  malformed clause, like ``DCT_SHARD_RULES``;
- :func:`carve_stage_meshes` — per-stage sub-meshes carved from the
  device pool (the PR 11 mesh layer, one ``(data, model)`` mesh per
  stage — stages may have HETEROGENEOUS slice sizes);
- :func:`build_schedule` — per-stage op lists (``1f1b`` | ``gpipe``)
  with every op tagged ``fill`` / ``steady`` / ``drain``, so the span
  and goodput layers can attribute exactly where the bubble went;
- :func:`split_state` / :func:`merge_stage_states` — the SPMD
  stacked-pytree TrainState <-> per-stage TrainStates pivot (pure data
  movement, bitwise both ways; optimizer-state param mirrors are
  discovered structurally so any optax chain splits correctly);
- :class:`StageExecutor` — runs ONE stage's op list against a pair of
  neighbor channels; the in-process thread-per-stage runner
  (:class:`MpmdRunner`) and the multi-process socket worker
  (:mod:`dct_tpu.train.mpmd_worker`) share it, so the two deployment
  modes execute the identical schedule;
- bubble accounting — :func:`analytic_bubble` (the ``(P-1)/(M+P-1)``
  model both schedules obey in the uniform-tick limit) and
  :func:`measured_bubble` (the slope method: the fraction of a step's
  wall not explained by the marginal microbatch cost — measurable for
  ANY schedule, SPMD or MPMD, without per-tick device introspection).

Stage backward programs RECOMPUTE their forward from the stored input
activation (``jax.vjp`` inside one jitted program — full-remat style):
the only cross-op residual is the stage input, which is exactly the
1F1B in-flight set the schedule bounds at ``P - stage`` activations.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

SCHEDULES = ("1f1b", "gpipe")


class MpmdSpecError(ValueError):
    """A malformed MPMD spec (stage map / schedule / microbatches) —
    raised at parse time, naming the offending clause: a typo'd
    pipeline must never silently train single-stage."""


class MpmdTransferTimeout(RuntimeError):
    """An inter-stage transfer did not arrive within the configured
    ``DCT_MPMD_TRANSFER_TIMEOUT_S`` window — a dead or wedged neighbor
    stage."""


@dataclasses.dataclass
class MpmdSpec:
    """The resolved MPMD run shape (config.MpmdConfig, parsed)."""

    n_stages: int
    device_counts: tuple  # per-stage device counts, len == n_stages
    n_microbatches: int
    schedule: str = "1f1b"
    transfer_timeout_s: float = 120.0
    port_base: int = 29600

    @property
    def total_devices(self) -> int:
        return int(sum(self.device_counts))


def parse_stage_spec(text: str, *, n_devices: int | None = None) -> tuple:
    """``DCT_MPMD_STAGES`` -> per-stage device counts.

    Grammar (loud failure on anything else):

    - ``"P"`` (one positive int): P stages, devices split evenly —
      needs ``n_devices`` divisible by P when given;
    - ``"d0,d1,...,dP-1"``: explicit per-stage device counts (stages
      may be heterogeneous — a fat embedding stage can own more chips).

    Raises :class:`MpmdSpecError` naming the clause on: empty spec,
    non-integer tokens, zero/negative counts, fewer than 2 stages, or
    a device sum exceeding ``n_devices``.
    """
    raw = (text or "").strip()
    if not raw:
        raise MpmdSpecError(
            "DCT_MPMD_STAGES is empty: expected a stage count ('2') or "
            "per-stage device counts ('1,1')"
        )
    toks = [t.strip() for t in raw.split(",")]
    for t in toks:
        if not (t.lstrip("-").isdigit()):
            raise MpmdSpecError(
                f"DCT_MPMD_STAGES token {t!r} is not an integer "
                f"(spec: {raw!r})"
            )
    vals = [int(t) for t in toks]
    if len(vals) == 1:
        p = vals[0]
        if p < 2:
            raise MpmdSpecError(
                f"DCT_MPMD_STAGES={p}: an MPMD pipeline needs >= 2 "
                "stages (use the plain trainer for 1)"
            )
        if n_devices is not None:
            if n_devices % p:
                raise MpmdSpecError(
                    f"DCT_MPMD_STAGES={p} does not divide the "
                    f"{n_devices}-device pool evenly; give explicit "
                    "per-stage counts instead"
                )
            return tuple([n_devices // p] * p)
        return tuple([1] * p)
    if any(v < 1 for v in vals):
        raise MpmdSpecError(
            f"DCT_MPMD_STAGES={raw!r}: every per-stage device count "
            "must be >= 1"
        )
    if len(vals) < 2:
        raise MpmdSpecError(
            f"DCT_MPMD_STAGES={raw!r}: an MPMD pipeline needs >= 2 stages"
        )
    if n_devices is not None and sum(vals) > n_devices:
        raise MpmdSpecError(
            f"DCT_MPMD_STAGES={raw!r} asks for {sum(vals)} devices but "
            f"only {n_devices} are available"
        )
    return tuple(vals)


def spec_from_env_values(
    stages: str, microbatches: int, schedule: str,
    transfer_timeout_s: float, port_base: int,
    *, n_devices: int | None = None,
) -> MpmdSpec:
    """Validate the raw MpmdConfig knob values into an :class:`MpmdSpec`
    (all failures are loud :class:`MpmdSpecError`, at parse time)."""
    counts = parse_stage_spec(stages, n_devices=n_devices)
    sched = (schedule or "1f1b").strip().lower()
    if sched not in SCHEDULES:
        raise MpmdSpecError(
            f"DCT_MPMD_SCHEDULE={schedule!r} not in {SCHEDULES}"
        )
    m = int(microbatches) if microbatches else 2 * len(counts)
    if m < len(counts):
        raise MpmdSpecError(
            f"DCT_MPMD_MICROBATCHES={m} < {len(counts)} stages: the "
            "pipeline would never reach steady state"
        )
    if transfer_timeout_s <= 0:
        raise MpmdSpecError(
            f"DCT_MPMD_TRANSFER_TIMEOUT_S={transfer_timeout_s} must be "
            "> 0 (a zero timeout is an instant transfer failure)"
        )
    return MpmdSpec(
        n_stages=len(counts), device_counts=counts, n_microbatches=m,
        schedule=sched, transfer_timeout_s=float(transfer_timeout_s),
        port_base=int(port_base),
    )


def carve_stage_meshes(counts, devices=None, *, model: int = 1):
    """Partition the device pool into per-stage sub-meshes.

    Each stage gets a ``jax.sharding.Mesh`` over its OWN contiguous
    slice of ``devices`` with axes ``(data, model)`` — the PR 11 mesh
    layer, one mesh per stage, disjoint by construction. ``model`` > 1
    gives every stage a tensor-parallel axis (its per-stage partition
    rules place the projection kernels over it)."""
    devices = list(devices if devices is not None else jax.devices())
    if sum(counts) > len(devices):
        raise MpmdSpecError(
            f"stage device counts {tuple(counts)} need {sum(counts)} "
            f"devices, have {len(devices)}"
        )
    from jax.sharding import Mesh

    meshes, off = [], 0
    for k, c in enumerate(counts):
        if c % model:
            raise MpmdSpecError(
                f"stage {k}'s {c}-device slice does not tile the "
                f"model={model} tensor-parallel axis"
            )
        grid = np.array(devices[off:off + c]).reshape(c // model, model)
        meshes.append(Mesh(grid, ("data", "model")))
        off += c
    return meshes


def slice_descriptor(counts) -> str:
    """One label value for a stage map's slice topology (part of the
    per-stage AOT identity: the same stage id on a different carve is a
    different program)."""
    return "x".join(str(int(c)) for c in counts)


# ----------------------------------------------------------------------
# Schedules. Ops are (kind, microbatch, phase); per-stage lists execute
# strictly in order, blocking on the neighbor channels for inputs.


@dataclasses.dataclass(frozen=True)
class Op:
    kind: str  # "fwd" | "bwd"
    mb: int
    phase: str  # "fill" | "steady" | "drain"


def build_schedule(n_stages: int, n_microbatches: int, kind: str = "1f1b"):
    """Per-stage op lists.

    ``1f1b`` (PipeDream-flush): stage ``i`` warms up with
    ``min(P-1-i, M)`` forwards (phase ``fill``), alternates
    fwd/bwd in steady state (phase ``steady``), drains the remaining
    backwards (phase ``drain``). In steady state every stage is
    saturated — the bubble is confined to fill + drain, which is what
    the per-phase spans make visible.

    ``gpipe``: all M forwards then all M backwards per stage (the SPMD
    comparator's order, runnable on the MPMD substrate for A/B); the
    first ``P-1-i`` fwd slots are still the fill, the trailing
    backwards past the last aligned one the drain.
    """
    p, m = int(n_stages), int(n_microbatches)
    if kind not in SCHEDULES:
        raise MpmdSpecError(f"unknown schedule {kind!r} (valid: {SCHEDULES})")
    out = []
    for i in range(p):
        ops: list[Op] = []
        if kind == "1f1b":
            warm = min(p - 1 - i, m)
            for j in range(warm):
                ops.append(Op("fwd", j, "fill"))
            for j in range(m - warm):
                ops.append(Op("fwd", warm + j, "steady"))
                ops.append(Op("bwd", j, "steady"))
            for j in range(m - warm, m):
                ops.append(Op("bwd", j, "drain"))
        else:  # gpipe
            warm = min(p - 1 - i, m)
            for j in range(m):
                ops.append(Op("fwd", j, "fill" if j < warm else "steady"))
            drain_from = m - warm
            for j in range(m):
                ops.append(
                    Op("bwd", j, "steady" if j < drain_from else "drain")
                )
        out.append(ops)
    return out


def analytic_bubble(n_stages: int, n_microbatches: int) -> float:
    """The uniform-tick bubble fraction ``(P-1)/(M+P-1)`` BOTH
    schedules obey over the whole step (GPipe's lockstep ramps and
    1F1B's fill+drain cost the same wall; 1F1B's win is that its
    STEADY-STATE window is bubble-free, and that stages are distinct
    programs — see docs/PARALLELISM.md §MPMD for the measurement
    contract)."""
    p, m = int(n_stages), int(n_microbatches)
    return (p - 1) / float(m + p - 1)


def measured_bubble(t_small: float, t_large: float,
                    m_small: int, m_large: int) -> float:
    """Slope-method measured bubble at ``m_small`` microbatches.

    Fit ``t(M) = a*M + c`` through two measured step walls; the bubble
    at M is the wall fraction not explained by the marginal microbatch
    cost: ``c / t(M) = 1 - a*M/t(M)``. Schedule-agnostic (works for the
    SPMD lockstep program and the MPMD runner alike) and robust to how
    the work is spread over devices — for an ideal pipeline it recovers
    exactly ``(P-1)/(M+P-1)``."""
    if m_large <= m_small or t_small <= 0:
        raise ValueError("need m_large > m_small and t_small > 0")
    slope = (t_large - t_small) / float(m_large - m_small)
    return max(0.0, min(1.0, 1.0 - slope * m_small / t_small))


# ----------------------------------------------------------------------
# TrainState pivot: SPMD stacked-pytree <-> per-stage states.
# The SPMD layout is the PP family's param tree:
#   {"params": {"in_proj": ..., "pp_stages": <stacked, dim0 = stage>,
#               "ln_out": ..., "head": ...}}
# Stage k owns pp_stages[k] under the key "stage", stage 0 additionally
# the embedding head ("in_proj"), the last stage the output head
# ("ln_out", "head"). Optimizer-state param mirrors (Adam mu/nu, sgd
# traces, ...) are discovered STRUCTURALLY — any opt_state node whose
# treedef equals the params treedef splits/merges the same way — so the
# pivot works for every optax chain the platform configures.

STACKED_KEY = "pp_stages"
STAGE_KEY = "stage"
_FIRST_EXTRAS = ("in_proj",)
_LAST_EXTRAS = ("ln_out", "head")


def stage_layers(n_layers: int, n_stages: int) -> int:
    """Layers per stage, or a loud refusal when the model cannot tile
    the requested stage map (the untileable-stage contract)."""
    if n_stages < 2:
        raise MpmdSpecError(f"n_stages={n_stages}: MPMD needs >= 2 stages")
    if n_layers % n_stages:
        raise MpmdSpecError(
            f"n_layers={n_layers} does not tile n_stages={n_stages} "
            "homogeneous stages; adjust DCT_N_LAYERS or DCT_MPMD_STAGES"
        )
    return n_layers // n_stages


def split_params(full_params: dict, k: int, n_stages: int) -> dict:
    """The stage-``k`` slice of the SPMD param tree (pure indexing —
    bitwise)."""
    inner = full_params["params"]
    if STACKED_KEY not in inner:
        raise MpmdSpecError(
            f"param tree has no '{STACKED_KEY}' stacked stage pytree — "
            "MPMD requires the pipeline-parallel family "
            "(weather_transformer_pp)"
        )
    stacked = inner[STACKED_KEY]
    lead = int(jax.tree.leaves(stacked)[0].shape[0])
    if lead != n_stages:
        raise MpmdSpecError(
            f"checkpoint holds {lead} stacked stages but the run "
            f"configures {n_stages} — an untileable stage map; restore "
            "with the saving stage count or retrain"
        )
    out = {STAGE_KEY: jax.tree.map(lambda a: a[k], stacked)}
    if k == 0:
        for key in _FIRST_EXTRAS:
            out[key] = inner[key]
    if k == n_stages - 1:
        for key in _LAST_EXTRAS:
            out[key] = inner[key]
    return {"params": out}


def merge_params(stage_params: list) -> dict:
    """Per-stage param trees -> the SPMD stacked tree (inverse of
    :func:`split_params`). Leaves are brought to HOST first — the
    stages live on disjoint device slices, and the merge is a
    checkpoint/pivot operation; stacking host copies of the original
    slices is bitwise the original stack."""
    def host(leaf):
        return np.asarray(jax.device_get(leaf))

    n = len(stage_params)
    slices = [
        jax.tree.map(host, p["params"][STAGE_KEY]) for p in stage_params
    ]
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *slices)
    inner = {STACKED_KEY: stacked}
    for key in _FIRST_EXTRAS:
        inner[key] = jax.tree.map(host, stage_params[0]["params"][key])
    for key in _LAST_EXTRAS:
        inner[key] = jax.tree.map(host, stage_params[n - 1]["params"][key])
    return {"params": inner}


def _map_opt_mirrors(opt_state, params_def, fn):
    """Rebuild ``opt_state`` with ``fn`` applied to every node whose
    tree structure equals ``params_def`` (the param mirrors)."""
    def rec(node):
        try:
            if jax.tree.structure(node) == params_def:
                return fn(node)
        except Exception:  # noqa: BLE001 — unhashable/odd nodes: descend
            pass
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*[rec(c) for c in node])
        if isinstance(node, tuple):
            return tuple(rec(c) for c in node)
        if isinstance(node, list):
            return [rec(c) for c in node]
        if isinstance(node, dict):
            return {kk: rec(v) for kk, v in node.items()}
        return node

    return rec(opt_state)


def _zip_opt_mirrors(opt_states, params_defs, fn):
    """Walk N structurally-parallel opt_states; at every param-mirror
    node call ``fn([node_0, ..., node_N-1])``. Used by the merge
    direction (each stage's mirror has a DIFFERENT treedef — its own
    params)."""
    def rec(nodes):
        head = nodes[0]
        try:
            if jax.tree.structure(head) == params_defs[0]:
                for k, nd in enumerate(nodes):
                    if jax.tree.structure(nd) != params_defs[k]:
                        raise MpmdSpecError(
                            f"stage {k}'s optimizer state does not "
                            "mirror its params — mixed optimizer "
                            "configs across stages"
                        )
                return fn(list(nodes))
        except MpmdSpecError:
            raise
        except Exception:  # noqa: BLE001
            pass
        if isinstance(head, tuple) and hasattr(head, "_fields"):
            return type(head)(
                *[rec([n[i] for n in nodes]) for i in range(len(head))]
            )
        if isinstance(head, tuple):
            return tuple(
                rec([n[i] for n in nodes]) for i in range(len(head))
            )
        if isinstance(head, list):
            return [rec([n[i] for n in nodes]) for i in range(len(head))]
        if isinstance(head, dict):
            return {kk: rec([n[kk] for n in nodes]) for kk in head}
        return head

    return rec(list(opt_states))


def split_state(full_state, k: int, n_stages: int):
    """SPMD TrainState -> stage ``k``'s TrainState (same tx; step/rng
    shared; optimizer mirrors split structurally). Bitwise: every leaf
    is an index or a pass-through."""
    params_def = jax.tree.structure(full_state.params)
    stage_params = split_params(full_state.params, k, n_stages)
    opt = _map_opt_mirrors(
        full_state.opt_state, params_def,
        lambda mirror: split_params(mirror, k, n_stages),
    )
    return full_state.replace(params=stage_params, opt_state=opt)


def merge_stage_states(stage_states: list, template=None):
    """Per-stage TrainStates -> the SPMD TrainState (inverse pivot;
    bitwise). ``template`` (a full-model TrainState) supplies tx /
    apply_fn; defaults to stage 0's."""
    params = merge_params([s.params for s in stage_states])
    defs = [jax.tree.structure(s.params) for s in stage_states]
    opt = _zip_opt_mirrors(
        [s.opt_state for s in stage_states], defs, merge_params
    )
    base = template if template is not None else stage_states[0]
    return base.replace(
        step=stage_states[0].step, params=params, opt_state=opt,
        rng=stage_states[0].rng,
    )


# ----------------------------------------------------------------------
# Per-stage programs: fwd / bwd / update, jitted per stage, optionally
# fronted by a per-stage AOT store. The backward recomputes the forward
# from the stored stage input (vjp inside one program — full remat).


def make_stage_programs(
    k: int, n_stages: int, stage_fns: dict, *, store=None,
):
    """Compile stage ``k``'s program set from the model-level callables
    (``first_fwd(p, x)``, ``mid_fwd(p, a)``, ``last_fwd(p, a, y, w) ->
    (loss_sum, count)``, built by the trainer layer).

    Returns ``{"fwd": ..., "bwd": ..., "update": ..., "eval": ...}``
    where every entry is a jitted program (wrapped by the per-stage AOT
    ``store`` when given, program keys ``mpmd_<name>_s<k>`` — stage id
    and slice topology are already part of the store identity)."""
    first, last = k == 0, k == n_stages - 1
    if first:
        fwd_fn = stage_fns["first_fwd"]
    elif last:
        fwd_fn = stage_fns["last_fwd"]
    else:
        fwd_fn = stage_fns["mid_fwd"]

    if last:
        def bwd(params, a_in, y, w, acc):
            def loss_of(p, a):
                return stage_fns["last_fwd"](p, a, y, w)[0]

            _, vjp = jax.vjp(loss_of, params, a_in)
            gp, ga = vjp(jnp.ones(()))
            return jax.tree.map(jnp.add, acc, gp), ga
    elif first:
        def bwd(params, x, g, acc):
            _, vjp = jax.vjp(fwd_fn, params, x)
            gp, _gx = vjp(g)
            return jax.tree.map(jnp.add, acc, gp)
    else:
        def bwd(params, a_in, g, acc):
            _, vjp = jax.vjp(fwd_fn, params, a_in)
            gp, ga = vjp(g)
            return jax.tree.map(jnp.add, acc, gp), ga

    def update(state, acc, total):
        grads = jax.tree.map(lambda g: g / total, acc)
        return state.apply_gradients(grads)

    progs = {
        "fwd": jax.jit(fwd_fn),
        "bwd": jax.jit(bwd),
        "update": jax.jit(update),
    }
    if last:
        progs["eval"] = jax.jit(stage_fns["last_eval"])
    if store is not None:
        progs = {
            name: store.wrap(fn, program=f"mpmd_{name}_s{k}")
            for name, fn in progs.items()
        }
    return progs


def zero_grads(params):
    return jax.tree.map(jnp.zeros_like, params)


# ----------------------------------------------------------------------
# Stage execution: one stage's op list against neighbor channels. The
# channel protocol is two methods — ``send(payload)`` and
# ``recv(timeout) -> payload`` — implemented in-process by
# :class:`QueueChannel` and cross-process by
# :class:`dct_tpu.parallel.mpmd_transfer.SocketChannel`.


class QueueChannel:
    """In-process channel: a bounded queue of device arrays (the local
    ``jax.device_put`` transfer happens on the SENDER, so the consumer's
    wait is genuine transfer wait)."""

    def __init__(self, maxsize: int = 0):
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)

    def send(self, payload) -> None:
        self._q.put(payload)

    def recv(self, timeout: float):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            raise MpmdTransferTimeout(
                f"no payload within {timeout}s"
            ) from None


@dataclasses.dataclass
class StageReport:
    """One stage's accounting for one pipeline step."""

    stage: int
    busy_s: float = 0.0
    transfer_wait_s: float = 0.0
    send_s: float = 0.0
    phase_busy: dict = dataclasses.field(
        default_factory=lambda: {"fill": 0.0, "steady": 0.0, "drain": 0.0}
    )
    steady_window_s: float = 0.0
    steady_busy_s: float = 0.0
    window_s: float = 0.0


class StageExecutor:
    """Executes ONE stage's schedule for one optimizer step.

    ``channels``: dict with (any of) ``act_in``/``act_out``/``grad_in``/
    ``grad_out``; ``place_out``/``place_grad`` map an outgoing payload
    into the neighbor's representation (device_put onto its sub-mesh
    in-process; host numpy for the socket plane). Timing is measured
    with ``block_until_ready`` after every program so the per-phase
    busy/wait attribution is real device time, not dispatch time.
    """

    def __init__(
        self, k: int, n_stages: int, programs: dict, *,
        channels: dict, transfer_timeout_s: float = 120.0,
        place_in=None, clock=time.perf_counter,
    ):
        self.k = k
        self.n_stages = n_stages
        self.programs = programs
        self.channels = channels
        self.timeout = transfer_timeout_s
        self.place_in = place_in or (lambda x: x)
        self.clock = clock

    def _recv(self, name: str, rep: StageReport):
        t0 = self.clock()
        try:
            payload = self.channels[name].recv(self.timeout)
        except MpmdTransferTimeout as e:
            raise MpmdTransferTimeout(
                f"stage {self.k} waited > {self.timeout}s on {name} "
                f"({e})"
            ) from e
        rep.transfer_wait_s += self.clock() - t0
        return self.place_in(payload)

    def _send(self, name: str, payload, rep: StageReport) -> None:
        ch = self.channels.get(name)
        if ch is None:
            return
        t0 = self.clock()
        ch.send(payload)
        rep.send_s += self.clock() - t0

    def run_step(self, ops, state, microbatches, total) -> tuple:
        """Run one optimizer step's op list.

        ``microbatches``: for stage 0 a list of x microbatches; for the
        last stage a list of (y, w) pairs; None for middle stages.
        Returns (new_state, report, loss_sums) — loss_sums populated on
        the last stage only."""
        k, p = self.k, self.n_stages
        first, last = k == 0, k == p - 1
        rep = StageReport(stage=k)
        acc = zero_grads(state.params)
        saved: dict[int, object] = {}
        loss_sums: list = []
        t_start = None
        steady_t0 = steady_t1 = None
        for op in ops:
            if op.kind == "fwd":
                if first:
                    a_in = microbatches[op.mb]
                else:
                    a_in = self._recv("act_in", rep)
                t0 = self.clock()
                if last:
                    y, w = microbatches[op.mb]
                    loss_sum, count = self.programs["fwd"](
                        state.params, a_in, y, w
                    )
                    jax.block_until_ready(loss_sum)
                    out = None
                    loss_sums.append((loss_sum, count))
                else:
                    out = self.programs["fwd"](state.params, a_in)
                    jax.block_until_ready(out)
                t1 = self.clock()
                saved[op.mb] = a_in
                if out is not None:
                    self._send("act_out", out, rep)
            else:  # bwd
                a_in = saved.pop(op.mb)
                if last:
                    y, w = microbatches[op.mb]
                    t0 = self.clock()
                    acc, g_in = self.programs["bwd"](
                        state.params, a_in, y, w, acc
                    )
                else:
                    g = self._recv("grad_in", rep)
                    t0 = self.clock()
                    if first:
                        acc = self.programs["bwd"](
                            state.params, a_in, g, acc
                        )
                        g_in = None
                    else:
                        acc, g_in = self.programs["bwd"](
                            state.params, a_in, g, acc
                        )
                jax.block_until_ready(jax.tree.leaves(acc)[0])
                t1 = self.clock()
                if g_in is not None and not first:
                    self._send("grad_out", g_in, rep)
            if t_start is None:
                t_start = t0
            rep.busy_s += t1 - t0
            rep.phase_busy[op.phase] += t1 - t0
            if op.phase == "steady":
                steady_t0 = t0 if steady_t0 is None else steady_t0
                steady_t1 = t1
        t0 = self.clock()
        state = self.programs["update"](state, acc, total)
        jax.block_until_ready(state.step)
        t1 = self.clock()
        rep.busy_s += t1 - t0
        rep.window_s = t1 - (t_start if t_start is not None else t0)
        if steady_t0 is not None:
            rep.steady_window_s = steady_t1 - steady_t0
            rep.steady_busy_s = rep.phase_busy["steady"]
        return state, rep, loss_sums

    def run_eval(self, state, microbatches):
        """Forward-only microbatch pipeline for validation: stage 0
        feeds x microbatches, the last stage returns the 6 eval sums
        per microbatch; middle stages just relay."""
        k, p = self.k, self.n_stages
        first, last = k == 0, k == p - 1
        rep = StageReport(stage=k)
        sums = None
        n = len(microbatches) if microbatches is not None else None
        if n is None:
            # Middle stages learn the count from the stream: the
            # runner passes the microbatch count explicitly instead.
            raise ValueError("middle stages need an explicit count")
        for mb in range(n):
            if first:
                a_in = microbatches[mb]
            else:
                a_in = self._recv("act_in", rep)
            if last:
                y, w = microbatches[mb]
                out = self.programs["eval"](state.params, a_in, y, w)
                jax.block_until_ready(out[0])
                sums = (
                    out if sums is None
                    else tuple(a + b for a, b in zip(sums, out))
                )
            else:
                out = self.programs["fwd"](state.params, a_in)
                jax.block_until_ready(out)
                self._send("act_out", out, rep)
        return sums, rep


# ----------------------------------------------------------------------
# The in-process runner: one controller THREAD per stage (the
# multi-controller structure, single-process form) — stages genuinely
# overlap on their disjoint device slices, and the per-stage reports
# carry real fill/steady/drain/transfer-wait windows.


class MpmdRunner:
    def __init__(
        self, spec: MpmdSpec, stage_states: list, programs: list,
        meshes: list, *, clock=time.perf_counter,
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.spec = spec
        self.states = list(stage_states)
        self.programs = programs
        self.meshes = meshes
        self.clock = clock
        self.ops = build_schedule(
            spec.n_stages, spec.n_microbatches, spec.schedule
        )
        self._act_shardings = [
            NamedSharding(m, P()) for m in meshes
        ]
        self.last_reports: list[StageReport] = []

    def _executors(self):
        p = self.spec.n_stages
        act_ch = [QueueChannel() for _ in range(p - 1)]
        grad_ch = [QueueChannel() for _ in range(p - 1)]
        execs = []
        for k in range(p):
            sh = self._act_shardings
            channels = {}
            if k > 0:
                channels["act_in"] = act_ch[k - 1]
                # The SENDER places the payload onto the consumer's
                # sub-mesh (the local device_put transfer); wrap send.
                channels["grad_out"] = _PlacingChannel(
                    grad_ch[k - 1], sh[k - 1]
                )
            if k < p - 1:
                channels["act_out"] = _PlacingChannel(
                    act_ch[k], sh[k + 1]
                )
                channels["grad_in"] = grad_ch[k]
            execs.append(
                StageExecutor(
                    k, p, self.programs[k], channels=channels,
                    transfer_timeout_s=self.spec.transfer_timeout_s,
                    clock=self.clock,
                )
            )
        return execs

    def _split_mb(self, arr):
        m = self.spec.n_microbatches
        b = arr.shape[0]
        if b % m:
            raise MpmdSpecError(
                f"batch {b} does not tile n_microbatches={m}"
            )
        return [
            jnp.asarray(arr[i * (b // m):(i + 1) * (b // m)])
            for i in range(m)
        ]

    def train_step(self, x, y, w):
        """One optimizer step over the whole batch: returns
        (mean_loss, wall_s); per-stage reports in ``last_reports``."""
        xs = self._split_mb(np.asarray(x, np.float32))
        ys = self._split_mb(np.asarray(y))
        ws = self._split_mb(np.asarray(w, np.float32))
        positions = 1
        for d in np.asarray(y).shape[1:]:
            positions *= d
        total = max(
            float(np.asarray(w, np.float32).sum()) * positions, 1.0
        )
        execs = self._executors()
        p = self.spec.n_stages
        results: list = [None] * p
        errors: list = []

        def run(k):
            try:
                mbs = None
                if k == 0:
                    mbs = [
                        jax.device_put(a, self._act_shardings[0])
                        for a in xs
                    ]
                elif k == p - 1:
                    mbs = [
                        (
                            jax.device_put(ys[i], self._act_shardings[k]),
                            jax.device_put(ws[i], self._act_shardings[k]),
                        )
                        for i in range(len(ys))
                    ]
                results[k] = execs[k].run_step(
                    self.ops[k], self.states[k], mbs,
                    jnp.asarray(total, jnp.float32),
                )
            except BaseException as e:  # noqa: BLE001 — joined below
                errors.append((k, e))

        t0 = self.clock()
        threads = [
            threading.Thread(target=run, args=(k,), daemon=True)
            for k in range(p)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.spec.transfer_timeout_s * 4)
        wall = self.clock() - t0
        if errors:
            k, e = errors[0]
            raise RuntimeError(f"MPMD stage {k} failed: {e}") from e
        stuck = [k for k, t in enumerate(threads) if t.is_alive()]
        if stuck:
            raise MpmdTransferTimeout(
                f"stage thread(s) {stuck} still running past "
                f"{self.spec.transfer_timeout_s * 4}s — a wedged "
                "inter-stage dependency"
            )
        self.last_reports = [results[k][1] for k in range(p)]
        for k in range(p):
            self.states[k] = results[k][0]
        loss_sums = results[p - 1][2]
        loss = float(
            sum(float(np.asarray(s)) for s, _c in loss_sums) / total
        )
        return loss, wall

    def eval_pass(self, x, y, w):
        """Validation sums over one batch (forward-only pipeline):
        (loss_sum, acc_sum, count, tp, fp, fn) as floats."""
        xs = self._split_mb(np.asarray(x, np.float32))
        ys = self._split_mb(np.asarray(y))
        ws = self._split_mb(np.asarray(w, np.float32))
        execs = self._executors()
        p = self.spec.n_stages
        results: list = [None] * p
        errors: list = []

        def run(k):
            try:
                if k == 0:
                    mbs = [
                        jax.device_put(a, self._act_shardings[0])
                        for a in xs
                    ]
                elif k == p - 1:
                    mbs = [
                        (
                            jax.device_put(ys[i], self._act_shardings[k]),
                            jax.device_put(ws[i], self._act_shardings[k]),
                        )
                        for i in range(len(ys))
                    ]
                else:
                    mbs = [None] * len(xs)
                results[k] = execs[k].run_eval(self.states[k], mbs)
            except BaseException as e:  # noqa: BLE001
                errors.append((k, e))

        threads = [
            threading.Thread(target=run, args=(k,), daemon=True)
            for k in range(p)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.spec.transfer_timeout_s * 4)
        if errors:
            k, e = errors[0]
            raise RuntimeError(f"MPMD eval stage {k} failed: {e}") from e
        stuck = [k for k, t in enumerate(threads) if t.is_alive()]
        if stuck:
            raise MpmdTransferTimeout(
                f"eval stage thread(s) {stuck} still running past "
                f"{self.spec.transfer_timeout_s * 4}s"
            )
        sums = results[p - 1][0]
        return tuple(float(np.asarray(s)) for s in sums)

    def step_bubble(self, wall_s: float) -> dict:
        """Bubble accounting from the last step's per-stage reports:
        whole-step bubble, steady-state bubble, and per-stage phase
        attribution (the ``mpmd.step_report`` payload)."""
        p = self.spec.n_stages
        reps = self.last_reports
        busy = sum(r.busy_s for r in reps)
        step_bubble = 1.0 - busy / (p * wall_s) if wall_s > 0 else 0.0
        utils = [
            r.steady_busy_s / r.steady_window_s
            for r in reps
            if r.steady_window_s > 0
        ]
        steady_bubble = 1.0 - (sum(utils) / len(utils)) if utils else 0.0
        return {
            "schedule": self.spec.schedule,
            "n_stages": p,
            "n_microbatches": self.spec.n_microbatches,
            "wall_s": round(wall_s, 6),
            "step_bubble": round(max(0.0, step_bubble), 6),
            "steady_bubble": round(max(0.0, steady_bubble), 6),
            "analytic_bubble": round(
                analytic_bubble(p, self.spec.n_microbatches), 6
            ),
            "stages": [
                {
                    "stage": r.stage,
                    "busy_s": round(r.busy_s, 6),
                    "transfer_wait_s": round(r.transfer_wait_s, 6),
                    "send_s": round(r.send_s, 6),
                    "fill_s": round(r.phase_busy["fill"], 6),
                    "steady_s": round(r.phase_busy["steady"], 6),
                    "drain_s": round(r.phase_busy["drain"], 6),
                }
                for r in reps
            ],
        }


class _PlacingChannel:
    """Send-side wrapper: place the payload onto the consumer's
    sub-mesh before enqueueing (the explicit inter-slice transfer)."""

    def __init__(self, inner, sharding):
        self._inner = inner
        self._sharding = sharding

    def send(self, payload) -> None:
        self._inner.send(jax.device_put(payload, self._sharding))

    def recv(self, timeout: float):
        return self._inner.recv(timeout)
