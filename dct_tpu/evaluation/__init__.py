"""Continuous evaluation: champion/challenger harness, statistical
promotion gates, and drift detection for the rollout path.

The layer between training and deploy that the reference lacks
entirely: "best val_loss wins" becomes gated promotion — an offline
eval harness (:mod:`harness`) scores champion and challenger over the
same held-out split, statistical gates (:mod:`gates`) turn the paired
per-example loss deltas into a promote/hold/rollback decision, drift
detectors (:mod:`drift`) compare the serving-time world against the
training-data snapshot stamped into the deploy package, and
``python -m dct_tpu.evaluation.report`` pretty-prints the evidence.
See docs/EVALUATION.md.
"""

from dct_tpu.evaluation.gates import (  # noqa: F401
    GateDecision,
    GateRejection,
    PromotionGate,
    paired_bootstrap,
    record_decision,
    render_gate_metrics,
    sign_test,
)
from dct_tpu.evaluation.harness import (  # noqa: F401
    EvalError,
    EvalResult,
    PairedEval,
    evaluate_model,
    evaluate_pair,
    load_eval_split,
    load_model,
)
