"""Statistical promotion gates: paired bootstrap + sign test over the
harness's per-example loss deltas, thresholds, and a typed decision.

The reference's rollout advances shadow -> canary -> full on a timer;
these gates make each advance conditional on evidence:

- **paired bootstrap** — resample the per-example loss deltas
  (champion - challenger) ``bootstrap_samples`` times and measure how
  often the challenger wins on the mean; deterministic under the
  configured seed so a decision is reproducible from its inputs;
- **sign test** — distribution-free check on the per-example win
  count (exact binomial for small n, normal approximation above),
  robust to the heavy-tailed per-example NLLs the bootstrap mean can
  be dragged by;
- **thresholds** — ``min_improvement`` / ``max_regression`` on the
  mean delta, ``max_slice_regression`` on the worst per-slice loss
  regression, PSI/KS drift flags, and the shadow-stage prediction
  disagreement rate.

The product is a :class:`GateDecision` — ``promote`` / ``hold`` /
``rollback`` plus the full evidence — which
:class:`~dct_tpu.deploy.rollout.RolloutOrchestrator` consults between
stages (emitting ``deploy.gate`` events) and maps to its PR-3
``rollback()`` on anything but promote. Every decision also lands in a
JSON ledger that the serving server's ``GET /metrics`` (and the
``deploy_gate.prom`` textfile) renders as
``dct_deploy_gate_decisions_total`` / ``dct_drift_psi``.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field

import numpy as np

PROMOTE, HOLD, ROLLBACK = "promote", "hold", "rollback"


class GateRejection(RuntimeError):
    """A promotion gate blocked the rollout; carries the decision."""

    def __init__(self, decision: "GateDecision"):
        self.decision = decision
        super().__init__(
            f"Promotion gate {decision.decision} at {decision.stage}: "
            f"{decision.reason}"
        )


@dataclass
class GateDecision:
    """Typed gate outcome with its evidence.

    ``promote``  — advance the rollout stage;
    ``hold``     — do not advance (insufficient/negative evidence that
                   is not a proven regression: drift, disagreement,
                   missing improvement under ``require_improvement``);
    ``rollback`` — the challenger demonstrably regresses; revert.

    The orchestrator treats hold and rollback identically for traffic
    safety (revert to the champion); the distinction is the operator's
    triage signal.
    """

    decision: str
    stage: str
    reason: str
    evidence: dict = field(default_factory=dict)

    @property
    def promoted(self) -> bool:
        return self.decision == PROMOTE

    def to_dict(self) -> dict:
        return {
            "decision": self.decision,
            "stage": self.stage,
            "reason": self.reason,
            "evidence": self.evidence,
        }


# ----------------------------------------------------------------------
# Statistics (pure, deterministic).

def paired_bootstrap(
    deltas: np.ndarray, *, n_boot: int = 1000, seed: int = 42
) -> dict:
    """Bootstrap distribution of the mean paired delta.

    Returns mean_delta, p_better (fraction of resample means > 0 — the
    challenger's win probability on the mean), and the central 90% band.
    Deterministic: seeded generator, vectorized resampling.
    """
    d = np.asarray(deltas, np.float64)
    n = len(d)
    if n == 0:
        return {"mean_delta": 0.0, "p_better": 0.5,
                "ci_low": 0.0, "ci_high": 0.0, "n": 0}
    rng = np.random.default_rng(seed)
    n_boot = int(n_boot)
    # Chunked resampling: one (n_boot, n) index matrix is multi-GB at
    # dataset-scale splits (100k examples x 1000 resamples), and an
    # OOM-killed gate reads as a fail-closed hold. Consecutive
    # generator draws consume the same stream as a single big one, so
    # the result is bit-identical for a given seed at any chunking.
    chunk = max(1, min(n_boot, 4_000_000 // max(n, 1) or 1))
    means = np.empty(n_boot, np.float64)
    done = 0
    while done < n_boot:
        k = min(chunk, n_boot - done)
        idx = rng.integers(0, n, size=(k, n))
        means[done:done + k] = d[idx].mean(axis=1)
        done += k
    lo, hi = np.quantile(means, [0.05, 0.95])
    return {
        "mean_delta": float(d.mean()),
        "p_better": float((means > 0.0).mean()),
        "ci_low": float(lo),
        "ci_high": float(hi),
        "n": int(n),
    }


def sign_test(deltas: np.ndarray) -> dict:
    """Sign test over the per-example win counts, both tails.

    Distribution-free companion to the bootstrap: per-example NLLs are
    heavy-tailed, and a handful of outliers can drag the mean either
    way; the win COUNT cannot be. ``p_value`` is the challenger-better
    tail P(wins >= observed | fair coin); ``p_worse`` the
    challenger-worse tail P(losses >= observed). Exact binomial for
    n <= 200 (math.comb — no scipy on serving images), normal
    approximation with continuity correction above.
    """
    d = np.asarray(deltas, np.float64)
    wins = int((d > 0).sum())
    losses = int((d < 0).sum())
    n = wins + losses  # ties carry no sign information
    if n == 0:
        return {"wins": 0, "losses": 0, "p_value": 1.0, "p_worse": 1.0}

    def tail(k: int) -> float:
        if n <= 200:
            p = sum(math.comb(n, j) for j in range(k, n + 1)) / 2.0 ** n
        else:
            z = (k - 0.5 - n / 2.0) / math.sqrt(n / 4.0)
            p = 0.5 * math.erfc(z / math.sqrt(2.0))
        return float(min(1.0, p))

    return {
        "wins": wins, "losses": losses,
        "p_value": tail(wins), "p_worse": tail(losses),
    }


# ----------------------------------------------------------------------
# The gate.

class PromotionGate:
    """Consulted by the rollout orchestrator between stages.

    Stateless over rollouts: every :meth:`evaluate` loads both models,
    runs the harness over the held-out split, applies the drift and
    disagreement detectors, and returns a :class:`GateDecision`. The
    heavy offline eval is cached in the challenger package
    (``eval_report.json``), so the DAG's ``evaluate_challenger`` task
    pays it once and the per-stage consults reuse it.
    """

    def __init__(self, cfg=None, *, processed_dir: str | None = None):
        from dct_tpu.config import (
            DataConfig, EvaluationConfig, TrainConfig,
        )

        self.cfg = cfg or EvaluationConfig.from_env()
        self.processed_dir = processed_dir or os.environ.get(
            "DCT_PROCESSED_DIR", "data/processed"
        )
        # The harness must rebuild the TRAINER's validation split, not
        # a default one: a rig trained under DCT_SEED=7 splits on a
        # different permutation, and scoring the challenger on rows it
        # trained on would bias the whole comparison optimistic. These
        # env-derived values are the FALLBACK; a challenger package
        # whose manifest stamps its split (prepare_package does) wins —
        # the gate process has no env inheritance from the training
        # launch.
        self.val_fraction = DataConfig.from_env().val_fraction
        self.split_seed = TrainConfig.from_env().seed

    def _split_for(self, challenger_dir: str) -> tuple[float, int]:
        """(val_fraction, seed) for the harness split: the challenger
        manifest's stamped values when present, env fallback."""
        from dct_tpu.deploy.rollout import package_manifest

        split = package_manifest(challenger_dir).get("split") or {}
        try:
            vf = float(split["val_fraction"])
            seed = int(split["seed"])
            return vf, seed
        except (KeyError, TypeError, ValueError):
            return self.val_fraction, self.split_seed

    @classmethod
    def from_env(cls) -> "PromotionGate | None":
        from dct_tpu.config import EvaluationConfig

        cfg = EvaluationConfig.from_env()
        return cls(cfg) if cfg.gate_enabled else None

    # -- evidence collection -------------------------------------------
    def offline_eval(
        self, challenger_dir: str, champion_dir: str | None,
    ) -> dict:
        """The offline harness pass: paired per-example losses + sliced
        metrics + bootstrap/sign statistics + drift vs the champion
        package's stamped data snapshot. Cached as
        ``eval_report.json`` inside the challenger package. Raises
        :class:`~dct_tpu.evaluation.harness.EvalError` on missing
        prerequisites."""
        from dct_tpu.evaluation import harness

        from dct_tpu.observability import events as _events

        cache = os.path.join(challenger_dir, "eval_report.json")
        cached = self._read_cached_report(cache, champion_dir)
        if cached is not None:
            return cached

        log = _events.get_default()
        log.emit(
            "eval", "eval.start",
            champion=champion_dir, challenger=challenger_dir,
            engine=self.cfg.engine,
        )
        champion = harness.load_model(champion_dir)
        challenger = harness.load_model(challenger_dir)
        val_fraction, split_seed = self._split_for(challenger_dir)
        data = self._load_data()
        pair = harness.evaluate_pair(
            champion, challenger, self.processed_dir,
            batch_size=self.cfg.eval_batch, engine=self.cfg.engine,
            val_fraction=val_fraction, seed=split_seed,
            data=data,
        )
        report = pair.to_dict()
        report["champion_dir"] = champion_dir
        if pair.paired:
            report["bootstrap"] = paired_bootstrap(
                pair.deltas,
                n_boot=self.cfg.bootstrap_samples, seed=self.cfg.seed,
            )
            report["sign_test"] = sign_test(pair.deltas)
        report["drift"] = self._drift_report(champion_dir, data=data)
        self._write_cached_report(cache, report)
        log.emit(
            "eval", "eval.report",
            champion_loss=report["champion"]["loss_mean"],
            challenger_loss=report["challenger"]["loss_mean"],
            mean_delta=report["mean_delta"],
            n=report["champion"]["n"], paired=report["paired"],
            max_psi=(report["drift"] or {}).get("max_psi"),
        )
        return report

    def _load_data(self):
        """One parquet load per evaluation, shared by the harness split
        and the drift report (dataset-scale splits must not pay the IO
        twice) — and cached across CONSECUTIVE evaluations by snapshot
        identity (dataset._snapshot_key: part-file name/mtime/size), so
        the always-on loop's repeated evals against one processed
        snapshot pay the parquet IO once. None when unavailable —
        callers degrade."""
        from dct_tpu.data.dataset import load_processed_dataset_cached

        try:
            return load_processed_dataset_cached(self.processed_dir)
        except Exception:  # noqa: BLE001 — harness raises its own
            return None  # typed EvalError; drift just has no evidence

    def _drift_report(self, champion_dir: str | None, *, data=None) -> dict | None:
        """New ETL output vs the data snapshot stamped into the CHAMPION
        package (what the deployed model was trained on)."""
        from dct_tpu.evaluation import drift as _drift

        if not champion_dir:
            return None
        snapshot = None
        try:
            with open(os.path.join(champion_dir, "run_info.json")) as f:
                snapshot = json.load(f).get("data_snapshot")
        except (OSError, ValueError):
            pass
        if not snapshot:
            return None
        if data is None:
            data = self._load_data()
        if data is None:
            return None
        # Align strictly BY NAME (the snapshot was taken from the same
        # loader, so names match on a healthy pipeline): a positional
        # fallback would compare renamed columns against the wrong
        # snapshot entries and silence exactly the schema drift the
        # detector exists to flag.
        return _drift.feature_drift(
            snapshot, data.features, list(data.feature_names),
            psi_threshold=self.cfg.psi_threshold,
            ks_threshold=self.cfg.ks_threshold,
        )

    def _read_cached_report(
        self, path: str, champion_dir: str | None
    ) -> dict | None:
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError):
            return None
        # The cache is only valid against the same champion.
        if report.get("champion_dir") != champion_dir:
            return None
        return report

    @staticmethod
    def _write_cached_report(path: str, report: dict) -> None:
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2)
            os.replace(tmp, path)
        except (OSError, TypeError):
            pass  # caching is an optimization, never a blocker

    # -- decision ------------------------------------------------------
    def evaluate(
        self,
        *,
        challenger_dir: str,
        champion_dir: str | None,
        stage: str,
        mirror_capture: str | None = None,
        shadow_slot: str | None = None,
    ) -> GateDecision:
        """Full gate consult for one stage transition. Never raises:
        missing prerequisites resolve per ``fail_open``.
        ``shadow_slot`` scopes the mirror capture to pairs whose shadow
        really was this rollout's challenger slot."""
        from dct_tpu.evaluation import drift as _drift
        from dct_tpu.evaluation.harness import EvalError

        if not champion_dir or not os.path.exists(
            os.path.join(champion_dir, "model.npz")
        ) or os.path.abspath(champion_dir) == os.path.abspath(challenger_dir):
            # First deployment, a retired/wiped champion package, or a
            # reused package dir: nothing to compare against.
            return GateDecision(PROMOTE, stage, "no_champion")
        try:
            report = self.offline_eval(challenger_dir, champion_dir)
        except EvalError as e:
            dec = PROMOTE if self.cfg.fail_open else HOLD
            return GateDecision(
                dec, stage, f"no_eval_evidence: {e}",
                evidence={"fail_open": self.cfg.fail_open},
            )
        disagreement = None
        if stage == "canary":  # the shadow -> canary transition
            disagreement = _drift.disagreement_report(
                mirror_capture, max_disagreement=self.cfg.max_disagreement,
                shadow_slot=shadow_slot,
            )
        return self.decide(
            report, stage=stage, disagreement=disagreement
        )

    def decide(
        self, report: dict, *, stage: str, disagreement: dict | None = None
    ) -> GateDecision:
        """Pure decision over collected evidence (unit-testable without
        packages or data)."""
        cfg = self.cfg
        evidence = {
            "mean_delta": report.get("mean_delta", 0.0),
            "paired": report.get("paired", False),
            "champion_loss": report["champion"]["loss_mean"],
            "challenger_loss": report["challenger"]["loss_mean"],
            "slice_regressions": report.get("slice_regressions", {}),
        }
        boot = report.get("bootstrap")
        sign = report.get("sign_test")
        if boot:
            evidence["bootstrap"] = boot
        if sign:
            evidence["sign_test"] = sign
        drift_rep = report.get("drift")
        if drift_rep:
            evidence["drift"] = {
                "max_psi": drift_rep.get("max_psi", 0.0),
                "any_drift": drift_rep.get("any_drift", False),
            }
        if disagreement:
            evidence["disagreement"] = disagreement

        mean_delta = evidence["mean_delta"]
        alpha = 1.0 - cfg.confidence
        sig_boot_worse = boot is not None and boot["p_better"] <= alpha
        sig_sign_worse = (
            sign is not None and sign.get("p_worse", 1.0) <= alpha
        )
        # 1. Proven regression -> rollback. Paired evidence requires
        # either test (bootstrap mean OR per-example win count — the
        # sign test catches what a few champion outlier losses can hide
        # from the mean) to call the regression significant; unpaired
        # (family upgrade) falls back to the raw mean threshold.
        if boot is not None or sign is not None:
            significantly_worse = (
                mean_delta < -cfg.max_regression
                and (sig_boot_worse or sig_sign_worse)
            )
        else:
            significantly_worse = mean_delta < -max(
                cfg.max_regression, 1e-9
            )
        if significantly_worse:
            return GateDecision(
                ROLLBACK, stage, "challenger_regression", evidence
            )
        # 2. Slice regression beyond tolerance -> rollback (an aggregate
        # win must not hide the rain slice getting worse).
        worst = max(
            evidence["slice_regressions"].values(), default=0.0
        )
        if worst > cfg.max_slice_regression:
            return GateDecision(
                ROLLBACK, stage, "slice_regression", evidence
            )
        # 2b. Per-example regression the mean hides -> hold: the
        # challenger loses on a significant majority of examples while
        # the mean improvement is NOT significant (a handful of champion
        # outlier losses dragging the mean positive must not promote).
        if sig_sign_worse and not (
            boot is not None and boot["p_better"] >= cfg.confidence
        ):
            return GateDecision(
                HOLD, stage, "per_example_regression", evidence
            )
        # 3. Shadow disagreement over real mirrored traffic -> hold.
        if disagreement and disagreement.get("exceeded"):
            return GateDecision(
                HOLD, stage, "shadow_disagreement", evidence
            )
        # 4. Feature drift vs the champion's training snapshot -> hold
        # (the data moved; the offline comparison may not transfer).
        if drift_rep and drift_rep.get("any_drift"):
            return GateDecision(HOLD, stage, "data_drift", evidence)
        # 5. Optional improvement requirement.
        if cfg.require_improvement or cfg.min_improvement > 0:
            improved = mean_delta >= cfg.min_improvement and (
                boot is None or boot["p_better"] >= cfg.confidence
            )
            if not improved:
                return GateDecision(
                    HOLD, stage, "insufficient_improvement", evidence
                )
            return GateDecision(PROMOTE, stage, "improvement", evidence)
        return GateDecision(PROMOTE, stage, "no_regression", evidence)


def log_eval_report(tracker, report: dict, report_path: str) -> str | None:
    """Log an offline eval report to the tracking store as an artifact.

    Opens a short-lived run of its own (params kind=evaluation) holding
    the headline metrics plus the report file under artifact path
    ``evaluation``. It logs no ``val_loss``, so the deploy DAGs'
    best-run selection query can never pick it up. Returns the run id,
    or None when the report file is missing (nothing to log).
    """
    if not report_path or not os.path.exists(report_path):
        return None
    run_id = tracker.start_run(params={"kind": "evaluation"})
    try:
        tracker.log_metrics(
            {
                "eval_champion_loss": report["champion"]["loss_mean"],
                "eval_challenger_loss": report["challenger"]["loss_mean"],
                "eval_mean_delta": report["mean_delta"],
            },
            step=0,
        )
        tracker.log_artifact(report_path, "evaluation")
    except Exception:
        # Close the books before surfacing: a half-logged evaluation
        # must not linger as a phantom RUNNING run in the store (the
        # same leak class the trainer closes for preempt/health exits).
        try:
            tracker.end_run(status="FAILED")
        except Exception:  # noqa: BLE001 — bookkeeping must not mask
            pass
        raise
    tracker.end_run()
    return run_id


# ----------------------------------------------------------------------
# Decision ledger -> /metrics. The gate runs in DAG task processes; the
# serving server is long-lived — a tiny JSON ledger bridges them (the
# textfile pattern, like the trainer's train_metrics.prom).

def gate_ledger_path(explicit: str = "") -> str:
    if explicit:
        return explicit
    if os.environ.get("DCT_GATE_LEDGER"):
        return os.environ["DCT_GATE_LEDGER"]
    events_dir = os.environ.get("DCT_EVENTS_DIR", "logs/events")
    return os.path.join(events_dir, "gate_ledger.json")


def record_decision(
    decision: GateDecision, *, ledger_path: str = ""
) -> None:
    """Fold one decision into the ledger (decision counters + last
    decision + last drift PSI per run) and refresh the
    ``deploy_gate.prom`` textfile beside it. Best-effort: telemetry
    never blocks a rollout."""
    path = gate_ledger_path(ledger_path)
    try:
        try:
            with open(path) as f:
                ledger = json.load(f)
        except (OSError, ValueError):
            ledger = {}
        counts = ledger.setdefault("decisions", {})
        counts[decision.decision] = int(counts.get(decision.decision, 0)) + 1
        ledger["last"] = {
            "decision": decision.decision,
            "stage": decision.stage,
            "reason": decision.reason,
        }
        drift = (decision.evidence or {}).get("drift")
        if drift is not None:
            ledger["max_psi"] = float(drift.get("max_psi", 0.0))
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(ledger, f, indent=2)
        os.replace(tmp, path)
        _write_gate_prom(ledger, os.path.join(
            os.path.dirname(path) or ".", "deploy_gate.prom"
        ))
    except OSError:
        pass


def render_gate_metrics(ledger_path: str = "") -> str:
    """Exposition-format text for the gate counters, appended to the
    serving server's ``GET /metrics`` body ("" when no ledger exists —
    rigs that never gate see no extra series)."""
    try:
        with open(gate_ledger_path(ledger_path)) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        return ""
    return _gate_families_text(ledger)


def _gate_families_text(ledger: dict) -> str:
    from dct_tpu.observability.prometheus import MetricFamily, render

    fams = []
    decisions = MetricFamily(
        "dct_deploy_gate_decisions_total", "counter",
        "Promotion-gate decisions by outcome (promote/hold/rollback).",
    )
    for name in (PROMOTE, HOLD, ROLLBACK):
        n = int((ledger.get("decisions") or {}).get(name, 0))
        decisions.add(n, {"decision": name})
    fams.append(decisions)
    if "max_psi" in ledger:
        fams.append(
            MetricFamily(
                "dct_drift_psi", "gauge",
                "Max per-feature PSI of the latest gated evaluation "
                "(new ETL output vs the champion's training snapshot).",
            ).add(float(ledger["max_psi"]))
        )
    return render(fams)


def _write_gate_prom(ledger: dict, path: str) -> None:
    """The textfile-collector twin of the /metrics surface."""
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(_gate_families_text(ledger))
        os.replace(tmp, path)
    except OSError:
        pass
