"""Eval-report pretty-printer: the run inspector's evaluation sibling.

Usage::

    python -m dct_tpu.evaluation.report <dir> [--events <events_dir>]

``<dir>`` is a challenger package dir (holding ``eval_report.json``),
a tracking artifacts tree, or any parent — every ``eval_report.json``
below it is rendered: champion vs challenger aggregate and per-slice
metrics, the bootstrap/sign statistics, drift PSI/KS per feature, and
the ``deploy.gate`` decisions found in the event log. Read-only over
the artifacts; missing surfaces degrade to "(none found)", never
errors — like the run inspector, partial evidence is exactly when an
operator reaches for this.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def find_reports(root: str) -> list[str]:
    if os.path.isfile(root):
        return [root]
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        if "eval_report.json" in filenames:
            out.append(os.path.join(dirpath, "eval_report.json"))
    return out


def load_gate_events(events_dir: str | None) -> list[dict]:
    if not events_dir:
        return []
    path = os.path.join(events_dir, "events.jsonl")
    if os.path.isfile(events_dir):
        path = events_dir
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    out = []
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and rec.get("event") == "deploy.gate":
            out.append(rec)
    return out


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)


def render_report(report: dict, path: str) -> str:
    """One eval report as a printable block (pure function of the
    artifact — unit-testable without capturing stdout)."""
    lines = []
    lines.append("=" * 72)
    lines.append(f"Evaluation report — {path}")
    lines.append("=" * 72)
    champ, chall = report.get("champion", {}), report.get("challenger", {})
    lines.append(
        f"  {'':14s} {'loss':>10s} {'accuracy':>10s} {'n':>8s}"
    )
    for label, side in (("champion", champ), ("challenger", chall)):
        lines.append(
            f"  {label:14s} {_fmt(side.get('loss_mean', '?')):>10s} "
            f"{_fmt(side.get('accuracy', '?')):>10s} "
            f"{str(side.get('n', '?')):>8s}"
        )
    md = report.get("mean_delta")
    if md is not None:
        verdict = "challenger better" if md > 0 else (
            "challenger worse" if md < 0 else "tied"
        )
        lines.append(
            f"  mean paired delta (champion - challenger): "
            f"{_fmt(md)}  ({verdict})"
        )
    boot = report.get("bootstrap")
    if boot:
        lines.append(
            f"  bootstrap: p_better={_fmt(boot.get('p_better'))} "
            f"90% band [{_fmt(boot.get('ci_low'))}, "
            f"{_fmt(boot.get('ci_high'))}] over n={boot.get('n')}"
        )
    sign = report.get("sign_test")
    if sign:
        lines.append(
            f"  sign test: {sign.get('wins')} wins / "
            f"{sign.get('losses')} losses, p={_fmt(sign.get('p_value'))}"
        )
    slices = chall.get("slices") or {}
    if slices:
        lines.append("")
        lines.append("  Slices (challenger vs champion loss):")
        regressions = report.get("slice_regressions", {})
        for name in sorted(slices):
            ch = slices[name]
            cp = (champ.get("slices") or {}).get(name, {})
            reg = regressions.get(name)
            tag = ""
            if reg is not None:
                tag = f"  Δ{_fmt(reg)}" + (" (regressed)" if reg > 0 else "")
            lines.append(
                f"    {name:16s} {_fmt(ch.get('loss'))} vs "
                f"{_fmt(cp.get('loss', '?'))} "
                f"(acc {_fmt(ch.get('accuracy'))}, n={ch.get('n')}){tag}"
            )
    drift = report.get("drift")
    lines.append("")
    lines.append("  Drift vs champion's training snapshot:")
    if drift:
        lines.append(
            f"    max_psi={_fmt(drift.get('max_psi'))} "
            f"(threshold {_fmt(drift.get('psi_threshold'))}) "
            f"any_drift={drift.get('any_drift')}"
        )
        for name in sorted(drift.get("features", {})):
            f = drift["features"][name]
            if "psi" in f:
                lines.append(
                    f"    {name:20s} psi={_fmt(f['psi'])} "
                    f"ks={_fmt(f['ks'])}"
                    + ("  DRIFTED" if f.get("drifted") else "")
                )
            else:
                lines.append(f"    {name:20s} schema drift: {f}")
    else:
        lines.append("    (no snapshot in the champion package)")
    return "\n".join(lines)


def render_gate_events(events: list[dict]) -> str:
    lines = ["", "Gate decisions (deploy.gate events):"]
    if not events:
        lines.append("  (none found)")
        return "\n".join(lines)
    for r in events:
        lines.append(
            f"  {r.get('run_id', '?')}  stage={r.get('stage')} "
            f"decision={r.get('decision')} reason={r.get('reason')} "
            f"mean_delta={_fmt(r.get('mean_delta', '?'))}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dct_tpu.evaluation.report",
        description=(
            "Pretty-print champion/challenger eval reports, drift "
            "metrics, and gate decisions."
        ),
    )
    parser.add_argument(
        "root",
        help="package dir, eval_report.json, or a parent to search",
    )
    parser.add_argument(
        "--events", default=os.environ.get("DCT_EVENTS_DIR", "logs/events"),
        help="events dir (or events.jsonl) for deploy.gate decisions",
    )
    args = parser.parse_args(argv)
    if not os.path.exists(args.root):
        print(f"error: {args.root} does not exist", file=sys.stderr)
        return 2
    reports = find_reports(args.root)
    if not reports:
        print(f"(no eval_report.json under {args.root})")
    for path in reports:
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"(unreadable report {path}: {e})", file=sys.stderr)
            continue
        print(render_report(report, path))
    print(render_gate_events(load_gate_events(args.events)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
