"""Deploy-side drift detection: PSI/KS against the package's stamped
training-data snapshot, plus shadow-stage prediction disagreement.

Two detectors feed the promotion gates:

1. **Feature-distribution drift** — ``prepare_package`` stamps a
   quantile snapshot of the training data (per-feature bin edges +
   counts + moments) into the deploy package's manifest; before a new
   cycle's challenger advances, the NEW ETL output is compared against
   the snapshot the deployed champion was trained on. Per feature:
   PSI (population stability index over the snapshot's quantile bins —
   the industry drift metric: ~0.1 moderate, ~0.2 major shift) and the
   two-sample KS D-statistic (bin-free, catches shape changes PSI's
   binning can smear). This is a different, later gate than the
   ETL-side run-over-run stats compare in :mod:`dct_tpu.etl.preprocess`
   — that one compares consecutive ETL runs, this one compares the
   serving-time world against what the champion actually learned from.

2. **Prediction disagreement** — during the shadow stage the endpoint
   mirrors a fraction of live traffic to the challenger;
   :class:`~dct_tpu.deploy.local.LocalEndpointClient` (and the HTTP
   endpoint server) capture each mirrored pair of responses to a JSONL
   file. The disagreement rate (argmax mismatch) and mean total
   variation between the two models' probabilities over REAL traffic is
   the signal a held-out file cannot give — it feeds the shadow->canary
   gate.

Everything is plain numpy + stdlib (no scipy on the serving images).
"""

from __future__ import annotations

import json
import os

import numpy as np


# ----------------------------------------------------------------------
# Snapshot: what prepare_package stamps into the deploy manifest.

def snapshot_features(
    features: np.ndarray, names: list[str], *, bins: int = 10
) -> dict:
    """JSON-able training-data snapshot: per-feature quantile bin edges
    + counts + moments. Quantile (not uniform) edges: every bin holds
    ~1/bins of the training mass, which is what makes PSI's expected
    fractions well-conditioned."""
    out: dict = {"rows": int(len(features)), "bins": int(bins), "features": {}}
    for j, name in enumerate(names):
        col = np.asarray(features[:, j], np.float64)
        qs = np.quantile(col, np.linspace(0.0, 1.0, bins + 1))
        # Strictly-increasing edges (ties collapse bins for discrete or
        # constant features); outermost edges widen to +-inf at use.
        edges = np.unique(qs)
        if len(edges) <= 3:
            # Heavy collapse = a discrete feature: per-VALUE bins
            # (midpoint boundaries) keep PSI sensitive to e.g. a binary
            # rate shift that a single quantile bin would swallow. A
            # constant feature stays degenerate; the drift comparison
            # falls back to a moment check for it.
            vals = np.unique(col)
            if 2 <= len(vals) <= 16:
                edges = np.concatenate(
                    [[vals[0]], (vals[:-1] + vals[1:]) / 2.0, [vals[-1]]]
                )
        counts, _ = np.histogram(col, _open_edges(edges))
        out["features"][name] = {
            "mean": float(col.mean()),
            "std": float(col.std(ddof=1)) if len(col) > 1 else 0.0,
            "edges": [float(e) for e in edges],
            "counts": [int(c) for c in counts],
            # Point-mass features: the KS leg's bin-uniform CDF
            # reconstruction misstates them, so the detector runs PSI
            # only (the per-value bins keep PSI sharp there).
            "discrete": bool(len(np.unique(col)) <= 16),
        }
    return out


def _open_edges(edges: np.ndarray) -> np.ndarray:
    """Histogram edges with open outer bins so out-of-range serving
    values still land in a bin instead of silently dropping."""
    e = np.asarray(edges, np.float64).copy()
    if len(e) < 2:
        return np.array([-np.inf, np.inf])
    e[0], e[-1] = -np.inf, np.inf
    return e


def psi(expected_counts, actual_counts) -> float:
    """Population stability index between two binned distributions
    (epsilon-smoothed: an empty bin must not blow the sum to inf)."""
    e = np.asarray(expected_counts, np.float64)
    a = np.asarray(actual_counts, np.float64)
    e = np.maximum(e / max(e.sum(), 1.0), 1e-6)
    a = np.maximum(a / max(a.sum(), 1.0), 1e-6)
    return float(np.sum((a - e) * np.log(a / e)))


def ks_statistic(a: np.ndarray, b: np.ndarray) -> float:
    """Two-sample Kolmogorov-Smirnov D-statistic (max CDF gap)."""
    a = np.sort(np.asarray(a, np.float64))
    b = np.sort(np.asarray(b, np.float64))
    allv = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, allv, side="right") / max(len(a), 1)
    cdf_b = np.searchsorted(b, allv, side="right") / max(len(b), 1)
    return float(np.abs(cdf_a - cdf_b).max()) if len(allv) else 0.0


def feature_drift(
    snapshot: dict,
    features: np.ndarray,
    names: list[str],
    *,
    psi_threshold: float = 0.2,
    ks_threshold: float = 0.15,
) -> dict:
    """Compare the new ETL output against the package's stamped
    training-data snapshot. Returns a JSON-able report with per-feature
    PSI/KS, ``max_psi`` (the /metrics gauge), and ``any_drift``.

    The KS leg compares the new sample against a synthetic sample drawn
    deterministically from the snapshot's binned distribution (the raw
    training column is not shipped in the manifest); with quantile bins
    the bin-uniform reconstruction is exact enough for a D-statistic
    threshold test.
    """
    feats: dict = {}
    any_drift = False
    snap_feats = (snapshot or {}).get("features", {})
    for j, name in enumerate(names):
        snap = snap_feats.get(name)
        col = np.asarray(features[:, j], np.float64)
        if snap is None:
            # Schema drift (feature added/renamed) IS drift.
            any_drift = True
            feats[name] = {"drifted": True, "missing_in_snapshot": True}
            continue
        edges = np.asarray(snap["edges"], np.float64)
        counts, _ = np.histogram(col, _open_edges(edges))
        p = psi(snap["counts"], counts)
        # The KS leg needs a faithful CDF reconstruction: the
        # bin-uniform sample misstates point masses — an i.i.d.
        # resample of a binary feature would read D ~ 0.5. PSI handles
        # discrete bins fine (per-value bins in the snapshot), so KS
        # only runs where the snapshot has real continuous support.
        continuous = len(edges) >= 4 and not snap.get("discrete")
        sample = _snapshot_sample(snap) if continuous else np.zeros(0)
        ks = ks_statistic(sample, col) if len(sample) else 0.0
        if len(edges) < 2 or (len(edges) == 2 and edges[0] == edges[-1]):
            # A feature that was CONSTANT at training time has one
            # degenerate bin, which blinds both PSI and KS: any value
            # change at all is drift by definition.
            drifted = bool(
                abs(col.mean() - snap["mean"]) > 1e-9 or col.std() > 1e-9
            )
        else:
            drifted = bool(p > psi_threshold or ks > ks_threshold)
        any_drift |= drifted
        feats[name] = {
            "psi": round(p, 4), "ks": round(ks, 4), "drifted": drifted,
        }
    # Features the champion trained on that the new ETL no longer
    # produces are schema drift too (the name-aligned loop above only
    # sees the CURRENT columns).
    for name in sorted(set(snap_feats) - set(names)):
        any_drift = True
        feats[name] = {"drifted": True, "missing_in_current": True}
    psis = [v["psi"] for v in feats.values() if "psi" in v]
    return {
        "psi_threshold": psi_threshold,
        "ks_threshold": ks_threshold,
        "features": feats,
        "max_psi": max(psis) if psis else 0.0,
        "any_drift": any_drift,
    }


def _snapshot_sample(snap: dict, per_bin: int = 32) -> np.ndarray:
    """Deterministic sample from a snapshot's binned distribution:
    ``per_bin`` evenly-spaced points per bin, weighted by repeating
    proportional to the bin count — enough support for a KS D test."""
    edges = np.asarray(snap["edges"], np.float64)
    counts = np.asarray(snap["counts"], np.float64)
    if len(edges) < 2 or counts.sum() <= 0:
        return np.zeros(0)
    total = counts.sum()
    parts = []
    for i in range(len(counts)):
        lo, hi = edges[i], edges[i + 1]
        if not (np.isfinite(lo) and np.isfinite(hi)):
            lo = edges[1] if not np.isfinite(lo) else lo
            hi = edges[-2] if not np.isfinite(hi) else hi
        reps = int(round(per_bin * len(counts) * counts[i] / total))
        if reps:
            parts.append(np.linspace(lo, hi, reps, endpoint=False))
    return np.concatenate(parts) if parts else np.zeros(0)


# ----------------------------------------------------------------------
# Prediction disagreement over mirrored shadow traffic.

def prediction_disagreement(
    live_probs: np.ndarray, shadow_probs: np.ndarray
) -> dict:
    """Disagreement between the champion's live responses and the
    challenger's mirrored ones: argmax mismatch rate + mean total
    variation distance."""
    live = np.asarray(live_probs, np.float64)
    shadow = np.asarray(shadow_probs, np.float64)
    n = min(len(live), len(shadow))
    if n == 0:
        return {"n": 0, "rate": 0.0, "mean_tv": 0.0}
    live, shadow = live[:n], shadow[:n]
    rate = float(
        (np.argmax(live, axis=-1) != np.argmax(shadow, axis=-1)).mean()
    )
    tv = float(0.5 * np.abs(live - shadow).sum(axis=-1).mean())
    return {"n": int(n), "rate": rate, "mean_tv": round(tv, 6)}


def read_mirror_capture(
    path: str, *, shadow_slot: str | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Parse a mirror-capture JSONL (LocalEndpointClient / endpoint
    server writers) into (live_probs, shadow_probs) row-aligned arrays.
    ``shadow_slot`` keeps only pairs mirrored to that slot (the gate
    must score THIS rollout's challenger, not every shadow ever
    captured). Torn trailing lines are skipped — capture is append-only
    telemetry."""
    live, shadow = [], []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return np.zeros((0, 0)), np.zeros((0, 0))
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if shadow_slot is not None and rec.get("shadow_slot") != shadow_slot:
            continue
        lp, sp = rec.get("live_probs"), rec.get("shadow_probs")
        if lp and sp:
            # One capture record may carry a batch of rows.
            live.extend(lp)
            shadow.extend(sp)
    if not live:
        return np.zeros((0, 0)), np.zeros((0, 0))
    return np.asarray(live, np.float64), np.asarray(shadow, np.float64)


def disagreement_report(
    capture_path: str | None,
    *,
    max_disagreement: float = 0.25,
    shadow_slot: str | None = None,
) -> dict | None:
    """Shadow-stage disagreement report from a mirror capture file, or
    None when no capture exists (the gate treats that as no evidence,
    not as agreement)."""
    if not capture_path or not os.path.exists(capture_path):
        return None
    live, shadow = read_mirror_capture(capture_path, shadow_slot=shadow_slot)
    if len(live) == 0:
        return None
    rep = prediction_disagreement(live, shadow)
    rep["max_disagreement"] = max_disagreement
    if shadow_slot is not None:
        rep["shadow_slot"] = shadow_slot
    rep["exceeded"] = bool(rep["rate"] > max_disagreement)
    return rep
