"""Champion/challenger offline eval harness.

The reference's deploy DAGs promote whatever run has the lowest
``val_loss`` in the tracking store and then walk shadow -> canary ->
full rollout on a timer — nothing ever *evaluates* the challenger
against the deployed champion. This harness is that missing comparison:
load the champion (the currently-deployed package) and the challenger
(the fresh cycle's package or best checkpoint), run both over the SAME
held-out eval split, and return per-example losses plus sliced metrics
— the raw material the statistical gates (:mod:`gates`) turn into a
promote/hold/rollback decision.

Two inference engines over one split:

- ``numpy`` (default) — the serving twin (:mod:`dct_tpu.serving.runtime`):
  bitwise the math the deployed score.py runs, so the gate judges
  exactly what production would serve;
- ``jax`` — the training-side path: rebuild the registry model from the
  checkpoint's self-describing meta and run a jitted batched apply with
  each chunk sharded over the mesh ``data`` axis (the same declarative
  pjit/mesh dispatch the train/eval steps use) — the throughput choice
  for dataset-scale eval splits on accelerator rigs.

The eval split is the trainer's OWN validation split (same
``val_fraction``/seed arithmetic, same gapped contiguous tail for
window families), so champion and challenger are compared on data
neither trained on.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np


class EvalError(RuntimeError):
    """The harness could not produce a comparison (missing package,
    incompatible data, empty split). Gates map this to fail-open/closed."""


# ----------------------------------------------------------------------
# Model loading: both sides of the comparison normalize to
# (serving weights, meta) — the deployed representation.

def model_from_package(package_dir: str) -> tuple[dict, dict]:
    """(weights, meta) of a deploy package (model.npz + model_meta.json).
    Raises :class:`EvalError` for a missing/incomplete package."""
    npz_path = os.path.join(package_dir, "model.npz")
    meta_path = os.path.join(package_dir, "model_meta.json")
    from dct_tpu.serving.runtime import assemble_weights

    try:
        npz = np.load(npz_path)
        # Quantized packages (serving/quant.py) reconstitute to
        # QuantTensor / widened-f32 leaves; plain packages pass through.
        # Every downstream consumer (numpy engine, gates, jax scorer)
        # sees the ORIGINAL keys either way.
        weights = assemble_weights({k: npz[k] for k in npz.files})
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise EvalError(f"Unreadable deploy package {package_dir}: {e}") from e
    return weights, meta


def model_from_checkpoint(ckpt_path: str) -> tuple[dict, dict]:
    """(weights, meta) from a raw .ckpt (the challenger before
    packaging) via the packager's own export path."""
    from dct_tpu.serving.score_gen import weights_from_checkpoint

    try:
        return weights_from_checkpoint(ckpt_path)
    except (OSError, ValueError, KeyError) as e:
        raise EvalError(f"Unreadable checkpoint {ckpt_path}: {e}") from e


def load_model(path: str) -> tuple[dict, dict]:
    """Dispatch: a directory is a deploy package, a file a checkpoint."""
    if os.path.isdir(path):
        return model_from_package(path)
    return model_from_checkpoint(path)


# ----------------------------------------------------------------------
# Eval split: the trainer's validation split, rebuilt from the processed
# parquet with the same arithmetic.

def load_eval_split(
    processed_dir: str,
    meta: dict,
    *,
    val_fraction: float = 0.2,
    seed: int = 42,
    data=None,
) -> tuple[np.ndarray, np.ndarray]:
    """(x, y) of the held-out split shaped for ``meta``'s family.

    Row families get the seeded permutation split's val block; window
    families get the gapped contiguous tail (no row shared with any
    train window) — identical index arithmetic to Trainer.fit, so the
    harness scores data the challenger never trained on. ``data``
    (pre-loaded WeatherArrays) skips the parquet load.
    """
    from dct_tpu.data.dataset import load_processed_dataset
    from dct_tpu.data.pipeline import contiguous_split, train_val_split
    from dct_tpu.serving.runtime import _SEQUENCE_FAMILIES

    if data is None:
        try:
            data = load_processed_dataset(processed_dir)
        except (OSError, ValueError, FileNotFoundError) as e:
            raise EvalError(f"No eval data under {processed_dir}: {e}") from e
    family = meta.get("model", "weather_mlp")
    if family in _SEQUENCE_FAMILIES:
        from dct_tpu.data.windows import make_windows
        from dct_tpu.models.registry import is_causal_model

        seq_len = int(meta["seq_len"])
        windows = make_windows(data, seq_len)
        # Same gap arithmetic as Trainer.fit: a causal family with
        # horizon H supervised train window i on label rows up to
        # i+seq_len+H-1, so the held-out tail must clear that reach too
        # or the harness scores rows the challenger trained on.
        horizon = int(meta.get("horizon", 1) or 1)
        gap = seq_len + (horizon - 1 if is_causal_model(family) else 0)
        _, val_idx = contiguous_split(
            len(windows), val_fraction=val_fraction, gap=gap
        )
        x = np.ascontiguousarray(windows.features[val_idx], np.float32)
        y = np.asarray(windows.labels[val_idx], np.int64)
    else:
        _, val_idx = train_val_split(
            len(data), val_fraction=val_fraction, seed=seed
        )
        x = data.features[val_idx]
        y = np.asarray(data.labels[val_idx], np.int64)
    if len(x) == 0:
        raise EvalError(f"Empty eval split from {processed_dir}")
    return x, y


# ----------------------------------------------------------------------
# Batched apply.

def batched_probs(
    weights: dict,
    meta: dict,
    x: np.ndarray,
    *,
    batch_size: int = 1024,
    engine: str = "numpy",
) -> np.ndarray:
    """[N, C] class probabilities via the chosen engine (chunked: a
    sequence family's attention scores are O(B * S^2), so a whole-split
    forward would OOM at exactly the scale an eval harness exists for).
    Multi-horizon causal heads collapse to the next-step forecast (the
    slice the serving contract scores)."""
    if engine == "jax":
        probs = _batched_probs_jax(weights, meta, x, batch_size)
    else:
        from dct_tpu.serving.runtime import forward_numpy, softmax_numpy

        parts = []
        for start in range(0, len(x), batch_size):
            piece = np.ascontiguousarray(
                x[start:start + batch_size], np.float32
            )
            parts.append(softmax_numpy(forward_numpy(weights, meta, piece)))
        probs = np.concatenate(parts, axis=0)
    if probs.ndim == 3:  # [N, H, C] multi-horizon -> next-step
        probs = probs[:, 0]
    return probs


def _batched_probs_jax(
    weights: dict, meta: dict, x: np.ndarray, batch_size: int
) -> np.ndarray:
    """The training-side inference path: registry model rebuilt from the
    self-describing meta, jitted forward, chunks sharded over the mesh
    ``data`` axis (the same batched-apply idiom as train/steps.py's
    eval body and jobs/predict.py's jax engine). Quantized packages are
    host-dequantized to dense f32 first — the harness's jax engine is a
    correctness path; the resident-int8 throughput variant lives in the
    serving batcher (serving/batching.py)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dct_tpu.config import MeshConfig, ModelConfig
    from dct_tpu.models.registry import (
        get_model, is_causal_model, is_sequence_model,
    )
    from dct_tpu.ops.attention import make_attention_fn
    from dct_tpu.parallel.mesh import batch_sharding, make_mesh

    weights = dense_weights(weights)
    family = meta.get("model", "weather_mlp")
    fields = {f.name for f in dataclasses.fields(ModelConfig)}
    cfg = ModelConfig(name=family, **{
        k: v for k, v in meta.items() if k in fields and k != "name"
    })
    mesh = make_mesh(MeshConfig.from_env())
    input_dim = int(meta["input_dim"])
    if is_sequence_model(family):
        model = get_model(
            cfg, input_dim=input_dim, compute_dtype=jnp.float32,
            attn_fn=make_attention_fn(mesh), mesh=mesh,
        )
    else:
        model = get_model(cfg, input_dim=input_dim, compute_dtype=jnp.float32)
    params = _unflatten_weights(weights, family)
    # The challenger scores under the SAME partition rules the trainer
    # uses (docs/PARALLELISM.md): on a model/seq mesh the params take
    # their tensor-parallel placement instead of replicating — the eval
    # harness can judge a model bigger than one chip's memory. On a
    # pure-data mesh every rule resolves to replication and the math
    # (and bits) are unchanged.
    from dct_tpu.parallel.sharding_rules import (
        match_partition_rules, rules_for_family,
    )
    from jax.sharding import NamedSharding

    param_specs = match_partition_rules(rules_for_family(family), params)
    params = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), param_specs)
    )
    causal = is_causal_model(family)

    @jax.jit
    def forward(p, xb):
        logits = model.apply({"params": p}, xb, train=False)
        if causal:
            logits = logits[:, -1]
        return jax.nn.softmax(logits, axis=-1)

    sharding = batch_sharding(mesh)
    dp = mesh.shape["data"]
    chunk = max(dp, -(-batch_size // dp) * dp)
    parts = []
    for start in range(0, len(x), chunk):
        piece = np.ascontiguousarray(x[start:start + chunk], np.float32)
        real = len(piece)
        pad = (chunk - real) if len(x) > chunk else ((-real) % dp)
        if pad:
            piece = np.concatenate([piece, np.repeat(piece[-1:], pad, axis=0)])
        out = np.asarray(jax.device_get(
            forward(params, jax.device_put(piece, sharding))
        ))
        parts.append(out[:real])
    return np.concatenate(parts, axis=0)


def dense_weights(weights: dict) -> dict:
    """Host-dequantize a serving weights dict: QuantTensor leaves back
    to dense f32, everything else untouched (no copy)."""
    from dct_tpu.serving.runtime import QuantTensor

    return {
        k: v.dequantize() if isinstance(v, QuantTensor) else v
        for k, v in weights.items()
    }


def _unflatten_weights(weights: dict, family: str) -> dict:
    """Invert score_gen's export: '/'-joined flat keys back to the flax
    param tree (sequence families) or w0/b0.. to layers_N (MLP)."""
    if family == "weather_mlp" or not any("/" in k for k in weights):
        # The packager exported the MLP as an anonymous w0/b0.. stack;
        # the registry model's flax auto-names are TorchStyleDense_<i>.
        n_layers = sum(1 for k in weights if k.startswith("w"))
        return {
            f"TorchStyleDense_{i}": {
                "kernel": weights[f"w{i}"], "bias": weights[f"b{i}"],
            }
            for i in range(n_layers)
        }
    tree: dict = {}
    for key, val in weights.items():
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return tree


# ----------------------------------------------------------------------
# Per-example losses + sliced metrics.

@dataclass
class EvalResult:
    """One model's pass over the eval split."""

    name: str
    n: int
    loss_mean: float
    accuracy: float
    per_example_loss: np.ndarray = field(repr=False)
    predictions: np.ndarray = field(repr=False)
    # slice name -> {n, loss, accuracy}; slices are label classes
    # (rain/no-rain for the flagship binary task).
    slices: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "n": self.n,
            "loss_mean": self.loss_mean,
            "accuracy": self.accuracy,
            "slices": self.slices,
        }


def per_example_nll(probs: np.ndarray, labels: np.ndarray) -> np.ndarray:
    """[N] negative log-likelihood of the true class — the paired unit
    the bootstrap/sign tests resample (clipped: a deployed softmax can
    underflow to exactly 0 in float32)."""
    p = np.clip(probs[np.arange(len(labels)), labels], 1e-12, 1.0)
    return -np.log(p).astype(np.float64)


_SLICE_NAMES = {0: "no_rain", 1: "rain"}


def slice_metrics(
    labels: np.ndarray, losses: np.ndarray, preds: np.ndarray
) -> dict:
    """Per-label-class metric slices (the reference task's rain/no-rain
    split; any class count generalizes to label_<c>)."""
    out = {}
    for c in np.unique(labels):
        m = labels == c
        name = _SLICE_NAMES.get(int(c), str(int(c)))
        out[f"label_{name}"] = {
            "n": int(m.sum()),
            "loss": float(losses[m].mean()),
            "accuracy": float((preds[m] == labels[m]).mean()),
        }
    return out


def evaluate_model(
    name: str,
    weights: dict,
    meta: dict,
    x: np.ndarray,
    y: np.ndarray,
    *,
    batch_size: int = 1024,
    engine: str = "numpy",
) -> EvalResult:
    probs = batched_probs(
        weights, meta, x, batch_size=batch_size, engine=engine
    )
    losses = per_example_nll(probs, y)
    preds = np.argmax(probs, axis=-1)
    return EvalResult(
        name=name,
        n=len(y),
        loss_mean=float(losses.mean()),
        accuracy=float((preds == y).mean()),
        per_example_loss=losses,
        predictions=preds,
        slices=slice_metrics(y, losses, preds),
    )


@dataclass
class PairedEval:
    """Champion and challenger over the SAME examples, plus the paired
    per-example loss deltas (champion - challenger: positive = the
    challenger is better on that example)."""

    champion: EvalResult
    challenger: EvalResult
    deltas: np.ndarray = field(repr=False)
    paired: bool = True

    @property
    def mean_delta(self) -> float:
        """Mean loss delta, positive = challenger better. For an
        unpaired (family-upgrade) comparison the per-example deltas are
        empty, but the aggregate difference of means is still
        well-defined — the gates' mean-threshold checks must see it,
        not a constant 0."""
        if len(self.deltas):
            return float(self.deltas.mean())
        return float(self.champion.loss_mean - self.challenger.loss_mean)

    def slice_regressions(self) -> dict:
        """Per-slice loss regression (challenger - champion; positive =
        the challenger is WORSE on that slice)."""
        out = {}
        for name, ch in self.challenger.slices.items():
            cp = self.champion.slices.get(name)
            if cp is not None:
                out[name] = float(ch["loss"] - cp["loss"])
        return out

    def to_dict(self) -> dict:
        return {
            "champion": self.champion.to_dict(),
            "challenger": self.challenger.to_dict(),
            "mean_delta": self.mean_delta,
            "paired": self.paired,
            "slice_regressions": self.slice_regressions(),
        }


def evaluate_pair(
    champion: tuple[dict, dict],
    challenger: tuple[dict, dict],
    processed_dir: str,
    *,
    batch_size: int = 1024,
    engine: str = "numpy",
    val_fraction: float = 0.2,
    seed: int = 42,
    data=None,
) -> PairedEval:
    """Run both models over the held-out split.

    Per-example pairing requires both models to consume the same input
    shape (same family class: row vs window, same seq_len). A family
    upgrade (e.g. MLP champion vs transformer challenger) is evaluated
    UNPAIRED over each model's own view of the same held-out rows —
    the gates then fall back to mean-threshold comparisons only.
    """
    from dct_tpu.serving.runtime import _SEQUENCE_FAMILIES

    cw, cm = champion
    hw, hm = challenger

    def shape_key(meta):
        fam = meta.get("model", "weather_mlp")
        seq = int(meta.get("seq_len", 0)) if fam in _SEQUENCE_FAMILIES else 0
        return (fam in _SEQUENCE_FAMILIES, seq, int(meta.get("input_dim", 0)))

    if shape_key(cm) == shape_key(hm):
        x, y = load_eval_split(
            processed_dir, hm, val_fraction=val_fraction, seed=seed,
            data=data,
        )
        champ_res = evaluate_model(
            "champion", cw, cm, x, y, batch_size=batch_size, engine=engine
        )
        chall_res = evaluate_model(
            "challenger", hw, hm, x, y, batch_size=batch_size, engine=engine
        )
        deltas = champ_res.per_example_loss - chall_res.per_example_loss
        return PairedEval(champ_res, chall_res, deltas, paired=True)
    # Incomparable input shapes: unpaired mean comparison over each
    # model's own windows of the same held-out rows.
    cx, cy = load_eval_split(
        processed_dir, cm, val_fraction=val_fraction, seed=seed, data=data
    )
    hx, hy = load_eval_split(
        processed_dir, hm, val_fraction=val_fraction, seed=seed, data=data
    )
    champ_res = evaluate_model(
        "champion", cw, cm, cx, cy, batch_size=batch_size, engine=engine
    )
    chall_res = evaluate_model(
        "challenger", hw, hm, hx, hy, batch_size=batch_size, engine=engine
    )
    return PairedEval(
        champ_res, chall_res, np.zeros(0, np.float64), paired=False
    )
