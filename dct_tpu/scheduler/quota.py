"""Chip-time quota ledger and the round-lease grant policy.

The scheduler's unit of arbitration is the ROUND LEASE: one tenant's
permission to run one training round on the shared chips. Grants are
decided by **strict priority class, then weighted deficit**:

- among the waiting tenants, the best (lowest) priority class wins;
- within a class, the tenant with the smallest *deficit* —
  ``granted_chip_seconds / weight`` — wins (deterministic name
  tie-break), so long-run granted chip time converges to each tenant's
  ``weight / sum(weights)`` share regardless of per-round duration
  (a tenant whose round ran long — supervisor healing, bigger model —
  simply waits until the others catch up);
- the policy is work-conserving: free capacity is never held back for
  a tenant that is not asking (an only waiter is granted immediately).

Starvation preemption: when the best waiter outranks every running
tenant's class and has waited past ``preempt_wait_s``, the most-junior
running tenant (worst class, then largest deficit) is named the victim.
The scheduler preempts that round through the PR 3 graceful-preemption
path — the trainer finishes its in-flight step, makes the resume
snapshot durable, and the round ends early with zero lost progress —
so priority costs a checkpoint boundary, never work.

The ledger also carries the per-tenant goodput/badput split (useful
seconds vs healing/overhead inside granted leases) and the round-wait
series — the numbers the ``tenant``-labelled metrics and the quota
acceptance check read.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class TenantLedger:
    """One tenant's chip-time account."""

    weight: float = 1.0
    priority_rank: int = 1
    chips: int = 1
    granted_chip_s: float = 0.0
    goodput_s: float = 0.0
    badput_s: float = 0.0
    rounds: int = 0
    preempted_rounds: int = 0
    waits_s: list = field(default_factory=list)

    @property
    def deficit(self) -> float:
        """Granted chip time normalized by weight — the fair-queueing
        virtual time. Lower = more underserved."""
        return self.granted_chip_s / self.weight

    @property
    def goodput_fraction(self) -> float | None:
        total = self.goodput_s + self.badput_s
        return (self.goodput_s / total) if total > 0 else None

    @property
    def mean_wait_s(self) -> float | None:
        return (
            sum(self.waits_s) / len(self.waits_s) if self.waits_s else None
        )


class QuotaLedger:
    """The roster-wide account + grant arithmetic. NOT thread-safe on
    its own — the scheduler mutates it under its grant lock."""

    def __init__(self):
        self.tenants: dict[str, TenantLedger] = {}

    def register(
        self, name: str, *, weight: float, priority_rank: int,
        chips: int = 1,
    ) -> TenantLedger:
        t = TenantLedger(
            weight=float(weight), priority_rank=int(priority_rank),
            chips=max(1, int(chips)),
        )
        self.tenants[name] = t
        return t

    # -- accounting ----------------------------------------------------
    def record_grant(self, name: str, wait_s: float) -> None:
        self.tenants[name].waits_s.append(max(0.0, float(wait_s)))

    def record_release(
        self, name: str, *, wall_s: float, goodput_s: float | None = None,
        preempted: bool = False,
    ) -> dict:
        """Book one finished lease; returns the booked numbers (the
        event/metric payload). ``goodput_s`` is the useful train wall
        inside the lease (None = the whole lease counts as goodput —
        a supervised round with zero restarts)."""
        t = self.tenants[name]
        wall_s = max(0.0, float(wall_s))
        good = wall_s if goodput_s is None else min(wall_s, max(0.0, goodput_s))
        chip_s = wall_s * t.chips
        t.granted_chip_s += chip_s
        t.goodput_s += good
        t.badput_s += wall_s - good
        t.rounds += 1
        if preempted:
            t.preempted_rounds += 1
        return {
            "wall_s": round(wall_s, 3),
            "chip_s": round(chip_s, 3),
            "goodput_s": round(good, 3),
            "badput_s": round(wall_s - good, 3),
        }

    # -- queries -------------------------------------------------------
    def deficit(self, name: str) -> float:
        return self.tenants[name].deficit

    def fair_share(self, name: str, active: list[str] | None = None) -> float:
        """Configured share among ``active`` tenants (default: all)."""
        names = list(active) if active is not None else list(self.tenants)
        total = sum(self.tenants[n].weight for n in names)
        return self.tenants[name].weight / total if total > 0 else 0.0

    def granted_share(self, name: str) -> float | None:
        total = sum(t.granted_chip_s for t in self.tenants.values())
        if total <= 0:
            return None
        return self.tenants[name].granted_chip_s / total

    # -- policy --------------------------------------------------------
    def pick(self, waiters: list[str]) -> str | None:
        """The next grant among ``waiters``: strict priority class, then
        lowest deficit, then name (deterministic)."""
        if not waiters:
            return None
        return min(
            waiters,
            key=lambda n: (
                self.tenants[n].priority_rank, self.tenants[n].deficit, n
            ),
        )

    def preemption_victim(
        self, waiter: str, running: list[str],
    ) -> str | None:
        """The running tenant a starved ``waiter`` may preempt: only
        tenants of a strictly WORSE class are eligible (equal-class
        starvation is resolved by deficit at the next boundary, not by
        preemption); the most junior — worst class, largest deficit —
        pays."""
        wrank = self.tenants[waiter].priority_rank
        victims = [
            n for n in running if self.tenants[n].priority_rank > wrank
        ]
        if not victims:
            return None
        return max(
            victims,
            key=lambda n: (
                self.tenants[n].priority_rank, self.tenants[n].deficit, n
            ),
        )

    def report(self) -> dict:
        """The per-tenant account as one JSON-able dict (``sched.stop``
        payload / scheduler summary)."""
        out = {}
        for name, t in self.tenants.items():
            out[name] = {
                "weight": t.weight,
                "priority_rank": t.priority_rank,
                "chips": t.chips,
                "rounds": t.rounds,
                "preempted_rounds": t.preempted_rounds,
                "granted_chip_s": round(t.granted_chip_s, 3),
                "goodput_s": round(t.goodput_s, 3),
                "badput_s": round(t.badput_s, 3),
                "goodput_fraction": (
                    round(t.goodput_fraction, 4)
                    if t.goodput_fraction is not None else None
                ),
                "mean_wait_s": (
                    round(t.mean_wait_s, 3)
                    if t.mean_wait_s is not None else None
                ),
                "fair_share": round(self.fair_share(name), 4),
                "granted_share": (
                    round(self.granted_share(name), 4)
                    if self.granted_share(name) is not None else None
                ),
            }
        return out
