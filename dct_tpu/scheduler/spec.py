"""Tenant spec: the declarative roster of workloads sharing one pod.

``DCT_TENANTS`` names the roster either INLINE (a JSON array / object —
the value starts with ``[`` or ``{``) or as a path to a ``tenants.json``
file. Shape::

    [
      {"name": "alpha", "family": "weather_mlp", "weight": 2.0,
       "priority": "high",
       "env": {"DCT_LOOP_EPOCHS_PER_ROUND": "1"}},
      {"name": "beta", "weight": 1.0}
    ]

(or ``{"tenants": [...]}``). Fields:

``name``      required; the tenant's identity everywhere — run-dir
              subtree, ``tenant`` metric label, ``DCT_RUN_ID`` suffix,
              default endpoint name. ``[A-Za-z0-9][A-Za-z0-9_-]*``,
              unique per roster.
``family``    registry model name (``DCT_MODEL``); default = the base
              config's family. Tenants of the SAME family share the
              compile/AOT cache (docs/SCHEDULER.md).
``weight``    chip-time quota weight (> 0, default 1.0). Long-run
              granted chip time converges to ``weight / sum(weights)``
              within a priority class.
``priority``  ``high`` | ``normal`` | ``low`` (default ``normal``).
              Strict at grant time: a waiting higher class is granted
              before any lower class; a starved higher class may
              PREEMPT a running lower-class round at the graceful
              checkpoint boundary (``DCT_SCHED_PREEMPT_WAIT_S``).
``env``       per-tenant ``DCT_*`` config overrides (fault drills,
              round quantum, optimizer knobs, ...). Scheduler-assigned
              keys (run dirs, run ID, resume plumbing) are RESERVED —
              a spec naming one is rejected at parse time, not
              silently shadowed.
``endpoint``  local endpoint the tenant promotes into (default: the
              tenant name).

Validation is strict and front-loaded: a malformed roster fails the
scheduler at startup with a :class:`TenantSpecError` naming the clause,
never mid-session with one tenant silently misconfigured.
"""

from __future__ import annotations

import json
import math
import os
import re
from dataclasses import dataclass, field

#: Priority classes, best first. Grant order is strict across classes;
#: quota weights share chip time within a class.
PRIORITIES = ("high", "normal", "low")
_PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_-]{0,63}$")

#: Env keys a tenant spec may NOT override: the scheduler assigns them
#: (isolation would silently break), or they are supervisor plumbing
#: the loop/relauncher owns. ``DCT_SCHED_*`` / ``DCT_TENANTS`` are
#: rejected by prefix — a tenant must not reconfigure its scheduler.
RESERVED_ENV = frozenset({
    "DCT_RUN_ID",
    "DCT_RESUME",
    "DCT_EPOCHS",
    "DCT_PROCESSED_DIR",
    "DCT_MODELS_DIR",
    "DCT_EVENTS_DIR",
    "DCT_HEARTBEAT_DIR",
    "DCT_LOOP_PACKAGES_DIR",
    "DCT_LOOP_ENDPOINT",
    "DCT_STARTUP_RECOVERY_DEBT_S",
})
_RESERVED_PREFIXES = ("DCT_SCHED_", "DCT_TENANTS")


class TenantSpecError(ValueError):
    """A tenant roster that must not reach the grant loop."""


@dataclass
class TenantSpec:
    """One tenant's declaration (module docstring for field semantics)."""

    name: str
    family: str | None = None
    weight: float = 1.0
    priority: str = "normal"
    env: dict = field(default_factory=dict)
    endpoint: str | None = None

    @property
    def priority_rank(self) -> int:
        """Numeric class rank, best (high) = 0 — the grant sort key."""
        return _PRIORITY_RANK[self.priority]

    def resolved_endpoint(self) -> str:
        return self.endpoint or self.name


def _validate_one(raw: dict, index: int) -> TenantSpec:
    where = f"tenant[{index}]"
    if not isinstance(raw, dict):
        raise TenantSpecError(f"{where}: expected an object, got {type(raw).__name__}")
    unknown = set(raw) - {"name", "family", "weight", "priority", "env", "endpoint"}
    if unknown:
        raise TenantSpecError(f"{where}: unknown field(s) {sorted(unknown)}")
    name = raw.get("name")
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise TenantSpecError(
            f"{where}: 'name' must match {_NAME_RE.pattern!r} (got {name!r})"
        )
    where = f"tenant {name!r}"
    family = raw.get("family")
    if family is not None and (not isinstance(family, str) or not family):
        raise TenantSpecError(f"{where}: 'family' must be a non-empty string")
    try:
        weight = float(raw.get("weight", 1.0))
    except (TypeError, ValueError):
        raise TenantSpecError(f"{where}: 'weight' must be a number") from None
    if not (math.isfinite(weight) and weight > 0):
        raise TenantSpecError(f"{where}: 'weight' must be finite and > 0 (got {weight})")
    priority = str(raw.get("priority", "normal")).strip().lower()
    if priority not in PRIORITIES:
        raise TenantSpecError(
            f"{where}: 'priority' must be one of {PRIORITIES} (got {priority!r})"
        )
    env_raw = raw.get("env", {})
    if not isinstance(env_raw, dict):
        raise TenantSpecError(f"{where}: 'env' must be an object of DCT_* strings")
    env: dict[str, str] = {}
    for k, v in env_raw.items():
        if not isinstance(k, str) or not k.startswith("DCT_"):
            raise TenantSpecError(f"{where}: env key {k!r} must be a DCT_* string")
        if k in RESERVED_ENV or any(k.startswith(p) for p in _RESERVED_PREFIXES):
            raise TenantSpecError(
                f"{where}: env key {k!r} is scheduler-assigned (reserved)"
            )
        env[k] = str(v)
    if family is not None and "DCT_MODEL" in env:
        raise TenantSpecError(
            f"{where}: set the family via 'family' OR env DCT_MODEL, not both"
        )
    endpoint = raw.get("endpoint")
    if endpoint is not None and (
        not isinstance(endpoint, str) or not endpoint
    ):
        raise TenantSpecError(f"{where}: 'endpoint' must be a non-empty string")
    return TenantSpec(
        name=name, family=family, weight=weight, priority=priority,
        env=env, endpoint=endpoint,
    )


def parse_tenants(raw: str) -> list[TenantSpec]:
    """Parse a ``DCT_TENANTS`` value (inline JSON or a tenants.json
    path) into a validated roster."""
    if not raw or not raw.strip():
        raise TenantSpecError("DCT_TENANTS is empty: no tenants declared")
    text = raw.strip()
    if not text.startswith(("[", "{")):
        try:
            with open(text) as f:
                text = f.read()
        except OSError as e:
            raise TenantSpecError(f"cannot read tenant spec file {raw!r}: {e}") from e
    try:
        doc = json.loads(text)
    except ValueError as e:
        raise TenantSpecError(f"tenant spec is not valid JSON: {e}") from e
    if isinstance(doc, dict):
        doc = doc.get("tenants")
    if not isinstance(doc, list) or not doc:
        raise TenantSpecError(
            "tenant spec must be a non-empty JSON array "
            "(or {'tenants': [...]})"
        )
    specs = [_validate_one(item, i) for i, item in enumerate(doc)]
    names = [s.name for s in specs]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise TenantSpecError(f"duplicate tenant name(s): {dupes}")
    return specs


def tenants_from_env(env=None) -> list[TenantSpec]:
    """The process's roster, from ``DCT_TENANTS``."""
    raw = (env if env is not None else os.environ).get("DCT_TENANTS", "")
    return parse_tenants(raw)
