"""Multi-tenant workload scheduler: N always-on loops sharing one pod.

The PR 10 :class:`~dct_tpu.continuous.loop.AlwaysOnLoop` babysits ONE
workload; this supervisor runs a roster of them concurrently against
shared hardware. Each tenant is a full always-on loop — its own run
dirs, deploy registry, endpoint slots and ``DCT_RUN_ID`` namespace
under ``<DCT_SCHED_ROOT>/<name>/`` — whose ingest watcher and
promotion evaluator run continuously (host-side work), while TRAINING
ROUNDS time-share the chips through round leases:

- before each round the tenant's loop blocks on the scheduler's grant
  gate; grants follow strict priority class then weighted deficit
  (:mod:`dct_tpu.scheduler.quota`), so chip time converges to the
  configured quota shares at the loop's natural preemption point —
  round boundaries — with no trainer changes;
- a starved higher-class waiter preempts a running lower-class round
  through the PR 3 graceful-preemption path (the trainer checkpoints
  and the round ends early; progress is never lost);
- fault isolation rides the PR 3 exit-code classifier: one tenant's
  crash is healed by ITS round's supervisor; a health-halt or
  restart-budget exhaustion PARKS that tenant (``tenant.parked``)
  while every other tenant's supervisor, watcher and evaluator keep
  running untouched;
- tenants of the same family share the PR 9 compile/AOT cache
  (``DCT_SCHED_SHARED_CACHE``): the second tenant's first round
  deserializes the programs the first one compiled (``cache=hit``).

Observability: ``sched.*`` / ``tenant.*`` events on the scheduler's
log, per-tenant training telemetry on each tenant's own log, and the
per-tenant goodput/badput/chip-time/round-wait ledger published under
a ``tenant`` label on the PR 8 aggregated ``/metrics`` plane
(``DCT_METRICS_DIR``; the terminal snapshot is ``final`` so one scrape
after a drain still reads the session's quota account).

Shutdown: SIGTERM (via ``jobs/scheduler.py``) or ``request_stop()``
drains every tenant — in-flight rounds finish, each loop runs its own
final evaluator sweep — then emits ``sched.stop`` with the quota
report. A relaunch resumes every tenant's trajectory and champion
unchanged, exactly like the single-tenant loop.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from dct_tpu.config import RunConfig
from dct_tpu.scheduler.quota import QuotaLedger
from dct_tpu.scheduler.spec import TenantSpec, TenantSpecError, parse_tenants

#: Round-wait histogram buckets (seconds): lease waits run from
#: sub-second (idle pod) to minutes (behind a healing round).
WAIT_BUCKETS = (0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 240.0, 900.0)

#: Coordinator ports for per-tenant supervised worlds: each tenant's
#: launcher gets its own port so concurrent leases never collide on
#: the rendezvous socket.
_BASE_COORDINATOR_PORT = 29531


@contextlib.contextmanager
def _env_overlay(overlay: dict):
    """Temporarily overlay ``os.environ`` (tenant config construction
    reuses ``RunConfig.from_env`` — THE parser — instead of a second,
    driftable path). Only used serially at scheduler startup."""
    saved = {k: os.environ.get(k) for k in overlay}
    try:
        os.environ.update({k: str(v) for k, v in overlay.items()})
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class TenantRuntime:
    """One tenant's live state inside the scheduler."""

    def __init__(self, spec: TenantSpec, *, root: str, run_id: str):
        self.spec = spec
        self.name = spec.name
        self.root = root
        self.run_id = run_id
        self.env: dict[str, str] = {}
        self.cfg: RunConfig | None = None
        self.loop = None
        self.thread: threading.Thread | None = None
        # pending -> waiting -> running -> idle -> ... -> stopped|parked
        self.state = "pending"
        self.chips = 1
        self.wait_started: float | None = None
        self.lease_t0: float | None = None
        self.preempt_sent = False
        self.summary: dict | None = None
        self.parked_reason: str | None = None


class WorkloadScheduler:
    """The grant loop + tenant supervisors (module docstring).

    ``cfg`` carries the scheduler knobs (``cfg.sched``) and the
    scheduler's OWN observability sinks; ``tenants`` overrides the
    roster (default: parsed from ``cfg.sched.spec`` / ``DCT_TENANTS``).
    ``base_env`` is a dict of DCT_* defaults applied under every
    tenant's config overlay before its own ``env`` (tests and benches
    shrink polls/soaks for the whole roster with it)."""

    def __init__(
        self,
        cfg: RunConfig | None = None,
        *,
        tenants: list[TenantSpec] | None = None,
        base_env: dict | None = None,
        clock=time.time,
    ):
        from dct_tpu.observability.events import current_run_id

        self.cfg = cfg if cfg is not None else RunConfig.from_env()
        self.sched_cfg = self.cfg.sched
        self._clock = clock
        self._base_env = dict(base_env or {})
        self.run_id = self.cfg.obs.run_id or current_run_id()
        self.events = self._event_log()
        self.ledger = QuotaLedger()
        self._cond = threading.Condition()
        self._active: set[str] = set()
        self._stopping = False
        self.stop_reason: str | None = None
        self.total_rounds = 0
        self.preempts = 0
        self._t0: float | None = None
        self._runtimes: dict[str, TenantRuntime] = {}
        self._threads: list[threading.Thread] = []
        self._monitor: threading.Thread | None = None
        self._saved_cache_env: dict | None = None
        self._metrics = None
        self._publisher = None
        self._anomaly_monitor = None
        if tenants is None:
            tenants = parse_tenants(self.sched_cfg.spec)
        if not tenants:
            raise TenantSpecError("scheduler needs at least one tenant")
        self.tenants = tenants

    # -- construction ---------------------------------------------------
    def _event_log(self):
        from dct_tpu.observability.events import EventLog

        path = (
            os.path.join(self.cfg.obs.events_dir, "events.jsonl")
            if self.cfg.obs.enabled and self.cfg.obs.events_dir
            else None
        )
        return EventLog(path, run_id=self.run_id)

    def _init_metrics(self) -> None:
        if not self.cfg.obs.metrics_dir:
            return
        from dct_tpu.observability.aggregate import SnapshotPublisher
        from dct_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        self._metrics = {
            "chip_s": reg.counter(
                "dct_tenant_chip_seconds_total",
                "Chip-seconds granted to each tenant's round leases "
                "(lease wall x tenant chips) — the quota account.",
            ),
            "goodput_s": reg.counter(
                "dct_tenant_goodput_seconds_total",
                "Useful training seconds inside each tenant's leases.",
            ),
            "badput_s": reg.counter(
                "dct_tenant_badput_seconds_total",
                "Lease seconds lost to healing/restarts per tenant.",
            ),
            "rounds": reg.counter(
                "dct_tenant_rounds_total",
                "Round leases completed per tenant, by outcome.",
            ),
            "restarts": reg.counter(
                "dct_tenant_restarts_total",
                "Supervised in-round relaunches per tenant (the PR 3 "
                "healer working inside that tenant's lease).",
            ),
            "wait": reg.histogram(
                "dct_tenant_round_wait_seconds",
                "Seconds each tenant waited for a round lease.",
                buckets=WAIT_BUCKETS,
            ),
            "goodput_frac": reg.gauge(
                "dct_tenant_goodput_fraction",
                "Per-tenant goodput fraction over granted lease time.",
                agg="last",
            ),
            "quota_share": reg.gauge(
                "dct_tenant_quota_share",
                "Configured chip-time share (weight / sum of weights).",
                agg="last",
            ),
            "granted_share": reg.gauge(
                "dct_tenant_granted_share",
                "Actual chip-time share granted so far.",
                agg="last",
            ),
            "parked": reg.gauge(
                "dct_tenant_parked",
                "1 while the tenant is parked (crash budget exhausted "
                "or health halt); 0 otherwise.",
                agg="max",
            ),
            "preempts": reg.counter(
                "dct_sched_preempts_total",
                "Graceful round preemptions, labelled by the preempted "
                "tenant.",
            ),
        }
        self._publisher = SnapshotPublisher(
            reg,
            self.cfg.obs.metrics_dir,
            proc=f"scheduler-{os.getpid()}",
            interval_s=self.cfg.obs.metrics_publish_s,
            clock=self._clock,
        )
        # Telemetry history plane (ISSUE 17): the scheduler watches its
        # tenants' metric history (goodput dips, grad-norm spikes) and
        # assembles incident bundles; None unless DCT_TS_DIR arms it.
        from dct_tpu.observability import detect as _detect

        self._anomaly_monitor = _detect.arm_from_env(
            registry=reg, emit=self.events.emit,
        )

    def _shared_cache_env(self) -> dict:
        """Process-wide compile/AOT cache pinning: same-family tenants
        amortize each other's compiles through ONE store (the trainer
        resolves ``DCT_COMPILE_CACHE_AOT_DIR`` from the live env at fit
        time, so this must be set for the whole session, not only under
        the per-tenant construction overlay). An operator's explicit
        dirs win."""
        if not self.sched_cfg.shared_cache:
            return {}
        root = os.path.abspath(self.sched_cfg.root)
        env = {"DCT_COMPILE_CACHE": os.environ.get("DCT_COMPILE_CACHE") or "on"}
        if not os.environ.get("DCT_COMPILE_CACHE_DIR"):
            env["DCT_COMPILE_CACHE_DIR"] = os.path.join(root, "xla-cache-shared")
        if not os.environ.get("DCT_COMPILE_CACHE_AOT_DIR"):
            env["DCT_COMPILE_CACHE_AOT_DIR"] = os.path.join(root, "aot-shared")
        return env

    def _build_runtime(self, spec: TenantSpec, index: int) -> TenantRuntime:
        from dct_tpu.continuous.loop import AlwaysOnLoop

        troot = os.path.join(self.sched_cfg.root, spec.name)
        rt = TenantRuntime(
            spec, root=troot, run_id=f"{self.run_id}-{spec.name}"
        )
        assigned = {
            "DCT_RUN_ID": rt.run_id,
            "DCT_PROCESSED_DIR": os.path.join(troot, "processed"),
            "DCT_MODELS_DIR": os.path.join(troot, "models"),
            "DCT_EVENTS_DIR": os.path.join(troot, "events"),
            "DCT_HEARTBEAT_DIR": os.path.join(troot, "heartbeats"),
            "DCT_LOOP_PACKAGES_DIR": os.path.join(troot, "packages"),
            "DCT_LOOP_ENDPOINT": spec.resolved_endpoint(),
        }
        if spec.family:
            assigned["DCT_MODEL"] = spec.family
        # Spec validation already rejects reserved keys, so the merge
        # order only decides base_env vs spec.env (tenant wins).
        rt.env = {**self._base_env, **spec.env, **assigned}
        # Stream mode: the N workloads become N streams. Each tenant
        # defaults to its own topic (named after the tenant) under its
        # own root — setdefault, because a tenant may point at a shared
        # log or an explicit topic and that must win.
        if rt.env.get(
            "DCT_INGEST_MODE", os.environ.get("DCT_INGEST_MODE", "poll")
        ) == "stream":
            rt.env.setdefault("DCT_STREAM_DIR", os.path.join(troot, "stream"))
            rt.env.setdefault("DCT_STREAM_TOPIC", spec.name)
        with _env_overlay(rt.env):
            rt.cfg = RunConfig.from_env()
        rt.chips = max(1, int(rt.env.get("DCT_WORLD_SIZE") or
                              os.environ.get("DCT_WORLD_SIZE") or 1))
        if rt.cfg.resilience.fault_spec and rt.cfg.loop.train_mode != "supervised":
            # An inline crash fault is os._exit — it would take the
            # whole scheduler (and every peer tenant) down with it.
            raise TenantSpecError(
                f"tenant {spec.name!r}: DCT_FAULT_SPEC requires "
                "DCT_LOOP_TRAIN_MODE=supervised under the scheduler"
            )
        rt.loop = AlwaysOnLoop(
            rt.cfg,
            round_gate=lambda rt=rt: self._acquire(rt),
            on_round=lambda rec, rt=rt: self._on_round(rt, rec),
            extra_round_env=rt.env,
            launcher_kwargs={
                "coordinator_port": _BASE_COORDINATOR_PORT + index,
            },
        )
        self.ledger.register(
            spec.name, weight=spec.weight,
            priority_rank=spec.priority_rank, chips=rt.chips,
        )
        return rt

    # -- grant machinery ------------------------------------------------
    def _best_waiter(self) -> TenantRuntime | None:
        waiters = [
            t for t in self._runtimes.values() if t.state == "waiting"
        ]
        name = self.ledger.pick([t.name for t in waiters])
        return self._runtimes[name] if name else None

    def _acquire(self, rt: TenantRuntime) -> bool:
        """The tenant loop's round gate: block until a lease is granted
        (True) or the tenant/session is draining (False)."""
        with self._cond:
            rt.state = "waiting"
            rt.wait_started = self._clock()
            self._cond.notify_all()
            while True:
                if self._stopping or rt.loop.stopping:
                    rt.state = "draining"
                    self._cond.notify_all()
                    return False
                if (
                    len(self._active) < self.sched_cfg.concurrent
                    and self._best_waiter() is rt
                ):
                    wait_s = self._clock() - rt.wait_started
                    rt.state = "running"
                    rt.lease_t0 = self._clock()
                    rt.preempt_sent = False
                    self._active.add(rt.name)
                    self.ledger.record_grant(rt.name, wait_s)
                    m = self._metrics
                    if m is not None:
                        m["wait"].observe(wait_s, {"tenant": rt.name})
                    self.events.emit(
                        "sched", "sched.grant",
                        tenant=rt.name, wait_s=round(wait_s, 3),
                        deficit=round(self.ledger.deficit(rt.name), 3),
                        active=sorted(self._active),
                    )
                    return True
                self._cond.wait(0.2)

    def _on_round(self, rt: TenantRuntime, rec: dict) -> None:
        """Lease release at the round boundary (the loop's on_round)."""
        self._release(rt, rec)

    def _release(self, rt: TenantRuntime, rec: dict | None) -> None:
        with self._cond:
            if rt.name not in self._active:
                return
            wall_s = self._clock() - (rt.lease_t0 or self._clock())
            rec = rec or {}
            preempted = bool(rec.get("preempted"))
            outcome = "preempted" if preempted else (
                "error" if rec.get("error") else "ok"
            )
            goodput_s = rec.get("goodput_s")
            if goodput_s is None and outcome != "ok":
                # An errored round (or an inline preemption, whose
                # trainer result is lost) must not book its whole wall
                # as goodput — a chronically failing tenant would read
                # as perfectly efficient. Unmeasured non-ok leases book
                # ZERO goodput; supervised records carry the measured
                # attempt wall either way.
                goodput_s = 0.0
            booked = self.ledger.record_release(
                rt.name, wall_s=wall_s,
                goodput_s=goodput_s, preempted=preempted,
            )
            self._active.discard(rt.name)
            rt.state = "idle"
            self.total_rounds += 1
            restarts = int(rec.get("restarts") or 0)
            m = self._metrics
            if m is not None:
                lab = {"tenant": rt.name}
                m["chip_s"].inc(booked["chip_s"], lab)
                m["goodput_s"].inc(booked["goodput_s"], lab)
                m["badput_s"].inc(booked["badput_s"], lab)
                m["rounds"].inc(1, {"tenant": rt.name, "outcome": outcome})
                if restarts:
                    m["restarts"].inc(restarts, lab)
                frac = self.ledger.tenants[rt.name].goodput_fraction
                if frac is not None:
                    m["goodput_frac"].set(round(frac, 4), lab)
                self._refresh_share_gauges()
                if self._publisher is not None:
                    self._publisher.maybe_publish()
            self.events.emit(
                "sched", "sched.release",
                tenant=rt.name, outcome=outcome, restarts=restarts,
                **booked,
            )
            if (
                self.sched_cfg.max_rounds
                and self.total_rounds >= self.sched_cfg.max_rounds
            ):
                self._request_stop_locked("max_rounds")
            self._cond.notify_all()

    def _refresh_share_gauges(self) -> None:
        m = self._metrics
        if m is None:
            return
        for name in self.ledger.tenants:
            lab = {"tenant": name}
            m["quota_share"].set(
                round(self.ledger.fair_share(name), 4), lab
            )
            gs = self.ledger.granted_share(name)
            if gs is not None:
                m["granted_share"].set(round(gs, 4), lab)

    # -- starvation preemption + budgets (monitor thread) ---------------
    def _monitor_body(self) -> None:
        while True:
            with self._cond:
                if self._stopping:
                    return
                self._cond.wait(self.sched_cfg.poll_s)
                if self._stopping:
                    return
                if (
                    self.sched_cfg.max_wall_s
                    and self._t0 is not None
                    and self._clock() - self._t0 >= self.sched_cfg.max_wall_s
                ):
                    self._request_stop_locked("max_wall_s")
                    return
                victim = self._preemption_check()
            if victim is not None:
                # Outside the lock: preempt_round touches the victim
                # loop's own (independent) synchronization.
                victim.loop.preempt_round()
            if self._publisher is not None:
                self._publisher.maybe_publish()

    def _preemption_check(self) -> TenantRuntime | None:
        """Under the lock: name a victim for a starved, strictly
        higher-class waiter (quota.preemption_victim), once per lease."""
        if self.sched_cfg.preempt_wait_s <= 0:
            return None
        best = self._best_waiter()
        if best is None or best.wait_started is None:
            return None
        if self._clock() - best.wait_started < self.sched_cfg.preempt_wait_s:
            return None
        victim_name = self.ledger.preemption_victim(
            best.name, sorted(self._active)
        )
        if victim_name is None:
            return None
        victim = self._runtimes[victim_name]
        if victim.preempt_sent:
            return None
        victim.preempt_sent = True
        self.preempts += 1
        if self._metrics is not None:
            self._metrics["preempts"].inc(1, {"tenant": victim_name})
        self.events.emit(
            "sched", "sched.preempt",
            tenant=victim_name, waiter=best.name,
            waited_s=round(self._clock() - best.wait_started, 3),
        )
        return victim

    # -- tenant threads --------------------------------------------------
    def _run_tenant(self, rt: TenantRuntime) -> None:
        try:
            rt.summary = rt.loop.run()
        except Exception as e:  # noqa: BLE001 — one tenant's crash must not unwind the pod
            rt.summary = {
                "reason": "runtime_error",
                "error": f"{type(e).__name__}: {e}"[:300],
            }
        finally:
            self._release(rt, {"error": rt.summary and rt.summary.get("error")})
        reason = str(rt.summary.get("reason") or "")
        error = rt.summary.get("error")
        # The loop's terminal reasons carry the PR 3 classifier through:
        # "train_health_halt" / "train_crash" / "train_hang" = the
        # supervisor gave up inside a round; "train_error" = an inline
        # round raised. All park the tenant; a drain does not.
        parked = bool(error) or reason.startswith("train_")
        with self._cond:
            if parked and not self._stopping:
                rt.state = "parked"
                rt.parked_reason = reason or "error"
                classification = (
                    reason[len("train_"):] if reason.startswith("train_")
                    else "error"
                )
                if self._metrics is not None:
                    self._metrics["parked"].set(1, {"tenant": rt.name})
                    if self._publisher is not None:
                        self._publisher.maybe_publish()
                self.events.emit(
                    "tenant", "tenant.parked",
                    tenant=rt.name, classification=classification,
                    reason=reason, error=error,
                )
            else:
                rt.state = "stopped"
            self.events.emit(
                "tenant", "tenant.stop",
                tenant=rt.name, reason=reason or None, error=error,
                rounds=rt.summary.get("rounds"),
                promotions=rt.summary.get("promotions"),
                held=rt.summary.get("held"),
            )
            self._cond.notify_all()

    # -- lifecycle -------------------------------------------------------
    def _restore_cache_env(self) -> None:
        if not self._saved_cache_env:
            return
        for k, v in self._saved_cache_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        self._saved_cache_env = None

    def start(self) -> None:
        """Build every tenant (serially — config construction overlays
        the process env), then start their threads + the monitor."""
        self._t0 = self._clock()
        cache_env = self._shared_cache_env()
        self._saved_cache_env = {
            k: os.environ.get(k) for k in cache_env
        }
        os.environ.update(cache_env)
        try:
            self._init_metrics()
            for i, spec in enumerate(self.tenants):
                self._runtimes[spec.name] = self._build_runtime(spec, i)
        except Exception:
            # A rejected roster must not leak the session's cache pins
            # into the process env.
            self._restore_cache_env()
            raise
        self.events.emit(
            "sched", "sched.start",
            tenants=[
                {
                    "name": s.name, "family": s.family, "weight": s.weight,
                    "priority": s.priority,
                    "endpoint": s.resolved_endpoint(),
                }
                for s in self.tenants
            ],
            concurrent=self.sched_cfg.concurrent,
            preempt_wait_s=self.sched_cfg.preempt_wait_s,
            shared_cache=self.sched_cfg.shared_cache,
            root=self.sched_cfg.root,
        )
        self._refresh_share_gauges()
        for rt in self._runtimes.values():
            self.events.emit(
                "tenant", "tenant.start",
                tenant=rt.name, run_id=rt.run_id, root=rt.root,
                family=rt.cfg.model.name, weight=rt.spec.weight,
                priority=rt.spec.priority, chips=rt.chips,
                train_mode=rt.cfg.loop.train_mode,
            )
            t = threading.Thread(
                target=self._run_tenant, args=(rt,),
                name=f"tenant-{rt.name}", daemon=True,
            )
            rt.thread = t
            t.start()
            self._threads.append(t)
        self._monitor = threading.Thread(
            target=self._monitor_body, name="sched-monitor", daemon=True,
        )
        self._monitor.start()

    def request_stop(self, reason: str = "requested") -> None:
        with self._cond:
            self._request_stop_locked(reason)

    def _request_stop_locked(self, reason: str) -> None:
        if self.stop_reason is None:
            self.stop_reason = reason
        self._stopping = True
        for rt in self._runtimes.values():
            if rt.loop is not None:
                rt.loop.request_stop(f"scheduler_{reason}")
        self._cond.notify_all()

    @property
    def stopping(self) -> bool:
        return self._stopping

    def run(self) -> dict:
        """start() + block until every tenant thread finished (a parked
        tenant's thread HAS finished — parked is a terminal state the
        operator resolves), then drain and return the summary."""
        self.start()
        try:
            while True:
                alive = [t for t in self._threads if t.is_alive()]
                if not alive:
                    break
                # Short joins keep the main thread signal-responsive
                # (jobs/scheduler.py's SIGTERM handler runs here).
                alive[0].join(timeout=0.5)
        finally:
            summary = self.close()
        return summary

    def close(self) -> dict:
        """Drain: stop every loop (in-flight rounds finish), join, emit
        ``sched.stop``, leave a final metrics snapshot behind."""
        with self._cond:
            if self.stop_reason is None:
                self.stop_reason = "completed"
            self._request_stop_locked(self.stop_reason)
        for t in self._threads:
            t.join(timeout=300.0)
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
        summary = self.summary()
        self.events.emit("sched", "sched.stop", **summary)
        self.events.close()
        if self._anomaly_monitor is not None:
            self._anomaly_monitor.close()
        if self._publisher is not None:
            self._refresh_share_gauges()
            self._publisher.close(final=True)
        self._restore_cache_env()
        return summary

    def summary(self) -> dict:
        report = self.ledger.report()
        tenants = {}
        for name, rt in self._runtimes.items():
            entry = dict(report.get(name, {}))
            entry["state"] = rt.state
            if rt.parked_reason:
                entry["parked_reason"] = rt.parked_reason
            if rt.summary:
                entry["promotions"] = rt.summary.get("promotions")
                entry["loop_reason"] = rt.summary.get("reason")
                entry["error"] = rt.summary.get("error")
            tenants[name] = entry
        return {
            "reason": self.stop_reason,
            "wall_s": (
                round(self._clock() - self._t0, 3)
                if self._t0 is not None else None
            ),
            "total_rounds": self.total_rounds,
            "preempts": self.preempts,
            "tenants": tenants,
        }
