"""Multi-tenant workload scheduler (docs/SCHEDULER.md): N always-on
tenants sharing one pod with chip-time quota, priority classes, and
fault isolation at round-lease granularity."""

from dct_tpu.scheduler.quota import QuotaLedger, TenantLedger
from dct_tpu.scheduler.scheduler import TenantRuntime, WorkloadScheduler
from dct_tpu.scheduler.spec import (
    PRIORITIES,
    RESERVED_ENV,
    TenantSpec,
    TenantSpecError,
    parse_tenants,
    tenants_from_env,
)

__all__ = [
    "PRIORITIES",
    "RESERVED_ENV",
    "QuotaLedger",
    "TenantLedger",
    "TenantRuntime",
    "TenantSpec",
    "TenantSpecError",
    "WorkloadScheduler",
    "parse_tenants",
    "tenants_from_env",
]
