"""Experiment tracking: MLflow-compatible client with a local fallback.

The reference logs to an MLflow server (experiment ``weather_forecasting``,
metrics train_loss/val_loss/val_acc, best checkpoint under artifact path
``best_checkpoints``; jobs/train_lightning_ddp.py:92-96,146-164) and the
deploy DAGs *query* that store for the best run ordered by
``metrics.val_loss ASC`` (dags/azure_auto_deploy.py:32-39). That query is the
model-selection database of the whole platform, so the tracking API here is
shaped around it:

- :class:`MlflowTracking` talks to a real MLflow server (import gated — the
  training hosts get mlflow via their image, like Dockerfile.pytorch:20);
- :class:`LocalTracking` is a dependency-free file store with the same
  surface (start_run/log_metrics/log_artifact/search_best_run), used in
  tests, on hermetic TPU-VMs, and as the offline fallback;
- :func:`get_tracker` picks MLflow when importable + configured, local
  otherwise — training never fails because the tracking plane is down.

All methods are no-ops on non-coordinator processes; the reference relies on
Lightning to dedup its two per-rank MLflow clients (SURVEY §7 hard parts),
here the gate is explicit.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from dataclasses import dataclass, field
from typing import Protocol

from dct_tpu.observability import events as _events
from dct_tpu.observability import lineage as _lineage


@dataclass
class RunInfo:
    run_id: str
    experiment: str
    metrics: dict = field(default_factory=dict)  # final value per key
    params: dict = field(default_factory=dict)
    artifact_dir: str | None = None
    # The platform event log's run-correlation ID stamped at start_run
    # time (None for pre-observability runs): lets the deploy side join
    # the model it ships back to the training cycle that produced it.
    run_correlation_id: str | None = None


class TrackingClient(Protocol):
    def start_run(self, params: dict | None = None) -> str: ...
    def log_metrics(self, metrics: dict, step: int) -> None: ...
    def log_artifact(self, local_path: str, artifact_path: str) -> None: ...
    def end_run(self, status: str = "FINISHED") -> None: ...
    def search_best_run(self, metric: str = "val_loss", mode: str = "min") -> RunInfo | None: ...
    def download_artifacts(self, run_id: str, artifact_path: str, dst: str) -> str: ...


def _publish_json(path: str, obj: dict) -> None:
    """Atomic JSON publish (tmp + ``os.replace``): the deploy DAG's
    ``search_best_run`` reads ``meta.json`` from a different process
    while the trainer's ``end_run`` rewrites it — a torn read there
    would silently drop the run from model selection."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2)
    os.replace(tmp, path)


class LocalTracking:
    """File-backed store: <root>/<experiment>/<run_id>/{meta.json,
    metrics.jsonl, artifacts/...}."""

    def __init__(self, root: str | None = None, experiment: str = "weather_forecasting"):
        self.root = root or os.environ.get("DCT_TRACKING_DIR", "mlruns_local")
        self.experiment = experiment
        self._run_id: str | None = None
        self._active = False
        # Persistent metrics.jsonl handle for the active run: the
        # trainer logs thousands of per-step records per run, and an
        # open()/close() pair per record was a measurable slice of the
        # fit() dispatch gap. Each write is still flushed (per-record
        # durability unchanged); the handle closes with the run.
        self._metrics_fh = None

    # -- write surface -------------------------------------------------
    def _run_dir(self, run_id: str) -> str:
        return os.path.join(self.root, self.experiment, run_id)

    def start_run(self, params: dict | None = None) -> str:
        self._close_metrics_fh()
        self._run_id = uuid.uuid4().hex[:16]
        d = self._run_dir(self._run_id)
        os.makedirs(os.path.join(d, "artifacts"), exist_ok=True)
        log = _events.get_default()
        meta = {
            "run_id": self._run_id,
            "experiment": self.experiment,
            # Correlation with the platform event log: the tracking run
            # is one record of a launcher-minted training cycle.
            "run_correlation_id": log.run_id,
            "start_time": time.time(),
            "params": params or {},
            "status": "RUNNING",
        }
        _publish_json(os.path.join(d, "meta.json"), meta)
        self._active = True
        log.emit(
            "tracking", "run_start",
            tracking_run_id=self._run_id, experiment=self.experiment,
        )
        return self._run_id

    def _close_metrics_fh(self) -> None:
        if self._metrics_fh is not None:
            try:
                self._metrics_fh.close()
            except OSError:
                pass
            self._metrics_fh = None

    def log_metrics(self, metrics: dict, step: int) -> None:
        if not self._active:
            return
        if self._metrics_fh is None:
            d = self._run_dir(self._run_id)
            self._metrics_fh = open(
                os.path.join(d, "metrics.jsonl"), "a"
            )
        self._metrics_fh.write(
            json.dumps(
                {"step": int(step), "time": time.time(),
                 **{k: float(v) for k, v in metrics.items()}}
            )
            + "\n"
        )
        self._metrics_fh.flush()

    def log_artifact(self, local_path: str, artifact_path: str) -> None:
        if not self._active:
            return
        d = os.path.join(self._run_dir(self._run_id), "artifacts", artifact_path)
        os.makedirs(d, exist_ok=True)
        # Atomic: the deploy DAG downloads from this dir; a checkpoint
        # must appear complete or not at all.
        dst = os.path.join(d, os.path.basename(local_path))
        tmp = f"{dst}.tmp.{os.getpid()}"
        shutil.copy2(local_path, tmp)
        os.replace(tmp, dst)
        lin = _lineage.get_default()
        if lin.enabled and dst.endswith(".ckpt"):
            # Content addressing links the copy to the original for
            # free: identical bytes -> identical node id, so the
            # tracking-store sighting and the trainer's checkpoint node
            # merge, and the deploy side's ancestry walk crosses the
            # tracking registry without any shared ID plumbing.
            lin.node(
                "checkpoint", path=dst,
                attrs={
                    "tracking_run_id": self._run_id,
                    "artifact_path": artifact_path,
                },
            )

    def end_run(self, status: str = "FINISHED") -> None:
        if not self._active:
            return
        self._close_metrics_fh()
        d = self._run_dir(self._run_id)
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        meta["status"] = status
        meta["end_time"] = time.time()
        _publish_json(os.path.join(d, "meta.json"), meta)
        self._active = False
        _events.get_default().emit(
            "tracking", "run_end",
            tracking_run_id=self._run_id, status=status,
        )

    # -- query surface (the deploy DAGs' selection query) --------------
    def _final_metrics(self, run_dir: str) -> dict:
        path = os.path.join(run_dir, "metrics.jsonl")
        out: dict = {}
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    out.update(
                        {k: v for k, v in rec.items() if k not in ("step", "time")}
                    )
        return out

    def search_best_run(self, metric: str = "val_loss", mode: str = "min") -> RunInfo | None:
        """The analog of mlflow ``search_runs(order_by=["metrics.val_loss
        ASC"], max_results=1)`` (dags/azure_auto_deploy.py:32-35)."""
        exp_dir = os.path.join(self.root, self.experiment)
        if not os.path.isdir(exp_dir):
            return None
        best: RunInfo | None = None
        sign = 1.0 if mode == "min" else -1.0
        for run_id in os.listdir(exp_dir):
            run_dir = os.path.join(exp_dir, run_id)
            meta_path = os.path.join(run_dir, "meta.json")
            if not os.path.isfile(meta_path):
                continue
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("status") != "FINISHED":
                continue
            metrics = self._final_metrics(run_dir)
            if metric not in metrics:
                continue
            if best is None or sign * metrics[metric] < sign * best.metrics[metric]:
                best = RunInfo(
                    run_id=run_id,
                    experiment=self.experiment,
                    metrics=metrics,
                    params=meta.get("params", {}),
                    artifact_dir=os.path.join(run_dir, "artifacts"),
                    run_correlation_id=meta.get("run_correlation_id"),
                )
        return best

    def download_artifacts(self, run_id: str, artifact_path: str, dst: str) -> str:
        src = os.path.join(self._run_dir(run_id), "artifacts", artifact_path)
        if not os.path.isdir(src):
            raise FileNotFoundError(f"No artifacts at {src}")
        out = os.path.join(dst, artifact_path)
        os.makedirs(dst, exist_ok=True)
        if os.path.isdir(out):
            shutil.rmtree(out)
        # Stage the tree beside the destination, then rename: a crash
        # mid-copy leaves only .tmp debris, never a partial artifact
        # dir that a later prepare_package would mistake for complete.
        tmp_out = f"{out}.tmp.{os.getpid()}"
        if os.path.isdir(tmp_out):
            shutil.rmtree(tmp_out)
        shutil.copytree(src, tmp_out)
        os.replace(tmp_out, out)
        return out


class MlflowTracking:
    """Thin adapter over a real MLflow server (import gated).

    Every network op runs under :class:`~dct_tpu.resilience.retry.Retrier`
    (``DCT_RETRY_MAX_ATTEMPTS`` / ``DCT_RETRY_BACKOFF_S``): the tracking
    server is the model-selection database of the platform, but a
    transient registry flake must cost a backoff sleep, not the training
    cycle. Fatal errors (auth, bad request) still raise immediately.
    """

    def __init__(self, tracking_uri: str, experiment: str = "weather_forecasting"):
        import mlflow  # gated: present on training-host images, not required here

        from dct_tpu.resilience.retry import Retrier

        self._mlflow = mlflow
        self._retry = Retrier.from_env()
        mlflow.set_tracking_uri(tracking_uri)
        self._retry(
            lambda: mlflow.set_experiment(experiment), op="set_experiment"
        )
        self.experiment = experiment
        self._run = None

    def start_run(self, params: dict | None = None) -> str:
        self._run = self._retry(self._mlflow.start_run, op="start_run")
        if params:
            self._retry(
                lambda: self._mlflow.log_params(
                    {k: v for k, v in params.items() if v is not None}
                ),
                op="log_params",
            )
        log = _events.get_default()
        try:
            # Queryable correlation on the MLflow side too:
            # tags."dct.run_correlation_id" joins the tracking store to
            # the platform event log.
            self._mlflow.set_tag("dct.run_correlation_id", log.run_id)
        except Exception:  # noqa: BLE001 — tagging is best-effort
            pass
        log.emit(
            "tracking", "run_start",
            tracking_run_id=self._run.info.run_id,
            experiment=self.experiment,
        )
        return self._run.info.run_id

    def log_metrics(self, metrics: dict, step: int) -> None:
        self._retry(
            lambda: self._mlflow.log_metrics(
                {k: float(v) for k, v in metrics.items()}, step=step
            ),
            op="log_metrics",
        )

    def log_artifact(self, local_path: str, artifact_path: str) -> None:
        self._retry(
            lambda: self._mlflow.log_artifact(
                local_path, artifact_path=artifact_path
            ),
            op="log_artifact",
        )

    def end_run(self, status: str = "FINISHED") -> None:
        run_id = self._run.info.run_id if self._run is not None else None
        self._retry(
            lambda: self._mlflow.end_run(status=status), op="end_run"
        )
        _events.get_default().emit(
            "tracking", "run_end", tracking_run_id=run_id, status=status,
        )

    def search_best_run(self, metric: str = "val_loss", mode: str = "min") -> RunInfo | None:
        order = "ASC" if mode == "min" else "DESC"
        exp = self._retry(
            lambda: self._mlflow.get_experiment_by_name(self.experiment),
            op="get_experiment",
        )
        if exp is None:
            return None
        runs = self._retry(
            lambda: self._mlflow.search_runs(
                experiment_ids=[exp.experiment_id],
                order_by=[f"metrics.{metric} {order}"],
                max_results=1,
            ),
            op="search_runs",
        )
        if len(runs) == 0:
            return None
        row = runs.iloc[0]
        rid = None
        try:  # the tag column exists only for observability-era runs
            rid = row.get("tags.dct.run_correlation_id") or None
        except Exception:  # noqa: BLE001 — correlation is best-effort
            pass
        return RunInfo(
            run_id=row["run_id"],
            experiment=self.experiment,
            metrics={metric: float(row[f"metrics.{metric}"])},
            run_correlation_id=rid,
        )

    def download_artifacts(self, run_id: str, artifact_path: str, dst: str) -> str:
        # MlflowClient.download_artifacts was removed in MLflow 2.0; the
        # 2.x API is mlflow.artifacts.download_artifacts (keyword-only).
        from mlflow import artifacts

        return self._retry(
            lambda: artifacts.download_artifacts(
                run_id=run_id, artifact_path=artifact_path, dst_path=dst
            ),
            op="download_artifacts",
        )


class NullTracking:
    """No-op client for non-coordinator processes."""

    def start_run(self, params=None):
        return "null"

    def log_metrics(self, metrics, step):
        pass

    def log_artifact(self, local_path, artifact_path):
        pass

    def end_run(self, status="FINISHED"):
        pass

    def search_best_run(self, metric="val_loss", mode="min"):
        return None

    def download_artifacts(self, run_id, artifact_path, dst):
        raise FileNotFoundError("NullTracking has no artifacts")


def get_tracker(
    *, tracking_uri: str | None, experiment: str, coordinator: bool = True
):
    """MLflow if configured + importable, else local file store; Null on
    non-coordinator ranks (explicit version of Lightning's rank dedup)."""
    if not coordinator:
        return NullTracking()
    if tracking_uri:
        try:
            return MlflowTracking(tracking_uri, experiment)
        except Exception:
            pass  # server down or mlflow absent -> degrade to local store
    return LocalTracking(experiment=experiment)
