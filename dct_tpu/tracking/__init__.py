from dct_tpu.tracking.client import (  # noqa: F401
    TrackingClient,
    LocalTracking,
    MlflowTracking,
    get_tracker,
    RunInfo,
)
