"""Configuration for the TPU-native continuous-training framework.

The reference has no config system at all: hyperparameters are hardcoded
(lr 0.01 at jobs/train_lightning_ddp.py:88, batch 4 at :122, epochs 10 at
:132, split 0.8 at :117, seed 42 at :14, hidden 64 / dropout 0.2 at :57-61)
and the only runtime knobs are env vars interpolated by docker-compose
(MASTER_ADDR/MASTER_PORT/NODE_RANK/WORLD_SIZE at docker-compose.yml:121-124,
MLFLOW_TRACKING_URI at jobs/train_lightning_ddp.py:94).

Here every hyperparameter is a dataclass field whose default equals the
reference value (so a bare ``RunConfig()`` reproduces the parity config) and
every field can be overridden from the environment with a ``DCT_``-prefixed
variable (``DCT_EPOCHS=3``), while the reference's env-var names are honored
unprefixed at the DAG boundary (``WORLD_SIZE``, ``MASTER_ADDR``, ...).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any


def _env(name: str, default: Any, cast: type) -> Any:
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclass
class DataConfig:
    """Filesystem + split contract.

    Mirrors the reference's data contract: Spark writes a parquet *directory*
    ``<processed_dir>/data.parquet`` (jobs/preprocess.py:44-51); the trainer
    reads it, selects ``*_norm`` feature columns and the ``label_encoded``
    target (jobs/train_lightning_ddp.py:37-46), and splits 80/20
    (jobs/train_lightning_ddp.py:117-119).
    """

    processed_dir: str = "data/processed"
    raw_csv: str = "data/raw/weather.csv"
    models_dir: str = "data/models"
    val_fraction: float = 0.2
    feature_suffix: str = "_norm"
    label_column: str = "label_encoded"

    @classmethod
    def from_env(cls) -> "DataConfig":
        c = cls()
        c.processed_dir = _env("DCT_PROCESSED_DIR", c.processed_dir, str)
        c.raw_csv = _env("DCT_RAW_CSV", c.raw_csv, str)
        c.models_dir = _env("DCT_MODELS_DIR", c.models_dir, str)
        c.val_fraction = _env("DCT_VAL_FRACTION", c.val_fraction, float)
        return c


@dataclass
class ModelConfig:
    """Flagship model: the rain classifier MLP.

    Reference architecture: Linear(input_dim, 64) -> ReLU -> Dropout(0.2)
    -> Linear(64, 2)  (jobs/train_lightning_ddp.py:57-62).
    ``input_dim`` is inferred from data at runtime
    (jobs/train_lightning_ddp.py:125), so it is optional here.
    """

    name: str = "weather_mlp"
    input_dim: int | None = None
    hidden_dim: int = 64
    num_classes: int = 2
    dropout: float = 0.2
    # Transformer-family fields (unused by the MLP): window length consumed
    # from the weather stream, encoder width/depth, attention heads.
    seq_len: int = 32
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    # MoE-family fields (weather_moe): expert count, switch-routing
    # capacity factor, load-balance loss weight, dispatch engine
    # ('einsum' | 'sorted' | 'auto' — models/moe.py module docstring).
    n_experts: int = 4
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    moe_dispatch: str = "auto"
    # 'auto' dispatch crossover: elements of the one-hot [kN, E, C]
    # tensor past which the sorted engine is picked. Calibrate on the
    # target chip with bench.py's scaled_moe section.
    moe_auto_threshold: int = 1 << 21
    # 1 = switch (top-1); 2+ = GShard-style top-k with normalized gates.
    router_top_k: int = 1
    # Pipeline-parallel family (weather_transformer_pp): stage count over
    # the mesh's ``pipe`` axis; microbatches default to the stage count.
    n_stages: int = 2
    n_microbatches: int | None = None
    # Causal family (weather_transformer_causal): forecast horizon. 1 =
    # next-step (reference-style single label); H > 1 = DIRECT
    # multi-horizon — every position predicts steps t+1..t+H at once
    # (no autoregressive feedback), labels [B, S, H].
    horizon: int = 1
    # Activation rematerialization for the transformer families: store
    # only block boundaries forward, recompute internals backward — the
    # HBM-for-FLOPs trade (jax.checkpoint) that unlocks long sequences.
    remat: bool = False
    # Causal family: sliding-window local attention — position t attends
    # to the last `attn_window` positions only (0 = full causal). Works
    # on every attention path incl. both SP engines.
    attn_window: int = 0
    # Transformer families: grouped-query attention — K/V carry this many
    # heads (0 = classic MHA, = n_heads), each serving
    # n_heads/n_kv_heads query heads. The KV-bandwidth lever: smaller
    # projections, KV HBM reads divided by the group size in the flash
    # kernel, smaller KV payloads on the SP engines' collectives.
    n_kv_heads: int = 0
    # Transformer families: position encoding — "sincos" (additive fixed
    # table, the default) or "rope" (rotary embeddings applied to q/k
    # inside attention; relative-position structure, the standard choice
    # for long-context extrapolation). RoPE composes with both SP
    # engines (global positions, rotation happens before the seq-sharded
    # op) and with GQA.
    pos_embed: str = "sincos"

    @classmethod
    def from_env(cls) -> "ModelConfig":
        c = cls()
        c.name = _env("DCT_MODEL", c.name, str)
        c.hidden_dim = _env("DCT_HIDDEN_DIM", c.hidden_dim, int)
        c.num_classes = _env("DCT_NUM_CLASSES", c.num_classes, int)
        c.dropout = _env("DCT_DROPOUT", c.dropout, float)
        c.seq_len = _env("DCT_SEQ_LEN", c.seq_len, int)
        c.d_model = _env("DCT_D_MODEL", c.d_model, int)
        c.n_heads = _env("DCT_N_HEADS", c.n_heads, int)
        c.n_layers = _env("DCT_N_LAYERS", c.n_layers, int)
        c.d_ff = _env("DCT_D_FF", c.d_ff, int)
        c.n_experts = _env("DCT_N_EXPERTS", c.n_experts, int)
        c.capacity_factor = _env("DCT_CAPACITY_FACTOR", c.capacity_factor, float)
        c.router_aux_weight = _env(
            "DCT_ROUTER_AUX_WEIGHT", c.router_aux_weight, float
        )
        c.moe_dispatch = _env("DCT_MOE_DISPATCH", c.moe_dispatch, str)
        c.moe_auto_threshold = _env(
            "DCT_MOE_AUTO_THRESHOLD", c.moe_auto_threshold, int
        )
        c.router_top_k = _env("DCT_ROUTER_TOP_K", c.router_top_k, int)
        c.n_stages = _env("DCT_N_STAGES", c.n_stages, int)
        mb = os.environ.get("DCT_N_MICROBATCHES")
        c.n_microbatches = int(mb) if mb else c.n_microbatches
        c.horizon = _env("DCT_HORIZON", c.horizon, int)
        c.remat = _env("DCT_REMAT", c.remat, bool)
        c.attn_window = _env("DCT_ATTN_WINDOW", c.attn_window, int)
        c.n_kv_heads = _env("DCT_N_KV_HEADS", c.n_kv_heads, int)
        c.pos_embed = _env(
            "DCT_POS_EMBED", c.pos_embed, str
        ).strip().lower()
        return c


@dataclass
class TrainConfig:
    """Optimization loop parity config.

    Reference: Adam(lr=0.01) (jobs/train_lightning_ddp.py:88), batch_size 4
    *per rank* (:122), max_epochs 10 (:132), seed 42 (:14),
    log_every_n_steps 5 (:139).
    """

    epochs: int = 10
    # Per-device batch size; the global batch is batch_size * data-parallel
    # size, matching the reference's per-rank DataLoader(batch_size=4).
    batch_size: int = 4
    lr: float = 0.01
    # Optimizer family: adam (parity; weight_decay>0 upgrades to AdamW),
    # adamw, sgd (+momentum), adafactor (factored second moments — the
    # TPU choice when optimizer memory matters), lion. The reference is
    # locked to Adam (jobs/train_lightning_ddp.py:88).
    optimizer: str = "adam"
    momentum: float = 0.0  # sgd only
    # LR schedule: 'constant' (reference parity) or 'cosine'; optional
    # linear warmup. decay_steps 0 = auto (the run's total update count).
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    decay_steps: int = 0
    end_lr_fraction: float = 0.0
    # Decoupled weight decay (AdamW); 0 keeps plain Adam (reference
    # parity — torch.optim.Adam has no decoupled decay).
    weight_decay: float = 0.0
    # Global-norm gradient clipping (Lightning gradient_clip_val
    # semantics); 0 = off, parity default.
    grad_clip_norm: float = 0.0
    seed: int = 42
    log_every_n_steps: int = 5
    # Improvement over the reference (which never resumes,
    # jobs/train_lightning_ddp.py:143): resume from latest full train state.
    resume: bool = False
    # bfloat16 compute on the MXU; params stay f32. Reference is f32 CPU.
    bf16_compute: bool = True
    # lax.scan the whole epoch as one XLA program (one dispatch/epoch).
    # Numerically identical to the eager per-step loop; disable only for
    # datasets too large to stage an epoch in HBM.
    use_scan: bool = True
    # Weight-update (ZeRO-1 style) sharding: split Adam moments' leading
    # dim over the data axis; XLA reduce-scatters grads into the shards
    # and all-gathers updates. Memory win at scale; off for parity.
    shard_opt_state: bool = False
    # FSDP/ZeRO-3: shard the PARAMS (and their Adam moments) over the
    # data axis too; each rank stores 1/N of every weight and XLA
    # all-gathers on use. Layout-only — the trajectory is unchanged.
    shard_params: bool = False
    # Gradient accumulation: microbatches summed per optimizer update
    # (effective batch = batch_size * data_parallel * this) — capability
    # the reference lacks; 1 = parity behavior.
    grad_accum_steps: int = 1
    # Early stopping on val_loss: stop after this many epochs without
    # improvement (0 = off, reference parity — Lightning users pair
    # EarlyStopping with the ModelCheckpoint the reference configures).
    early_stop_patience: int = 0
    early_stop_min_delta: float = 0.0
    # Epochs fused into ONE XLA dispatch (scan path only; 1 = parity).
    # On a slow control plane each epoch costs a host round trip that can
    # dwarf the compute at parity batch sizes; chunking K epochs amortizes
    # it to 1/K. Trade-offs, all chunk-granular: deploy checkpoints and
    # resume snapshots land at chunk boundaries (per-epoch metrics are
    # still returned and logged), early stopping is evaluated per epoch
    # but can only take effect between chunks, and up to 2K epochs of
    # batches are resident in HBM at once (the current span plus the
    # span-ahead prefetch).
    epoch_chunk: int = 1
    # Spans kept in flight ahead of the host loop (scan path only).
    # 1 (default): the next span's host assembly + H2D staging runs on a
    # worker thread while the current span computes, AND the previous
    # span's bookkeeping (metric device_gets, health pass, tracker/event
    # logging, checkpoint writes) overlaps the current span's compute —
    # the dispatched-span results are consumed one span late, so at most
    # one span of device work is in flight past the bookkeeping. Costs
    # one extra resident copy of the train state (the fused step cannot
    # donate its input state while the checkpoint tier still reads it).
    # 0: strictly serial — assemble, dispatch, block, bookkeep, repeat
    # (restores state donation; use when HBM is the binding constraint).
    # Values above 1 clamp to 1 (deeper pipelines would let early-stop /
    # health decisions trail arbitrarily far behind the device).
    # Auto-disabled while a DCT_FAULT_SPEC is armed so fault-injection
    # drills observe the exact serial crash/checkpoint ordering.
    prefetch_spans: int = 1

    @classmethod
    def from_env(cls) -> "TrainConfig":
        c = cls()
        c.epochs = _env("DCT_EPOCHS", c.epochs, int)
        c.batch_size = _env("DCT_BATCH_SIZE", c.batch_size, int)
        c.lr = _env("DCT_LR", c.lr, float)
        c.optimizer = _env("DCT_OPTIMIZER", c.optimizer, str)
        c.momentum = _env("DCT_MOMENTUM", c.momentum, float)
        c.lr_schedule = _env("DCT_LR_SCHEDULE", c.lr_schedule, str)
        c.warmup_steps = _env("DCT_WARMUP_STEPS", c.warmup_steps, int)
        c.decay_steps = _env("DCT_DECAY_STEPS", c.decay_steps, int)
        c.end_lr_fraction = _env(
            "DCT_END_LR_FRACTION", c.end_lr_fraction, float
        )
        c.weight_decay = _env("DCT_WEIGHT_DECAY", c.weight_decay, float)
        c.grad_clip_norm = _env("DCT_GRAD_CLIP_NORM", c.grad_clip_norm, float)
        c.seed = _env("DCT_SEED", c.seed, int)
        c.log_every_n_steps = _env("DCT_LOG_EVERY_N_STEPS", c.log_every_n_steps, int)
        c.resume = _env("DCT_RESUME", c.resume, bool)
        c.bf16_compute = _env("DCT_BF16_COMPUTE", c.bf16_compute, bool)
        c.use_scan = _env("DCT_USE_SCAN", c.use_scan, bool)
        c.shard_opt_state = _env("DCT_SHARD_OPT_STATE", c.shard_opt_state, bool)
        c.shard_params = _env("DCT_SHARD_PARAMS", c.shard_params, bool)
        c.grad_accum_steps = _env("DCT_GRAD_ACCUM_STEPS", c.grad_accum_steps, int)
        c.early_stop_patience = _env(
            "DCT_EARLY_STOP_PATIENCE", c.early_stop_patience, int
        )
        c.early_stop_min_delta = _env(
            "DCT_EARLY_STOP_MIN_DELTA", c.early_stop_min_delta, float
        )
        c.epoch_chunk = _env("DCT_EPOCH_CHUNK", c.epoch_chunk, int)
        c.prefetch_spans = _env("DCT_PREFETCH_SPANS", c.prefetch_spans, int)
        return c


@dataclass
class MeshConfig:
    """Device-mesh layout.

    The reference's only parallelism is 2-rank DDP over a Docker bridge
    (docker-compose.yml:115-151). Here parallelism is a named mesh: ``data``
    is the DDP analog; ``model`` (tensor) and ``seq`` (sequence/context) are
    first-class axes used by the transformer family and ring attention.
    Sizes of -1 mean "all remaining devices".
    """

    data: int = -1
    model: int = 1
    seq: int = 1
    pipe: int = 1

    @classmethod
    def from_env(cls) -> "MeshConfig":
        c = cls()
        c.data = _env("DCT_MESH_DATA", c.data, int)
        c.model = _env("DCT_MESH_MODEL", c.model, int)
        c.seq = _env("DCT_MESH_SEQ", c.seq, int)
        c.pipe = _env("DCT_MESH_PIPE", c.pipe, int)
        return c


@dataclass
class DistributedConfig:
    """Multi-process rendezvous, honoring the reference's env contract.

    The reference rendezvous is Lightning's LightningEnvironment reading
    MASTER_ADDR / MASTER_PORT / NODE_RANK / WORLD_SIZE
    (docker-compose.yml:121-124,140-143) to form a gloo TCP store at
    pytorch-master:29500. The TPU-native analog is
    ``jax.distributed.initialize(coordinator_address, num_processes,
    process_id)``; we derive its arguments from the same env vars so the
    orchestration layer (DAGs / compose files) carries over unchanged.
    """

    coordinator_address: str | None = None
    num_processes: int = 1
    process_id: int = 0

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        c = cls()
        # Native names win; reference-compat names are the fallback.
        world = os.environ.get("DCT_NUM_PROCESSES") or os.environ.get("WORLD_SIZE")
        rank = os.environ.get("DCT_PROCESS_ID") or os.environ.get("NODE_RANK")
        # "" means unset, consistently with world/rank above — launchers
        # blank these vars to neutralize inherited overrides.
        coord = os.environ.get("DCT_COORDINATOR_ADDRESS") or None
        if coord is None:
            master_addr = os.environ.get("MASTER_ADDR")
            master_port = os.environ.get("MASTER_PORT", "29500")
            if master_addr:
                coord = f"{master_addr}:{master_port}"
        c.coordinator_address = coord
        c.num_processes = int(world) if world else 1
        c.process_id = int(rank) if rank else 0
        return c


@dataclass
class TrackingConfig:
    """Experiment tracking contract.

    Reference: MLFlowLogger(experiment_name="weather_forecasting",
    tracking_uri=env MLFLOW_TRACKING_URI default http://mlflow-server:5000)
    (jobs/train_lightning_ddp.py:92-96); best checkpoint uploaded to artifact
    path "best_checkpoints" from rank 0 (:146-164). Those names are load-
    bearing: the deploy DAGs query them (dags/azure_auto_deploy.py:32-39).
    """

    experiment: str = "weather_forecasting"
    tracking_uri: str | None = None
    artifact_path: str = "best_checkpoints"

    @classmethod
    def from_env(cls) -> "TrackingConfig":
        c = cls()
        c.experiment = _env("DCT_EXPERIMENT", c.experiment, str)
        c.tracking_uri = os.environ.get("MLFLOW_TRACKING_URI", c.tracking_uri)
        return c


@dataclass
class ProfileConfig:
    """Tracing/profiling window (absent in the reference — SURVEY §5.1:
    TensorBoard is installed but nothing writes it; the pipeline DAG's logs
    check warns on an empty dir, dags/pipeline.py:229-240).

    When enabled, the coordinator traces ONE epoch with ``jax.profiler``
    into a TensorBoard-compatible directory; per-epoch throughput metrics
    are logged to the tracker regardless.
    """

    enabled: bool = False
    trace_dir: str = "logs/profile"
    # Which epoch to trace (0-based). Default 1: epoch 0 pays compilation,
    # which would swamp the steady-state timeline.
    epoch: int = 1
    # On-demand flight recorder (observability/capture.py): touch this
    # file (or write a seconds value into it) and every rank starts a
    # jax.profiler capture at its next span boundary — no restart, no
    # pre-planned window. Each distinct file mtime fires once. "" turns
    # the file trigger off (SIGUSR2 still works when sigusr2 is set).
    trigger_path: str = "logs/profile.trigger"
    # Default capture length (seconds) when the trigger carries none;
    # the capture stops at the first span boundary past the deadline.
    capture_s: float = 5.0
    # Arm SIGUSR2 as a capture trigger (main thread only; worker-thread
    # trainers degrade to the file trigger automatically).
    sigusr2: bool = True

    @classmethod
    def from_env(cls) -> "ProfileConfig":
        c = cls()
        c.enabled = _env("DCT_PROFILE", c.enabled, bool)
        c.trace_dir = _env("DCT_TRACE_DIR", c.trace_dir, str)
        c.epoch = _env("DCT_PROFILE_EPOCH", c.epoch, int)
        c.trigger_path = _env("DCT_PROFILE_TRIGGER", c.trigger_path, str)
        c.capture_s = _env("DCT_PROF_CAPTURE_S", c.capture_s, float)
        c.sigusr2 = _env("DCT_PROF_SIGUSR2", c.sigusr2, bool)
        return c


@dataclass
class ObservabilityConfig:
    """The operator plane (dct_tpu.observability): structured event log,
    goodput/badput ledger, rank heartbeats, Prometheus metrics dump.

    ON by default — observability that must be remembered per-run is
    observability that is absent during the incident. All sinks live
    under ``logs/`` (gitignored) unless redirected; every writer
    degrades to a no-op on OS errors, so a full disk never fails a run.

    ``run_id`` is the run-correlation ID stamped on every event record:
    normally minted by the DAG/launcher and delivered via ``DCT_RUN_ID``
    so all ranks of one continuous-training cycle agree; a process that
    was never launched mints its own.
    """

    enabled: bool = True
    events_dir: str = "logs/events"
    run_id: str | None = None
    heartbeat_dir: str = "logs/heartbeats"
    # Same-phase heartbeats inside this window are throttled (writes are
    # tiny, but per-step beats must not become an I/O hot loop).
    heartbeat_interval: float = 5.0
    # A heartbeat older than this marks its rank stalled to the monitor.
    heartbeat_stall_seconds: float = 120.0
    # End-of-run Prometheus text dump; "" = <events_dir>/train_metrics.prom.
    metrics_path: str = ""
    # Distributed-tracing span files; "" = <events_dir>/spans. The
    # trace_id is the run-correlation ID; parent spans propagate to
    # child processes via DCT_SPAN_ID (observability/spans.py).
    spans_dir: str = ""
    # Training-health policy (observability/health.py): halt the run on
    # a non-finite loss / on a loss-or-grad-norm spike, or (default)
    # warn via health.* events and keep training. The z-score detector
    # compares each step against a rolling window of recent history.
    halt_on_nan: bool = False
    halt_on_spike: bool = False
    spike_zscore: float = 8.0
    spike_window: int = 16
    # Telemetry write batching (events + spans; observability/buffered.py).
    # 0 = write-through: every record reaches the OS before emit returns
    # (the historical per-record durability, minus the open()-per-record
    # syscall tax — a persistent handle is kept either way). > 0 = batch
    # appends for up to this many seconds (or telemetry_flush_records
    # lines), flushed on trainer exit paths, fault firing, and atexit;
    # a SIGKILL can cost at most that window of telemetry. Heartbeat
    # files are NEVER buffered — a buffered liveness signal is a dead
    # one — they are throttled by heartbeat_interval instead.
    telemetry_flush_s: float = 0.25
    telemetry_flush_records: int = 128
    # Cross-process metrics plane (observability/{metrics,aggregate}.py;
    # docs/OBSERVABILITY.md "Metrics plane"): processes publish atomic
    # registry snapshots under this dir and any /metrics scrape merges
    # the live siblings into fleet totals. "" = plane off (the serving
    # CLI jobs/serve.py arms logs/metrics by default; library-built
    # servers stay local-only unless the env opts in).
    metrics_dir: str = ""
    # Min seconds between snapshot publishes per process (the hot-path
    # throttle; an idle-process timer republishes on the same cadence).
    metrics_publish_s: float = 2.0
    # A LIVE process's snapshot older than this stops counting (dead
    # pids drop immediately; `final` batch snapshots never age out).
    metrics_stale_s: float = 30.0
    # SLO monitoring over the aggregated series (observability/slo.py
    # grammar): e.g. "availability:0.999;latency:0.25@0.95;goodput:0.5;
    # freshness:3600". Evaluated at scrape time by whichever process
    # answers /metrics; alerts emit slo.alert events + dct_slo_* gauges.
    slo_spec: str = "availability:0.999;latency:0.5@0.95"
    # Multi-window burn-rate rule: alert only when BOTH windows burn
    # error budget above the threshold (1.0 = exactly budget rate).
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    slo_burn_threshold: float = 1.0
    # Telemetry history plane (observability/timeseries.py; ISSUE 17):
    # every SnapshotPublisher also appends its snapshots to per-process
    # segment files under this dir. "" = plane off (the default — the
    # instantaneous metrics plane is untouched).
    ts_dir: str = ""
    # Comma-separated fnmatch patterns selecting the recorded families.
    ts_families: str = ""
    # Segment seal thresholds: points per raw segment / max segment age.
    ts_seg_points: int = 240
    ts_seg_s: float = 600.0
    # Active-segment republish cadence (appends between flushes are
    # memory-only — the armed-publish overhead budget lives here).
    ts_flush_s: float = 10.0
    # Sealed raw segments older than downsample_s fold into the coarse
    # ds tier (ds_res_s-wide bins); anything older than retention_s is
    # deleted at compaction time.
    ts_retention_s: float = 10800.0
    ts_downsample_s: float = 900.0
    ts_ds_res_s: float = 60.0
    # Online anomaly detection over the history store (detect.py):
    # EWMA/z-score change detection, edge-triggered like the SLO
    # monitor. Armed only when ts_dir is set.
    anomaly: bool = True
    anomaly_z: float = 4.0
    anomaly_alpha: float = 0.3
    anomaly_min_points: int = 8
    anomaly_window_s: float = 30.0
    anomaly_poll_s: float = 2.0
    # Auto-assembled incident bundles (incident.py): anomaly / SLO
    # triggers snapshot the surrounding window + events + lineage into
    # incidents/<stamp>-<signal>/. "" dir = sibling of ts_dir.
    incident: bool = True
    incident_dir: str = ""
    incident_window_s: float = 120.0
    incident_cooldown_s: float = 300.0
    # Fire the PR 14 flight recorder into each bundle (profile/).
    incident_profile: bool = False
    incident_profile_s: float = 2.0

    @classmethod
    def from_env(cls) -> "ObservabilityConfig":
        c = cls()
        c.enabled = _env("DCT_OBSERVABILITY", c.enabled, bool)
        c.events_dir = _env("DCT_EVENTS_DIR", c.events_dir, str)
        c.run_id = os.environ.get("DCT_RUN_ID") or c.run_id
        c.heartbeat_dir = _env("DCT_HEARTBEAT_DIR", c.heartbeat_dir, str)
        c.heartbeat_interval = _env(
            "DCT_HEARTBEAT_INTERVAL", c.heartbeat_interval, float
        )
        c.heartbeat_stall_seconds = _env(
            "DCT_HEARTBEAT_STALL_SECONDS", c.heartbeat_stall_seconds, float
        )
        c.metrics_path = _env("DCT_METRICS_PROM", c.metrics_path, str)
        c.spans_dir = _env("DCT_SPANS_DIR", c.spans_dir, str)
        c.halt_on_nan = _env("DCT_HALT_ON_NAN", c.halt_on_nan, bool)
        c.halt_on_spike = _env("DCT_HALT_ON_SPIKE", c.halt_on_spike, bool)
        c.spike_zscore = _env("DCT_SPIKE_ZSCORE", c.spike_zscore, float)
        c.spike_window = _env("DCT_SPIKE_WINDOW", c.spike_window, int)
        c.telemetry_flush_s = _env(
            "DCT_TELEMETRY_FLUSH_S", c.telemetry_flush_s, float
        )
        c.telemetry_flush_records = _env(
            "DCT_TELEMETRY_FLUSH_RECORDS", c.telemetry_flush_records, int
        )
        c.metrics_dir = _env("DCT_METRICS_DIR", c.metrics_dir, str)
        c.metrics_publish_s = _env(
            "DCT_METRICS_PUBLISH_S", c.metrics_publish_s, float
        )
        c.metrics_stale_s = _env(
            "DCT_METRICS_STALE_S", c.metrics_stale_s, float
        )
        c.slo_spec = _env("DCT_SLO_SPEC", c.slo_spec, str)
        c.slo_fast_window_s = _env(
            "DCT_SLO_FAST_WINDOW_S", c.slo_fast_window_s, float
        )
        c.slo_slow_window_s = _env(
            "DCT_SLO_SLOW_WINDOW_S", c.slo_slow_window_s, float
        )
        c.slo_burn_threshold = _env(
            "DCT_SLO_BURN_THRESHOLD", c.slo_burn_threshold, float
        )
        c.ts_dir = _env("DCT_TS_DIR", c.ts_dir, str)
        c.ts_families = _env("DCT_TS_FAMILIES", c.ts_families, str)
        c.ts_seg_points = _env("DCT_TS_SEG_POINTS", c.ts_seg_points, int)
        c.ts_seg_s = _env("DCT_TS_SEG_S", c.ts_seg_s, float)
        c.ts_flush_s = _env("DCT_TS_FLUSH_S", c.ts_flush_s, float)
        c.ts_retention_s = _env("DCT_TS_RETENTION_S", c.ts_retention_s, float)
        c.ts_downsample_s = _env(
            "DCT_TS_DOWNSAMPLE_S", c.ts_downsample_s, float
        )
        c.ts_ds_res_s = _env("DCT_TS_DS_RES_S", c.ts_ds_res_s, float)
        c.anomaly = _env("DCT_ANOMALY", c.anomaly, bool)
        c.anomaly_z = _env("DCT_ANOMALY_Z", c.anomaly_z, float)
        c.anomaly_alpha = _env("DCT_ANOMALY_ALPHA", c.anomaly_alpha, float)
        c.anomaly_min_points = _env(
            "DCT_ANOMALY_MIN_POINTS", c.anomaly_min_points, int
        )
        c.anomaly_window_s = _env(
            "DCT_ANOMALY_WINDOW_S", c.anomaly_window_s, float
        )
        c.anomaly_poll_s = _env(
            "DCT_ANOMALY_POLL_S", c.anomaly_poll_s, float
        )
        c.incident = _env("DCT_INCIDENT", c.incident, bool)
        c.incident_dir = _env("DCT_INCIDENT_DIR", c.incident_dir, str)
        c.incident_window_s = _env(
            "DCT_INCIDENT_WINDOW_S", c.incident_window_s, float
        )
        c.incident_cooldown_s = _env(
            "DCT_INCIDENT_COOLDOWN_S", c.incident_cooldown_s, float
        )
        c.incident_profile = _env(
            "DCT_INCIDENT_PROFILE", c.incident_profile, bool
        )
        c.incident_profile_s = _env(
            "DCT_INCIDENT_PROFILE_S", c.incident_profile_s, float
        )
        return c


@dataclass
class ResilienceConfig:
    """Self-healing knobs (dct_tpu.resilience; docs/ROBUSTNESS.md):
    supervised relaunch-and-resume, graceful preemption, fault
    injection, and transient-network retry policy.

    The supervisor-side knobs (``max_restarts``, backoff) govern
    whoever babysits the world — :meth:`LocalProcessLauncher.supervise`
    or the ``python -m dct_tpu.resilience.supervise`` CLI; the rank-side
    knobs (``graceful_preemption``, ``fault_spec``) govern the trainer.
    ``startup_debt_s`` is supervisor-set plumbing
    (``DCT_STARTUP_RECOVERY_DEBT_S``): the wall clock lost to the failed
    attempts, booked by the relaunched trainer as ``startup_recovery``
    badput so the cycle's goodput accounting stays honest.
    """

    max_restarts: int = 2
    restart_backoff_s: float = 5.0
    restart_backoff_factor: float = 2.0
    restart_jitter: float = 0.1
    preempt_grace_s: float = 30.0
    # Honor SIGTERM cooperatively: finish the in-flight step, save a
    # resume checkpoint, exit EXIT_PREEMPTED (75). Off = die like the
    # reference does.
    graceful_preemption: bool = True
    # Deterministic fault plan (resilience.faults grammar), e.g.
    # "crash@rank1:epoch2,slow_save". Empty = no faults.
    fault_spec: str = ""
    fault_sleep_s: float = 3.0
    # Transient-network retry policy (tracking client, deploy rollout).
    retry_max_attempts: int = 3
    retry_backoff_s: float = 0.5
    # Supervisor-set: lost wall clock to book as startup_recovery badput.
    startup_debt_s: float = 0.0

    @classmethod
    def from_env(cls) -> "ResilienceConfig":
        c = cls()
        c.max_restarts = _env("DCT_MAX_RESTARTS", c.max_restarts, int)
        c.restart_backoff_s = _env(
            "DCT_RESTART_BACKOFF_S", c.restart_backoff_s, float
        )
        c.restart_backoff_factor = _env(
            "DCT_RESTART_BACKOFF_FACTOR", c.restart_backoff_factor, float
        )
        c.restart_jitter = _env("DCT_RESTART_JITTER", c.restart_jitter, float)
        c.preempt_grace_s = _env(
            "DCT_PREEMPT_GRACE_S", c.preempt_grace_s, float
        )
        c.graceful_preemption = _env(
            "DCT_GRACEFUL_PREEMPTION", c.graceful_preemption, bool
        )
        c.fault_spec = _env("DCT_FAULT_SPEC", c.fault_spec, str)
        c.fault_sleep_s = _env("DCT_FAULT_SLEEP_S", c.fault_sleep_s, float)
        c.retry_max_attempts = _env(
            "DCT_RETRY_MAX_ATTEMPTS", c.retry_max_attempts, int
        )
        c.retry_backoff_s = _env(
            "DCT_RETRY_BACKOFF_S", c.retry_backoff_s, float
        )
        c.startup_debt_s = _env(
            "DCT_STARTUP_RECOVERY_DEBT_S", c.startup_debt_s, float
        )
        return c


@dataclass
class EvaluationConfig:
    """Continuous-evaluation knobs (dct_tpu.evaluation; docs/EVALUATION.md):
    the champion/challenger offline eval harness, the statistical
    promotion gates between rollout stages, and the drift detectors.

    The gate's null hypothesis is "the challenger is NOT worse": by
    default a cycle promotes unless the evidence says it regressed
    (``require_improvement`` flips that to "promote only on proven
    improvement"). All stochastic machinery (the paired bootstrap) is
    seeded from ``seed`` so a gate decision is reproducible from its
    evidence. ``DCT_DRIFT_THRESHOLD`` (the ETL-side stats gate in
    etl/preprocess.py) is a different, older knob; the deploy-side
    detectors here use PSI/KS against the snapshot stamped into the
    deploy package.
    """

    # Consult a PromotionGate between rollout stages (shadow -> canary
    # -> full). Off = the reference's timer-only walk.
    gate_enabled: bool = True
    # Mean per-example loss delta (champion - challenger) the challenger
    # must exceed to count as an improvement.
    min_improvement: float = 0.0
    # Mean regression tolerated before the gate blocks (challenger mean
    # loss may exceed champion's by at most this).
    max_regression: float = 0.0
    # One-sided confidence required of the paired bootstrap before a
    # delta counts as evidence (0.95 = the regression must be outside
    # the bootstrap's 95% band).
    confidence: float = 0.95
    bootstrap_samples: int = 1000
    # Bootstrap RNG seed: gate decisions must be deterministic.
    seed: int = 42
    # Worst tolerated per-slice loss regression (e.g. the rain slice may
    # not get this much worse even if the aggregate improved).
    max_slice_regression: float = 0.25
    # Promote only on statistically-significant improvement (default:
    # promote unless significantly worse — continuous-training default).
    require_improvement: bool = False
    # Examples per forward pass in the offline harness.
    eval_batch: int = 1024
    # 'numpy' = the serving twin (identical math to the deployed
    # score.py); 'jax' = jitted batched apply sharded over the mesh
    # data axis (the training-side inference path, for dataset-scale
    # eval splits on accelerator rigs).
    engine: str = "numpy"
    # Missing prerequisites (no champion, no eval data, unreadable
    # package): promote with a warning (True) or hold (False). A real
    # failing evaluation always blocks regardless.
    fail_open: bool = True
    # Gate-decision ledger consumed by /metrics; "" = <events_dir>/
    # gate_ledger.json.
    ledger_path: str = ""
    # Drift detectors: PSI above this flags a feature (industry rule of
    # thumb: 0.1 moderate, 0.2 major); KS D-statistic threshold; bins
    # for the stamped quantile snapshot; shadow-stage prediction
    # disagreement rate above which the shadow->canary gate holds.
    psi_threshold: float = 0.2
    ks_threshold: float = 0.15
    drift_bins: int = 10
    max_disagreement: float = 0.25

    @classmethod
    def from_env(cls) -> "EvaluationConfig":
        c = cls()
        c.gate_enabled = _env("DCT_GATE", c.gate_enabled, bool)
        c.min_improvement = _env(
            "DCT_GATE_MIN_IMPROVEMENT", c.min_improvement, float
        )
        c.max_regression = _env(
            "DCT_GATE_MAX_REGRESSION", c.max_regression, float
        )
        c.confidence = _env("DCT_GATE_CONFIDENCE", c.confidence, float)
        c.bootstrap_samples = _env(
            "DCT_GATE_BOOTSTRAP", c.bootstrap_samples, int
        )
        c.seed = _env("DCT_GATE_SEED", c.seed, int)
        c.max_slice_regression = _env(
            "DCT_GATE_MAX_SLICE_REGRESSION", c.max_slice_regression, float
        )
        c.require_improvement = _env(
            "DCT_GATE_REQUIRE_IMPROVEMENT", c.require_improvement, bool
        )
        c.eval_batch = _env("DCT_GATE_EVAL_BATCH", c.eval_batch, int)
        c.engine = _env("DCT_GATE_ENGINE", c.engine, str).strip().lower()
        c.fail_open = _env("DCT_GATE_FAIL_OPEN", c.fail_open, bool)
        c.ledger_path = _env("DCT_GATE_LEDGER", c.ledger_path, str)
        c.psi_threshold = _env("DCT_DRIFT_PSI", c.psi_threshold, float)
        c.ks_threshold = _env("DCT_DRIFT_KS", c.ks_threshold, float)
        c.drift_bins = _env("DCT_DRIFT_BINS", c.drift_bins, int)
        c.max_disagreement = _env(
            "DCT_DRIFT_MAX_DISAGREEMENT", c.max_disagreement, float
        )
        return c


@dataclass
class ServingConfig:
    """High-throughput serving tier knobs (dct_tpu.serving;
    docs/SERVING.md): the dynamic micro-batcher behind both HTTP server
    modes, the scoring worker pool, and the load-generation bench.

    The batcher merges compatible in-flight requests into one stacked
    forward — up to ``max_batch`` rows, waiting at most
    ``batch_window_ms`` past the oldest queued request for co-arrivals.
    ``batch_window_ms=0`` (default) is purely opportunistic: whatever
    is queued when a worker frees up merges, and an idle server adds
    zero latency; raise it to trade p50 for bigger batches under
    open-loop trickle traffic. Batched scoring is bit-identical to
    per-request scoring (serving/batching.py module docstring).
    """

    # Flush cap in ROWS (a request always flushes whole).
    max_batch: int = 64
    # Co-arrival deadline window in milliseconds (0 = opportunistic).
    batch_window_ms: float = 0.0
    # Scoring worker threads draining the batch queue (numpy releases
    # the GIL inside stacked GEMMs; 0 = score inline on the handler
    # thread through the same code path).
    workers: int = 2
    # Serving PROCESSES sharing one port via SO_REUSEPORT (ServerPool):
    # one Python process tops out at its GIL, N processes multiply the
    # ceiling. 1 = no fork (the safe default inside threaded hosts);
    # raise it on dedicated serving entry points (jobs/serve.py).
    processes: int = 1
    # 'numpy' (default; bit-identity guarantee) | 'jax' (jitted registry
    # model — the throughput choice for transformer/MoE on accelerator
    # rigs; matches numpy to ~2e-6, the harness's engine-parity band).
    engine: str = "numpy"
    # Zero-copy payload parsing: ndarray straight from the raw JSON
    # envelope bytes, no intermediate Python lists (runtime.
    # parse_envelope_array); non-rectangular payloads fall back to
    # json.loads transparently. Off = always json.loads.
    fast_parse: bool = True
    # Load-generation bench (serving/loadgen.py + bench.py serving_load
    # stanza): open-loop target qps (0 = closed loop), per-level wall
    # budget, requests per concurrency level, and the sweep's levels.
    loadgen_qps: float = 0.0
    loadgen_duration_s: float = 2.0
    loadgen_requests: int = 300
    loadgen_concurrency: str = "1,4,16"
    # --- elasticity (docs/SERVING.md §elasticity) ---------------------
    # Admission control: bounded queues + priority shedding. Off by
    # default — a library-built server keeps PR 7 semantics unless the
    # operator arms the control loop.
    admit: bool = False
    # Request header carrying the priority class (high|normal|low).
    priority_header: str = "x-dct-priority"
    # Queue budget in ROWS: low sheds at 50%, normal at 80%, high at
    # the cap (admission.CLASS_BUDGET_FRACTIONS).
    admit_max_queue: int = 256
    # Queue-wait budget (ms) estimated from the batcher's recent
    # service rate; 0 disables the wait leg (depth-only shedding).
    admit_wait_ms: float = 500.0
    # Base Retry-After for shed 429s; consecutive sheds of a class
    # escalate it exponentially with jitter (the PR 3 retry curve).
    retry_after_s: float = 0.25
    # Closed-loop autoscaler: scales ServerPool PROCESSES (pool mode)
    # or batcher WORKER threads (in-process) between min/max off the
    # queue-depth / SLO-burn / shed signals.
    autoscale: bool = False
    scale_min: int = 1
    scale_max: int = 4
    # Queue-rows thresholds: sustained >= up scales out, <= down scales
    # in (between them the controller holds).
    scale_up_queue: float = 32.0
    scale_down_queue: float = 2.0
    scale_poll_s: float = 1.0
    # Consecutive agreeing polls before a scale step (anti-flap).
    scale_hysteresis: int = 2
    # Seconds after any scale event before the next may fire.
    scale_cooldown_s: float = 5.0
    # Self-healing pool: respawn budget before the circuit breaks and
    # the pool exits nonzero (exponential backoff between respawns).
    max_restarts: int = 3

    @classmethod
    def from_env(cls) -> "ServingConfig":
        c = cls()
        c.max_batch = _env("DCT_SERVE_MAX_BATCH", c.max_batch, int)
        c.batch_window_ms = _env(
            "DCT_SERVE_BATCH_WINDOW_MS", c.batch_window_ms, float
        )
        c.workers = _env("DCT_SERVE_WORKERS", c.workers, int)
        c.processes = _env("DCT_SERVE_PROCS", c.processes, int)
        c.engine = _env("DCT_SERVE_ENGINE", c.engine, str).strip().lower()
        c.fast_parse = _env("DCT_SERVE_FAST_PARSE", c.fast_parse, bool)
        c.loadgen_qps = _env(
            "DCT_SERVE_LOADGEN_QPS", c.loadgen_qps, float
        )
        c.loadgen_duration_s = _env(
            "DCT_SERVE_LOADGEN_DURATION_S", c.loadgen_duration_s, float
        )
        c.loadgen_requests = _env(
            "DCT_SERVE_LOADGEN_REQUESTS", c.loadgen_requests, int
        )
        c.loadgen_concurrency = _env(
            "DCT_SERVE_LOADGEN_CONCURRENCY", c.loadgen_concurrency, str
        )
        c.admit = _env("DCT_SERVE_ADMIT", c.admit, bool)
        c.priority_header = _env(
            "DCT_SERVE_PRIORITY_HEADER", c.priority_header, str
        ).strip().lower()
        c.admit_max_queue = _env(
            "DCT_SERVE_ADMIT_MAX_QUEUE", c.admit_max_queue, int
        )
        c.admit_wait_ms = _env(
            "DCT_SERVE_ADMIT_WAIT_MS", c.admit_wait_ms, float
        )
        c.retry_after_s = _env(
            "DCT_SERVE_RETRY_AFTER_S", c.retry_after_s, float
        )
        c.autoscale = _env("DCT_SERVE_AUTOSCALE", c.autoscale, bool)
        c.scale_min = _env("DCT_SERVE_SCALE_MIN", c.scale_min, int)
        c.scale_max = _env("DCT_SERVE_SCALE_MAX", c.scale_max, int)
        c.scale_up_queue = _env(
            "DCT_SERVE_SCALE_UP_Q", c.scale_up_queue, float
        )
        c.scale_down_queue = _env(
            "DCT_SERVE_SCALE_DOWN_Q", c.scale_down_queue, float
        )
        c.scale_poll_s = _env(
            "DCT_SERVE_SCALE_POLL_S", c.scale_poll_s, float
        )
        c.scale_hysteresis = _env(
            "DCT_SERVE_SCALE_HYSTERESIS", c.scale_hysteresis, int
        )
        c.scale_cooldown_s = _env(
            "DCT_SERVE_SCALE_COOLDOWN_S", c.scale_cooldown_s, float
        )
        c.max_restarts = _env(
            "DCT_SERVE_MAX_RESTARTS", c.max_restarts, int
        )
        return c

    def concurrency_levels(self) -> list[int]:
        """The loadgen sweep's concurrency levels, parsed and sanitized
        (bad tokens dropped; at least level 1 always present)."""
        levels = []
        for tok in str(self.loadgen_concurrency).split(","):
            tok = tok.strip()
            if tok.isdigit() and int(tok) > 0:
                levels.append(int(tok))
        return sorted(set(levels)) or [1]


@dataclass
class LoopConfig:
    """Always-on overlapped cycles (dct_tpu.continuous;
    docs/CONTINUOUS.md): ingest watcher, continuous training rounds,
    and the concurrent evaluator that promotes mid-run.

    The loop replaces the episodic DAG clock (ROADMAP item 3): ETL,
    training, gating and deploy overlap instead of serializing, so
    data-arrival -> deployed-model freshness is bounded by stage
    latency, not cycle latency. Budgets (``max_*``) exist for smokes
    and benches; production leaves them 0 (run until SIGTERM).
    """

    # Ingest watcher poll cadence over the raw staging CSV (stat-based
    # pre-check; content digest decides no-op vs delta vs rebuild).
    poll_s: float = 2.0
    # Evaluator poll cadence over the deploy-tier best checkpoint.
    eval_poll_s: float = 2.0
    # Epochs per training round — the loop's train quantum. Small keeps
    # fresh data's wait-for-round short; each round EXTENDS the same
    # optimizer trajectory (DCT_RESUME semantics).
    epochs_per_round: int = 2
    # 'supervised' = each round runs under the PR 3 supervisor
    # (crash/hang/preemption healing, compile-cache continuity across
    # relaunches); 'inline' = Trainer.fit in-process (benches/tests).
    train_mode: str = "supervised"
    # Rollout soak per stage (shadow/canary dwell) for mid-run
    # promotions — the loop's evaluator overlaps these with training.
    soak_s: float = 5.0
    # Local endpoint name the loop promotes into.
    endpoint: str = "weather-loop"
    # Challenger package root (one package dir per promotion attempt;
    # slot-referenced packages are retained, stale ones pruned).
    packages_dir: str = "data/loop_packages"
    # Stop budgets: 0 = unbounded (production always-on).
    max_rounds: int = 0
    max_wall_s: float = 0.0
    max_promotions: int = 0

    @classmethod
    def from_env(cls) -> "LoopConfig":
        c = cls()
        c.poll_s = _env("DCT_LOOP_POLL_S", c.poll_s, float)
        c.eval_poll_s = _env("DCT_LOOP_EVAL_POLL_S", c.eval_poll_s, float)
        c.epochs_per_round = _env(
            "DCT_LOOP_EPOCHS_PER_ROUND", c.epochs_per_round, int
        )
        c.train_mode = _env(
            "DCT_LOOP_TRAIN_MODE", c.train_mode, str
        ).strip().lower()
        c.soak_s = _env("DCT_LOOP_SOAK_S", c.soak_s, float)
        c.endpoint = _env("DCT_LOOP_ENDPOINT", c.endpoint, str)
        c.packages_dir = _env("DCT_LOOP_PACKAGES_DIR", c.packages_dir, str)
        c.max_rounds = _env("DCT_LOOP_MAX_ROUNDS", c.max_rounds, int)
        c.max_wall_s = _env("DCT_LOOP_MAX_WALL_S", c.max_wall_s, float)
        c.max_promotions = _env(
            "DCT_LOOP_MAX_PROMOTIONS", c.max_promotions, int
        )
        return c


@dataclass
class StreamConfig:
    """Streaming ingest data plane (dct_tpu.stream; docs/STREAMING.md):
    per-tenant partitioned event logs, consumer-group offsets, and the
    exactly-once stream ETL.

    ``mode`` (``DCT_INGEST_MODE``) selects the continuous loop's ingest
    source: ``poll`` keeps the CSV stat-polling watcher (the default,
    reference-shaped path), ``stream`` consumes the partitioned event
    log under ``dir``/``topic`` through consumer group ``group``.
    Backpressure bounds consumer lag: when the slowest registered group
    falls more than ``lag_budget`` records behind, producers ``block``
    (up to ``block_timeout_s``, then shed) or ``shed`` outright —
    unbounded lag is unexpressible.
    """

    mode: str = "poll"
    dir: str = "data/stream"
    topic: str = "events"
    partitions: int = 1
    segment_records: int = 4096
    segment_bytes: int = 1 << 22
    group: str = "etl"
    backpressure: str = "block"
    lag_budget: int = 50000
    block_timeout_s: float = 30.0
    # Records consumed per ETL pass (one pass = one parquet part).
    max_batch: int = 8192
    # Stream-watcher poll cadence. Deliberately MUCH tighter than the
    # CSV watcher's DCT_LOOP_POLL_S: a no-change stream poll reads two
    # sidecar JSONs (~µs), where the CSV path's change-processing
    # re-hashes the whole staging file — the cheap pre-check is what
    # buys sub-second arrival→trainable freshness.
    poll_s: float = 0.1

    @classmethod
    def from_env(cls) -> "StreamConfig":
        c = cls()
        c.mode = _env("DCT_INGEST_MODE", c.mode, str).strip().lower()
        c.dir = _env("DCT_STREAM_DIR", c.dir, str)
        c.topic = _env("DCT_STREAM_TOPIC", c.topic, str)
        c.partitions = max(
            1, _env("DCT_STREAM_PARTITIONS", c.partitions, int)
        )
        c.segment_records = _env(
            "DCT_STREAM_SEGMENT_RECORDS", c.segment_records, int
        )
        c.segment_bytes = _env(
            "DCT_STREAM_SEGMENT_BYTES", c.segment_bytes, int
        )
        c.group = _env("DCT_STREAM_GROUP", c.group, str)
        c.backpressure = _env(
            "DCT_STREAM_BACKPRESSURE", c.backpressure, str
        ).strip().lower()
        c.lag_budget = _env("DCT_STREAM_LAG_BUDGET", c.lag_budget, int)
        c.block_timeout_s = _env(
            "DCT_STREAM_BLOCK_TIMEOUT_S", c.block_timeout_s, float
        )
        c.max_batch = _env("DCT_STREAM_MAX_BATCH", c.max_batch, int)
        c.poll_s = _env("DCT_STREAM_POLL_S", c.poll_s, float)
        return c


@dataclass
class SchedulerConfig:
    """Multi-tenant workload scheduler (dct_tpu.scheduler;
    docs/SCHEDULER.md): N always-on tenants sharing one pod with
    chip-time quota, priority classes, and fault isolation.

    ``spec`` is the tenant roster — inline JSON or a ``tenants.json``
    path (grammar in scheduler/spec.py). Training rounds time-share the
    chips through round leases granted by strict priority class then
    weighted deficit; ``concurrent`` leases may run at once (1 = the
    whole pod is one shared mesh, the default). A starved higher-class
    waiter preempts a running lower-class round gracefully after
    ``preempt_wait_s`` (0 = never preempt — strictly boundary-granted).
    ``shared_cache`` pins one compile/AOT store under ``root`` so
    same-family tenants amortize each other's compiles. Budgets
    (``max_*``) exist for smokes and benches; production leaves them 0.
    """

    spec: str = ""
    root: str = "data/tenants"
    concurrent: int = 1
    poll_s: float = 0.5
    preempt_wait_s: float = 0.0
    shared_cache: bool = True
    max_wall_s: float = 0.0
    max_rounds: int = 0

    @classmethod
    def from_env(cls) -> "SchedulerConfig":
        c = cls()
        c.spec = _env("DCT_TENANTS", c.spec, str)
        c.root = _env("DCT_SCHED_ROOT", c.root, str)
        c.concurrent = max(1, _env("DCT_SCHED_CONCURRENT", c.concurrent, int))
        c.poll_s = _env("DCT_SCHED_POLL_S", c.poll_s, float)
        c.preempt_wait_s = _env(
            "DCT_SCHED_PREEMPT_WAIT_S", c.preempt_wait_s, float
        )
        c.shared_cache = _env(
            "DCT_SCHED_SHARED_CACHE", c.shared_cache, bool
        )
        c.max_wall_s = _env("DCT_SCHED_MAX_WALL_S", c.max_wall_s, float)
        c.max_rounds = _env("DCT_SCHED_MAX_ROUNDS", c.max_rounds, int)
        return c


@dataclass
class MpmdConfig:
    """MPMD pipeline-parallel trainer knobs (dct_tpu.parallel.mpmd;
    docs/PARALLELISM.md §MPMD): distinct per-stage programs on disjoint
    device slices with explicit inter-stage transfers.

    ``stages`` is the stage map — a stage count (``"2"``, devices split
    evenly) or explicit per-stage device counts (``"2,1,1"`` — stages
    may be heterogeneous). The grammar is validated LOUDLY at parse
    time (:func:`dct_tpu.parallel.mpmd.parse_stage_spec`), like
    ``DCT_SHARD_RULES``: a typo'd stage map raises, it never silently
    trains single-stage. ``schedule`` picks the per-stage op order:
    ``1f1b`` (PipeDream-flush — bubble confined to fill/drain, steady
    state saturated) or ``gpipe`` (all-forward-then-all-backward, the
    A/B comparator). ``microbatches`` 0 = 2x the stage count.
    """

    stages: str = "2"
    microbatches: int = 0
    schedule: str = "1f1b"
    transfer_timeout_s: float = 120.0
    port_base: int = 29600

    @classmethod
    def from_env(cls) -> "MpmdConfig":
        c = cls()
        c.stages = _env("DCT_MPMD_STAGES", c.stages, str)
        c.microbatches = _env("DCT_MPMD_MICROBATCHES", c.microbatches, int)
        c.schedule = _env(
            "DCT_MPMD_SCHEDULE", c.schedule, str
        ).strip().lower()
        c.transfer_timeout_s = _env(
            "DCT_MPMD_TRANSFER_TIMEOUT_S", c.transfer_timeout_s, float
        )
        c.port_base = _env("DCT_MPMD_PORT_BASE", c.port_base, int)
        return c

    def to_spec(self, *, n_devices: int | None = None):
        """Parse/validate into an :class:`dct_tpu.parallel.mpmd
        .MpmdSpec` — every malformed clause raises ``MpmdSpecError``
        naming the offending knob."""
        from dct_tpu.parallel.mpmd import spec_from_env_values

        return spec_from_env_values(
            self.stages, self.microbatches, self.schedule,
            self.transfer_timeout_s, self.port_base, n_devices=n_devices,
        )


@dataclass
class RunConfig:
    """Top-level bundle passed to the Trainer."""

    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    dist: DistributedConfig = field(default_factory=DistributedConfig)
    tracking: TrackingConfig = field(default_factory=TrackingConfig)
    profile: ProfileConfig = field(default_factory=ProfileConfig)
    obs: ObservabilityConfig = field(default_factory=ObservabilityConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    evaluation: EvaluationConfig = field(default_factory=EvaluationConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    loop: LoopConfig = field(default_factory=LoopConfig)
    stream: StreamConfig = field(default_factory=StreamConfig)
    sched: SchedulerConfig = field(default_factory=SchedulerConfig)
    mpmd: MpmdConfig = field(default_factory=MpmdConfig)

    @classmethod
    def from_env(cls) -> "RunConfig":
        return cls(
            data=DataConfig.from_env(),
            model=ModelConfig.from_env(),
            train=TrainConfig.from_env(),
            mesh=MeshConfig.from_env(),
            dist=DistributedConfig.from_env(),
            tracking=TrackingConfig.from_env(),
            profile=ProfileConfig.from_env(),
            obs=ObservabilityConfig.from_env(),
            resilience=ResilienceConfig.from_env(),
            evaluation=EvaluationConfig.from_env(),
            serving=ServingConfig.from_env(),
            loop=LoopConfig.from_env(),
            stream=StreamConfig.from_env(),
            sched=SchedulerConfig.from_env(),
            mpmd=MpmdConfig.from_env(),
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ======================================================================
# The DCT_* environment registry — the contract of record.
#
# Every DCT_-prefixed environment variable any first-party code reads
# (or exports into a child process) is declared here with a one-line
# description, and mirrored in `.env.example`. The `env-registry`
# dct-lint rule (docs/ANALYSIS.md) holds the three surfaces equal:
# an undeclared read, an undocumented entry, and a dead entry are all
# findings. Entries that are dataclass knobs above carry no extra
# authority — the dict exists so the ~160-knob surface (bench,
# campaign scripts, DAG plumbing, launcher-exported IDs included) has
# ONE greppable index that cannot silently drift from the code.
# ======================================================================

ENV_REGISTRY: dict[str, str] = {
    # --- data / filesystem contract --------------------------------
    "DCT_PROCESSED_DIR": "Spark/pandas ETL output dir (parquet)",
    "DCT_RAW_CSV": "raw weather CSV the ETL ingests",
    "DCT_MODELS_DIR": "deploy-tier checkpoints + train_state root",
    "DCT_VAL_FRACTION": "held-out validation fraction (reference 0.2)",
    # --- model family ----------------------------------------------
    "DCT_MODEL": "registry model name (weather_mlp | transformers | moe)",
    "DCT_HIDDEN_DIM": "MLP hidden width (reference 64)",
    "DCT_NUM_CLASSES": "classifier classes (reference 2: rain/no-rain)",
    "DCT_DROPOUT": "dropout rate (reference 0.2)",
    "DCT_SEQ_LEN": "sequence families: window length",
    "DCT_D_MODEL": "transformer encoder width",
    "DCT_N_HEADS": "attention heads",
    "DCT_N_LAYERS": "encoder blocks",
    "DCT_D_FF": "feed-forward width",
    "DCT_N_EXPERTS": "MoE expert count",
    "DCT_CAPACITY_FACTOR": "MoE switch-routing capacity factor",
    "DCT_ROUTER_AUX_WEIGHT": "MoE load-balance aux-loss weight",
    "DCT_MOE_DISPATCH": "MoE dispatch engine: einsum | sorted | auto",
    "DCT_MOE_AUTO_THRESHOLD": "auto dispatch crossover (one-hot elements)",
    "DCT_ROUTER_TOP_K": "MoE top-k routing (1 = switch)",
    "DCT_N_STAGES": "pipeline-parallel stage count",
    "DCT_N_MICROBATCHES": "GPipe microbatches (default = stages)",
    "DCT_HORIZON": "causal family: forecast horizon H",
    "DCT_REMAT": "activation rematerialization on/off",
    "DCT_ATTN_WINDOW": "sliding-window local attention (0 = full causal)",
    "DCT_N_KV_HEADS": "grouped-query attention KV heads (0 = MHA)",
    "DCT_POS_EMBED": "position encoding: sincos | rope",
    # --- optimization loop -----------------------------------------
    "DCT_EPOCHS": "epoch budget per cycle (reference 10)",
    "DCT_BATCH_SIZE": "per-device batch size (reference 4 per rank)",
    "DCT_LR": "learning rate (reference 0.01)",
    "DCT_OPTIMIZER": "adam | adamw | sgd | adafactor | lion",
    "DCT_MOMENTUM": "sgd/adafactor momentum",
    "DCT_LR_SCHEDULE": "constant | cosine",
    "DCT_WARMUP_STEPS": "linear LR warmup steps",
    "DCT_DECAY_STEPS": "cosine decay horizon (0 = auto full trajectory)",
    "DCT_END_LR_FRACTION": "cosine floor as a fraction of peak LR",
    "DCT_WEIGHT_DECAY": "decoupled weight decay (>0 makes Adam AdamW)",
    "DCT_GRAD_CLIP_NORM": "global-norm gradient clipping (0 = off)",
    "DCT_SEED": "data split + init RNG seed (reference 42)",
    "DCT_LOG_EVERY_N_STEPS": "per-step train_loss logging cadence",
    "DCT_RESUME": "1 = extend the optimizer trajectory from train_state",
    "DCT_BF16_COMPUTE": "bfloat16 MXU compute (params stay f32)",
    "DCT_USE_SCAN": "lax.scan the epoch into one dispatch",
    "DCT_SHARD_OPT_STATE": "ZeRO-1 weight-update sharding over data axis",
    "DCT_SHARD_PARAMS": "FSDP/ZeRO-3 param + moment sharding",
    "DCT_SHARD_RULES": "partition-rule overrides: pattern=axes[;...] (docs/PARALLELISM.md)",
    "DCT_DTYPE_RULES": "mixed-precision compute rules: pattern=dtype[;...] (f32 masters; docs/PARALLELISM.md)",
    "DCT_GRAD_ACCUM_STEPS": "microbatches summed per optimizer update",
    "DCT_EARLY_STOP_PATIENCE": "epochs without val_loss improvement (0 = off)",
    "DCT_EARLY_STOP_MIN_DELTA": "improvement threshold for early stop",
    "DCT_EPOCH_CHUNK": "epochs fused into one XLA dispatch",
    "DCT_PREFETCH_SPANS": "1 = pipelined span consume; 0 = strict serial",
    # --- mesh / distributed topology -------------------------------
    "DCT_MESH_DATA": "mesh data axis size (-1 = remaining devices)",
    "DCT_MESH_MODEL": "mesh tensor-parallel axis size",
    "DCT_MESH_SEQ": "mesh sequence-parallel axis size",
    "DCT_MESH_PIPE": "mesh pipeline axis size",
    "DCT_NUM_PROCESSES": "jax.distributed world size (WORLD_SIZE compat)",
    "DCT_PROCESS_ID": "jax.distributed process index (NODE_RANK compat)",
    "DCT_COORDINATOR_ADDRESS": "host:port rendezvous (MASTER_ADDR compat)",
    "DCT_WORLD_SIZE": "supervise CLI: ranks per supervised world",
    "DCT_ICI_MESH": "ICI-aware torus device layout on real TPU meshes",
    "DCT_SP_ENGINE": "sequence-parallel engine: ring | a2a (Ulysses)",
    "DCT_RING_STRIPED": "zigzag layout for the causal ring: auto|on|off",
    # --- attention kernels -----------------------------------------
    "DCT_FLASH": "Pallas flash attention: auto | on | off | interpret",
    "DCT_FLASH_BLOCK_Q": "flash kernel query-tile size",
    "DCT_FLASH_BLOCK_K": "flash kernel key-tile size",
    "DCT_FLASH_BWD": "flash backward: kernel | remat escape hatch",
    # --- launcher / orchestration plumbing -------------------------
    "DCT_TRAIN_HOSTS": "comma-separated trainer hosts the DAG launches onto",
    "DCT_EXEC_TEMPLATE": "remote-exec template ({host}, {cmd})",
    "DCT_TRAIN_COMMAND": "override the DAG's per-host training command",
    "DCT_REPO_ROOT": "repo root for DAG task processes",
    "DCT_DEPLOY_TARGET": "deploy DAGs: azure | local endpoint surface",
    "DCT_KEEP_CHECKPOINTS": "pipeline DAG cleanup: newest ckpts to keep",
    "DCT_ETL_ENGINE": "ETL engine: spark | pandas fallback",
    "DCT_ETL_INCREMENTAL": "digest no-op + append-only delta ETL (default on)",
    "DCT_ETL_REBUILD_TOL": "basis-stats shift forcing a full ETL rebuild",
    # --- always-on loop (dct_tpu.continuous; docs/CONTINUOUS.md) ----
    "DCT_LOOP_POLL_S": "ingest watcher poll cadence over the raw CSV (s)",
    "DCT_LOOP_EVAL_POLL_S": "evaluator poll cadence over the best ckpt (s)",
    "DCT_LOOP_EPOCHS_PER_ROUND": "epochs per continuous training round",
    "DCT_LOOP_TRAIN_MODE": "round runner: supervised (PR 3) | inline",
    "DCT_LOOP_SOAK_S": "mid-run rollout soak per stage (s)",
    "DCT_LOOP_ENDPOINT": "local endpoint the loop promotes into",
    "DCT_LOOP_PACKAGES_DIR": "challenger package root for mid-run promotions",
    "DCT_LOOP_MAX_ROUNDS": "loop stop budget: training rounds (0 = unbounded)",
    "DCT_LOOP_MAX_WALL_S": "loop stop budget: wall seconds (0 = unbounded)",
    "DCT_LOOP_MAX_PROMOTIONS": "loop stop budget: promotions (0 = unbounded)",
    "DCT_LOOP_DAG_HOURS": "always-on DAG: one task occupancy before re-trigger",
    "DCT_LOOP_SMOKE_WAIT_S": "continuous-loop CI smoke: wall budget (s)",
    # --- streaming ingest data plane (dct_tpu.stream; docs/STREAMING.md) -
    "DCT_INGEST_MODE": "loop ingest source: poll (CSV stat-poll) | stream (event log)",
    "DCT_STREAM_DIR": "partitioned event-log root (per tenant)",
    "DCT_STREAM_TOPIC": "topic name under the stream root",
    "DCT_STREAM_PARTITIONS": "partitions per topic (single-writer each)",
    "DCT_STREAM_SEGMENT_RECORDS": "records per segment before the atomic seal",
    "DCT_STREAM_SEGMENT_BYTES": "bytes per segment before the atomic seal",
    "DCT_STREAM_GROUP": "consumer group the stream ETL commits under",
    "DCT_STREAM_BACKPRESSURE": "over-budget producer action: block | shed | off",
    "DCT_STREAM_LAG_BUDGET": "bounded-lag budget (records) before backpressure",
    "DCT_STREAM_BLOCK_TIMEOUT_S": "blocked-producer wait before shedding (s)",
    "DCT_STREAM_MAX_BATCH": "records per stream-ETL pass (one parquet part)",
    "DCT_STREAM_POLL_S": "stream-watcher poll cadence (s; idle poll is two sidecar reads)",
    "DCT_STREAM_SMOKE_WAIT_S": "streaming CI smoke: wall budget (s)",
    # --- multi-tenant scheduler (dct_tpu.scheduler; docs/SCHEDULER.md) -
    "DCT_TENANTS": "tenant roster: inline JSON or tenants.json path",
    "DCT_SCHED_ROOT": "per-tenant run-dir root (+ shared cache home)",
    "DCT_SCHED_CONCURRENT": "round leases running at once (1 = one shared mesh)",
    "DCT_SCHED_POLL_S": "scheduler monitor cadence (budgets, preemption)",
    "DCT_SCHED_PREEMPT_WAIT_S": "starved higher-class wait before graceful preempt (0 = never)",
    "DCT_SCHED_SHARED_CACHE": "pin one compile/AOT store for same-family tenants",
    "DCT_SCHED_MAX_WALL_S": "scheduler stop budget: wall seconds (0 = unbounded)",
    "DCT_SCHED_MAX_ROUNDS": "scheduler stop budget: total leases (0 = unbounded)",
    "DCT_SCHED_DAG_HOURS": "multi-tenant DAG: one task occupancy before re-trigger",
    "DCT_SCHED_SMOKE_WAIT_S": "scheduler CI smoke: wall budget (s)",
    # --- MPMD pipeline trainer (dct_tpu.parallel.mpmd; docs/PARALLELISM.md §MPMD) -
    "DCT_MPMD_STAGES": "stage map: stage count or per-stage device counts (loud parse)",
    "DCT_MPMD_MICROBATCHES": "microbatches per optimizer step (0 = 2x stages)",
    "DCT_MPMD_SCHEDULE": "per-stage op order: 1f1b | gpipe",
    "DCT_MPMD_TRANSFER_TIMEOUT_S": "inter-stage transfer wait before loud failure (s)",
    "DCT_MPMD_PORT_BASE": "multi-process transfer plane base port (stage k = base+k)",
    "DCT_MPMD_STAGE_ID": "worker plumbing: this process's stage index (NODE_RANK fallback)",
    "DCT_MPMD_SMOKE_WAIT_S": "MPMD CI smoke: wall budget (s)",
    "DCT_SPARK_MASTER_HOST": "Spark master hostname for the ETL DAG",
    "DCT_SOAK_SECONDS": "auto-deploy DAG: canary soak dwell",
    "DCT_ENDPOINT_NAME": "serve the named LOCAL rollout endpoint",
    "DCT_LOCAL_ENDPOINT_STATE": "local endpoint traffic-state JSON path",
    # --- observability ---------------------------------------------
    "DCT_OBSERVABILITY": "master switch for the operator plane",
    "DCT_EVENTS_DIR": "structured event log (+ spans, prom dump) dir",
    "DCT_RUN_ID": "launcher-minted run-correlation ID (exported to ranks)",
    "DCT_SPAN_ID": "parent span ID exported to child processes",
    "DCT_HEARTBEAT_DIR": "per-rank heartbeat files",
    "DCT_HEARTBEAT_INTERVAL": "same-phase heartbeat throttle (s)",
    "DCT_HEARTBEAT_STALL_SECONDS": "heartbeat age that marks a rank stalled",
    "DCT_METRICS_PROM": "end-of-run Prometheus textfile dump path",
    "DCT_SPANS_DIR": "distributed-tracing span files dir",
    "DCT_LINEAGE": "content-addressed provenance ledger (on by default)",
    "DCT_LINEAGE_DIR": "lineage ledger dir (default: the events dir)",
    "DCT_SERVE_TRACE": "opt-in per-request serving.score spans",
    "DCT_SERVE_LOG": "per-request serving access log",
    "DCT_HALT_ON_NAN": "halt training on non-finite loss",
    "DCT_HALT_ON_SPIKE": "halt on loss/grad-norm z-score spike",
    "DCT_SPIKE_ZSCORE": "spike detector z threshold",
    "DCT_SPIKE_WINDOW": "spike detector rolling window",
    "DCT_TELEMETRY_FLUSH_S": "event/span write-batch window (0 = through)",
    "DCT_TELEMETRY_FLUSH_RECORDS": "record cap forcing an early flush",
    "DCT_METRICS_DIR": "metrics-plane snapshot dir ('' = plane off)",
    "DCT_METRICS_PUBLISH_S": "min seconds between snapshot publishes",
    "DCT_METRICS_STALE_S": "live snapshot age that stops counting (s)",
    "DCT_SLO_SPEC": "SLO specs over the aggregated series (slo.py grammar)",
    "DCT_SLO_FAST_WINDOW_S": "burn-rate fast window (s)",
    "DCT_SLO_SLOW_WINDOW_S": "burn-rate slow window (s)",
    "DCT_SLO_BURN_THRESHOLD": "alert when BOTH windows burn above this",
    "DCT_PROFILE": "jax.profiler one-epoch trace window",
    "DCT_TRACE_DIR": "profiler trace output dir",
    "DCT_PROFILE_EPOCH": "which epoch to trace (0-based)",
    "DCT_PROFILE_TRIGGER": "flight-recorder trigger file ('' = off)",
    "DCT_PROF_CAPTURE_S": "flight-recorder default capture length (s)",
    "DCT_PROF_SIGUSR2": "arm SIGUSR2 as an on-demand capture trigger",
    "DCT_ROOFLINE": "XLA cost-model roofline accounting on/off",
    "DCT_HBM_GBPS": "per-chip HBM bandwidth override for roofline math",
    "DCT_TS_DIR": "telemetry history store dir ('' = plane off)",
    "DCT_TS_FAMILIES": "fnmatch patterns of recorded dct_* families",
    "DCT_TS_SEG_POINTS": "points per raw segment before sealing",
    "DCT_TS_SEG_S": "max raw segment age before sealing (s)",
    "DCT_TS_FLUSH_S": "active-segment republish cadence (s)",
    "DCT_TS_RETENTION_S": "segment age deleted at compaction (s)",
    "DCT_TS_DOWNSAMPLE_S": "raw-segment age folded to the ds tier (s)",
    "DCT_TS_DS_RES_S": "downsampled-tier bin width (s)",
    "DCT_ANOMALY": "EWMA/z-score anomaly detection over the history",
    "DCT_ANOMALY_Z": "anomaly z-score trigger threshold",
    "DCT_ANOMALY_ALPHA": "EWMA baseline smoothing factor",
    "DCT_ANOMALY_MIN_POINTS": "baseline samples before detection arms",
    "DCT_ANOMALY_WINDOW_S": "history window per detector read (s)",
    "DCT_ANOMALY_POLL_S": "detector poll cadence (s)",
    "DCT_INCIDENT": "auto-assembled incident bundles on anomaly/SLO",
    "DCT_INCIDENT_DIR": "bundle root ('' = sibling of DCT_TS_DIR)",
    "DCT_INCIDENT_WINDOW_S": "history/event window per bundle (s)",
    "DCT_INCIDENT_COOLDOWN_S": "min seconds between same-signal bundles",
    "DCT_INCIDENT_PROFILE": "fire the flight recorder into each bundle",
    "DCT_INCIDENT_PROFILE_S": "incident profile capture length (s)",
    # --- resilience ------------------------------------------------
    "DCT_MAX_RESTARTS": "supervised relaunch budget",
    "DCT_RESTART_BACKOFF_S": "first relaunch backoff",
    "DCT_RESTART_BACKOFF_FACTOR": "backoff growth per restart",
    "DCT_RESTART_JITTER": "relative backoff jitter",
    "DCT_PREEMPT_GRACE_S": "SIGTERM -> SIGKILL escalation window",
    "DCT_GRACEFUL_PREEMPTION": "SIGTERM: finish step, save, exit 75",
    "DCT_FAULT_SPEC": "deterministic chaos plan (faults.py grammar)",
    "DCT_FAULT_SLEEP_S": "slow_save / slow_epoch fault duration",
    "DCT_RETRY_MAX_ATTEMPTS": "tracking/deploy transient-network retries",
    "DCT_RETRY_BACKOFF_S": "network retry backoff",
    "DCT_STARTUP_RECOVERY_DEBT_S": "supervisor-set lost-wall-clock badput",
    "DCT_LAUNCH_TIMEOUT_S": "supervise CLI: per-attempt launch timeout",
    # --- evaluation / promotion gates / drift ----------------------
    "DCT_GATE": "consult the promotion gate between rollout stages",
    "DCT_GATE_MIN_IMPROVEMENT": "mean loss delta counted as improvement",
    "DCT_GATE_MAX_REGRESSION": "mean regression tolerated before blocking",
    "DCT_GATE_CONFIDENCE": "one-sided bootstrap confidence",
    "DCT_GATE_BOOTSTRAP": "paired-bootstrap resamples",
    "DCT_GATE_SEED": "bootstrap RNG seed (decisions deterministic)",
    "DCT_GATE_MAX_SLICE_REGRESSION": "worst tolerated per-slice regression",
    "DCT_GATE_REQUIRE_IMPROVEMENT": "strict mode: promote only on proof",
    "DCT_GATE_EVAL_BATCH": "harness examples per forward pass",
    "DCT_GATE_ENGINE": "eval engine: numpy serving twin | jax",
    "DCT_GATE_FAIL_OPEN": "missing prerequisites promote (1) or hold (0)",
    "DCT_GATE_LEDGER": "gate-decision ledger path for /metrics",
    "DCT_DRIFT_PSI": "per-feature PSI threshold vs stamped snapshot",
    "DCT_DRIFT_KS": "per-feature two-sample KS D threshold",
    "DCT_DRIFT_BINS": "quantile bins in the stamped snapshot",
    "DCT_DRIFT_MAX_DISAGREEMENT": "shadow prediction-disagreement hold rate",
    "DCT_DRIFT_THRESHOLD": "ETL-side daily-stats drift gate (older knob)",
    "DCT_MIRROR_CAPTURE": "mirrored shadow-response capture JSONL path",
    # --- tracking --------------------------------------------------
    "DCT_EXPERIMENT": "tracking experiment name",
    "DCT_TRACKING_DIR": "LocalTracking file-store root",
    # --- batch inference / serving ---------------------------------
    "DCT_CKPT": "checkpoint to score (default: newest best)",
    "DCT_PREDICTIONS": "batch-inference output parquet",
    "DCT_PREDICT_CHUNK": "rows/windows scored per forward pass",
    "DCT_PREDICT_ENGINE": "predict engine: numpy | jax",
    "DCT_PREDICT_DTYPE": "jax predict compute dtype (e.g. bfloat16)",
    "DCT_SERVE_HOST": "HTTP serving bind host",
    "DCT_SERVE_PORT": "HTTP serving port",
    "DCT_SERVE_MAX_BATCH": "micro-batcher flush cap in rows",
    "DCT_SERVE_BATCH_WINDOW_MS": "co-arrival deadline window (0 = opportunistic)",
    "DCT_SERVE_WORKERS": "scoring worker threads (0 = inline)",
    "DCT_SERVE_PROCS": "SO_REUSEPORT serving processes (1 = no fork)",
    "DCT_SERVE_ENGINE": "batched scorer: numpy (bit-identical) | jax (jitted)",
    "DCT_SERVE_FAST_PARSE": "zero-copy JSON envelope parsing on/off",
    "DCT_QUANT_DTYPE": "package quantization default: int8 | bf16 (docs/SERVING.md)",
    "DCT_QUANT_PROB_BOUND": "quantized-vs-f32 max-abs-prob parity bound",
    "DCT_SERVE_LOADGEN_QPS": "loadgen open-loop target qps (0 = closed loop)",
    "DCT_SERVE_LOADGEN_DURATION_S": "loadgen per-level wall budget (s)",
    "DCT_SERVE_LOADGEN_REQUESTS": "loadgen requests per concurrency level",
    "DCT_SERVE_LOADGEN_CONCURRENCY": "loadgen sweep levels (comma-separated)",
    # Elastic serving controls (docs/SERVING.md §elasticity).
    "DCT_SERVE_ADMIT": "priority admission control on/off",
    "DCT_SERVE_PRIORITY_HEADER": "request header carrying high|normal|low",
    "DCT_SERVE_ADMIT_MAX_QUEUE": "admission queue budget in rows",
    "DCT_SERVE_ADMIT_WAIT_MS": "admission queue-wait budget (ms; 0 = off)",
    "DCT_SERVE_RETRY_AFTER_S": "base Retry-After for shed 429s",
    "DCT_SERVE_AUTOSCALE": "closed-loop capacity autoscaler on/off",
    "DCT_SERVE_SCALE_MIN": "autoscaler floor (procs or workers)",
    "DCT_SERVE_SCALE_MAX": "autoscaler ceiling (procs or workers)",
    "DCT_SERVE_SCALE_UP_Q": "queue rows that vote scale-up",
    "DCT_SERVE_SCALE_DOWN_Q": "queue rows that vote scale-down",
    "DCT_SERVE_SCALE_POLL_S": "autoscaler poll interval (s)",
    "DCT_SERVE_SCALE_HYSTERESIS": "consecutive agreeing polls per scale step",
    "DCT_SERVE_SCALE_COOLDOWN_S": "min seconds between scale events",
    "DCT_SERVE_MAX_RESTARTS": "pool respawn budget before circuit-break",
    "DCT_SERVE_PROC_INDEX": "pool-exported child index (set by ServerPool)",
    # --- platform probing / caches / native ------------------------
    "DCT_REQUIRE_TPU": "fail fast when no TPU backend is available",
    "DCT_BACKEND_PROBE_TIMEOUT": "backend liveness probe timeout (s)",
    "DCT_BACKEND_PROBE_RETRIES": "backend probe retry count",
    "DCT_BACKEND_PROBE_BUDGET": "total probe wall-clock budget (s)",
    "DCT_PEAK_TFLOPS": "per-chip peak TFLOPs override for MFU math",
    "DCT_JAX_CACHE": "enable the persistent XLA compilation cache",
    "DCT_JAX_CACHE_DIR": "compilation cache directory",
    # Compile cache + AOT executables (dct_tpu.compilecache;
    # docs/OBSERVABILITY.md §compile): sub-second relaunch/spin-up.
    "DCT_COMPILE_CACHE": "compile cache mode: off | auto (dir arms) | on",
    "DCT_COMPILE_CACHE_DIR": "persistent XLA compile-cache dir (per-machine)",
    "DCT_COMPILE_CACHE_AOT": "AOT executable store on/off (default on)",
    "DCT_COMPILE_CACHE_AOT_DIR": "AOT store root override (default <models>/aot)",
    "DCT_COMPILE_CACHE_MIN_COMPILE_S": "min compile seconds worth caching (0 = all)",
    "DCT_COMPILE_CACHE_WARM_SIZES": "packaging scorer pre-compile batch sizes",
    "DCT_NATIVE": "enable the native (C++) extension build",
    "DCT_CXX": "C++ compiler for the native build",
    # --- bench / campaign scripts ----------------------------------
    "DCT_BENCH_ROWS": "bench dataset size (rows)",
    "DCT_BENCH_EPOCHS": "bench trainer-loop epochs",
    "DCT_BENCH_TORCH_EPOCHS": "bench torch-reference epochs",
    "DCT_BENCH_FUSE": "bench fused-step legs on/off",
    "DCT_BENCH_SCALED": "bench scaled-transformer leg on/off",
    "DCT_BENCH_SPINUP": "bench restart_spinup (cold/warm relaunch) leg on/off",
    "DCT_BENCH_FRESHNESS": "bench cycle_freshness (serial vs loop) leg on/off",
    "DCT_BENCH_SHARDED": "bench model_sharded (sharded vs DP) leg on/off",
    "DCT_BENCH_TENANTS": "bench multi_tenant (2-tenant scheduler) leg on/off",
    "DCT_BENCH_MPMD": "bench mpmd_pipeline (MPMD-1F1B vs SPMD-GPipe bubble) leg on/off",
    "DCT_BENCH_ROOFLINE": "bench roofline (local cost-model MFU) leg on/off",
    "DCT_BENCH_ELASTIC": "bench elastic_serving (overload controls A/B) leg on/off",
    "DCT_BENCH_TELEMETRY": "bench telemetry_history (detect latency + publish overhead) leg on/off",
    "DCT_BENCH_STREAM": "bench stream_ingest (events/s + lag p99 vs polling) leg on/off",
    "DCT_BENCH_LOWPREC": "bench low_precision (int8/bf16 serving + bf16 rules A/B) leg on/off",
    "DCT_BENCH_DEADLINE": "bench wall-clock deadline (s); legs self-gate",
    "DCT_BENCH_PARTIAL": "path for the partial-results stash",
    "DCT_VAL_PARITY_EPOCHS": "val-loss parity leg epoch budget",
    "DCT_SCALED_DMODEL": "scaled bench leg: d_model",
    "DCT_SCALED_LAYERS": "scaled bench leg: layers",
    "DCT_SCALED_HEADS": "scaled bench leg: heads",
    "DCT_SCALED_DFF": "scaled bench leg: d_ff",
    "DCT_SCALED_SEQ": "scaled bench leg: sequence length",
    "DCT_SCALED_BATCH": "scaled bench leg: per-device batch",
    "DCT_SCALED_WINDOW": "scaled bench leg: attention window",
    "DCT_SCALED_SCAN": "scaled bench leg: scan path on/off",
    "DCT_ONCHIP_MOE": "on-chip campaign: include the MoE section",
    "DCT_CAMPAIGN_SECTIONS": "campaign: comma-separated section filter",
    "DCT_CAMPAIGN_OUT": "campaign: output JSON path",
    "DCT_CAMPAIGN_MFU": "campaign: MFU gate threshold",
    "DCT_CAMPAIGN_ALLOW_CPU": "campaign: permit CPU (evidence-only) runs",
    "DCT_CAMPAIGN_INTERPRET": "campaign: Pallas interpret mode",
    "DCT_CAMPAIGN_FLASH_SHAPES": "campaign: flash shape sweep spec",
}
