from dct_tpu.etl.preprocess import preprocess_csv_to_parquet  # noqa: F401
