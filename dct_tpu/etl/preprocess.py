"""Native ETL: weather.csv -> normalized parquet, Spark-output-compatible.

Reproduces the exact data semantics of the reference Spark job
(jobs/preprocess.py):

- label encoding ``Rain == "rain" -> 1 else 0`` into ``label_encoded``
  (jobs/preprocess.py:23-25);
- per-column z-score normalization of the five features into ``*_norm``
  columns using mean and *sample* stddev (Spark ``stddev`` = stddev_samp,
  ddof=1) with a divide-by-zero guard (:32-41);
- output restricted to ``[*_norm, label_encoded]`` written as a parquet
  **directory** ``<out>/data.parquet`` containing part files, overwriting any
  previous run (:44-51) — so downstream readers built for Spark output work
  unchanged.

The north star keeps the real Spark cluster for production ETL (see
``dct_tpu/etl/spark_job.py``); this native path is the same transform without
a JVM for single-host runs, tests, and benches. It is vectorized numpy/arrow
on the host — ETL is IO-bound, not a TPU problem.

Continuous-training hygiene the reference lacks entirely: each run
persists the raw per-feature statistics beside the parquet
(``stats.json``) and compares them against the PREVIOUS run's
(:func:`detect_drift`), writing ``drift_report.json`` — so a daily
re-train on silently-shifted data is a visible event instead of a
mystery regression in val_loss. Thresholded on the standardized mean
shift (|Δmean|/σ_prev), the std ratio, and the label-rate shift;
``DCT_DRIFT_THRESHOLD`` tunes it.

Incremental mode (the always-on loop's path, ``incremental=True`` /
``DCT_ETL_INCREMENTAL``): an ``etl_state.json`` snapshot beside the
parquet records the input's content digest plus cumulative per-feature
moments, so

- an UNCHANGED CSV is a no-op (digest match — no parse, no rewrite);
- an APPEND-ONLY grown CSV processes only the delta rows: one new part
  file joins the Spark-style parquet directory, normalized with the
  SAME per-feature basis every prior part used (all parts share one
  z-score basis, so the loaded dataset is exactly "full reprocess under
  the basis stats"), while ``stats.json`` and the drift check see the
  FULL distribution via exact Chan-merged moments;
- any other change (rewrite, truncation, basis stats shifted past
  ``DCT_ETL_REBUILD_TOL`` — the point where the frozen normalization
  basis would misrepresent the data) falls back to a full rebuild,
  published with an atomic directory swap so a concurrently-reading
  trainer never observes a half-written snapshot.

Each processed generation is stamped into the state file
(``generation``, ``arrival_ts`` = the raw CSV's mtime) — the loop's
``cycle_freshness`` accounting reads data-arrival time from here and
the trainer stamps the generation into its checkpoints.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

import numpy as np

DEFAULT_FEATURES = ["Temperature", "Humidity", "Wind_Speed", "Cloud_Cover", "Pressure"]

#: Incremental-state schema version (bump on layout change: readers
#: treat an unknown version as "no state" and fall back to a full run).
ETL_STATE_VERSION = 1
ETL_STATE_NAME = "etl_state.json"


def detect_drift(
    prev: dict, new: dict, *, threshold: float | None = None
) -> dict:
    """Compare two runs' raw-data statistics.

    Per feature: ``mean_shift`` = |mean_new - mean_prev| / max(σ_prev,
    1e-12) (standardized, so 'moved by half a previous-σ' means the same
    for every feature) and ``std_ratio`` = σ_new/σ_prev; plus the label
    positive-rate shift. A feature drifts when mean_shift > threshold or
    std_ratio is outside [1/(1+t), 1+t]; the label drifts when its rate
    moves by more than t/2 absolute. Returns a JSON-able report with
    ``any_drift`` for pipeline gates."""
    if threshold is None:
        threshold = float(os.environ.get("DCT_DRIFT_THRESHOLD", "0.5"))
    feats = {}
    any_drift = False
    prev_feats = prev.get("features", {})
    new_feats = new.get("features", {})
    for name in sorted(set(prev_feats) | set(new_feats)):
        p, n = prev_feats.get(name), new_feats.get(name)
        if p is None or n is None:
            # Schema drift (column added/renamed/dropped) IS drift — the
            # exact silently-shifted-data event this detector exists for.
            any_drift = True
            feats[name] = {
                "drifted": True,
                "missing_in": "previous" if p is None else "current",
            }
            continue
        values = (p["mean"], p["std"], n["mean"], n["std"])
        if not all(np.isfinite(v) for v in values):
            # NaN stats (nulls in the raw CSV) would make every
            # comparison False; broken data must read as drifted.
            any_drift = True
            feats[name] = {"drifted": True, "non_finite_stats": True}
            continue
        sigma = max(abs(p["std"]), 1e-12)
        mean_shift = abs(n["mean"] - p["mean"]) / sigma
        std_ratio = (abs(n["std"]) + 1e-12) / sigma
        drifted = bool(
            mean_shift > threshold
            or std_ratio > 1.0 + threshold
            or std_ratio < 1.0 / (1.0 + threshold)
        )
        any_drift |= drifted
        feats[name] = {
            "mean_shift": round(mean_shift, 4),
            "std_ratio": round(std_ratio, 4),
            "drifted": drifted,
        }
    label_shift = abs(
        new.get("label_rate", 0.0) - prev.get("label_rate", 0.0)
    )
    # Label rates live in [0, 1]: clamp the derived threshold so a large
    # sigma-unit knob cannot silently disable label-drift detection.
    label_drifted = bool(label_shift > min(threshold / 2, 0.25))
    any_drift |= label_drifted
    return {
        "threshold": threshold,
        "features": feats,
        "label_rate_shift": round(label_shift, 4),
        "label_drifted": label_drifted,
        "rows_prev": int(prev.get("rows", 0)),
        "rows_new": int(new.get("rows", 0)),
        "any_drift": any_drift,
    }


def _effective_size(path: str) -> int:
    """Bytes through the LAST newline — the prefix of the file that is
    complete rows. A concurrent appender (the always-on loop's staging
    writer) can be mid-write when we poll; an unterminated final line
    would otherwise parse as a silently-truncated-but-valid row. The
    dangling bytes are simply not this generation's data: the next poll
    picks them up once their newline lands."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        pos = size
        while pos > 0:
            step = min(pos, 1 << 14)
            f.seek(pos - step)
            chunk = f.read(step)
            nl = chunk.rfind(b"\n")
            if nl >= 0:
                return pos - step + nl + 1
            pos -= step
    return 0


def _digest_input(
    path: str, prefix_at: int | None = None, limit: int | None = None
) -> dict:
    """Streaming content digest of the raw input (first ``limit`` bytes;
    None = whole file): sha256 + size + whether the content ends in a
    newline, plus (when ``prefix_at`` falls inside) the sha256 of the
    FIRST ``prefix_at`` bytes — one read pass serves both the no-op
    check (full digest) and the append-only check (prefix digest vs the
    previous run's full digest)."""
    h = hashlib.sha256()
    prefix_hex = None
    seen = 0
    last_byte = b""
    remaining = limit
    with open(path, "rb") as f:
        while True:
            want = 1 << 20 if remaining is None else min(1 << 20, remaining)
            if want == 0:
                break
            chunk = f.read(want)
            if not chunk:
                break
            if remaining is not None:
                remaining -= len(chunk)
            if (
                prefix_at is not None
                and seen < prefix_at <= seen + len(chunk)
            ):
                h.update(chunk[: prefix_at - seen])
                prefix_hex = h.hexdigest()
                h.update(chunk[prefix_at - seen:])
            else:
                h.update(chunk)
            seen += len(chunk)
            last_byte = chunk[-1:]
    if prefix_at is not None and prefix_at == seen:
        prefix_hex = h.hexdigest()
    return {
        "size": seen,
        "sha256": h.hexdigest(),
        "prefix_sha256": prefix_hex,
        "newline_end": last_byte == b"\n",
    }


def read_etl_state(output_dir: str) -> dict:
    """The incremental-ETL state snapshot ({} when absent/torn/foreign
    version) — also the loop's source for ``generation``/``arrival_ts``
    freshness accounting. Readers consult this BEFORE loading the
    parquet, so a stamped generation never claims data the concurrent
    writer had not yet published."""
    path = os.path.join(output_dir, ETL_STATE_NAME)
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(state, dict) or state.get("version") != ETL_STATE_VERSION:
        return {}
    return state


def _write_etl_state(output_dir: str, state: dict) -> None:
    path = os.path.join(output_dir, ETL_STATE_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=2)
    os.replace(tmp, path)


def _chan_merge(a: dict, b: dict) -> dict:
    """Chan's parallel combine of two {n, mean, m2} moment sets — the
    numerically-stable way to merge the previous cumulative stats with a
    delta chunk's (naive sum/sumsq cancels catastrophically at weather
    magnitudes like pressure ~1013)."""
    n = a["n"] + b["n"]
    if n == 0:
        return {"n": 0, "mean": 0.0, "m2": 0.0}
    delta = b["mean"] - a["mean"]
    mean = a["mean"] + delta * (b["n"] / n)
    m2 = a["m2"] + b["m2"] + delta * delta * (a["n"] * b["n"] / n)
    return {"n": n, "mean": mean, "m2": m2}


def _moments(col: np.ndarray) -> dict:
    n = int(len(col))
    if n == 0:
        return {"n": 0, "mean": 0.0, "m2": 0.0}
    mean = float(np.mean(col))
    return {"n": n, "mean": mean, "m2": float(np.sum((col - mean) ** 2))}


def _moments_stats(m: dict) -> dict:
    """{mean, std(ddof=1)} from a moment set (the stats.json schema the
    drift detector compares)."""
    std = (m["m2"] / (m["n"] - 1)) ** 0.5 if m["n"] > 1 else 0.0
    return {"mean": float(m["mean"]), "std": float(std)}


def _rebuild_tolerance() -> float:
    return float(os.environ.get("DCT_ETL_REBUILD_TOL", "0.5"))


def _basis_stale(basis: dict, merged: dict, tol: float) -> bool:
    """True when the merged full-distribution stats have shifted far
    enough from the frozen normalization basis that appending more
    basis-normalized rows would misrepresent the data (same standardized
    thresholds as :func:`detect_drift`)."""
    for name, b in basis.items():
        m = merged.get(name)
        if m is None:
            return True
        sigma = max(abs(b["std"]), 1e-12)
        if abs(m["mean"] - b["mean"]) / sigma > tol:
            return True
        ratio = (abs(m["std"]) + 1e-12) / sigma
        if ratio > 1.0 + tol or ratio < 1.0 / (1.0 + tol):
            return True
    return False


def _transform_columns(
    table,
    feature_cols: list[str],
    label_col: str,
    positive_label: str,
    *,
    basis: dict | None = None,
) -> tuple[dict, dict, dict, np.ndarray]:
    """One chunk's transform: (out_cols, per-feature moments,
    norm basis used, label_encoded). ``basis=None`` derives the z-score
    basis from this chunk (the full-run path, reference semantics);
    a provided basis normalizes against frozen stats (the delta path)."""
    labels_raw = table.column(label_col).to_numpy(zero_copy_only=False)
    label_encoded = (labels_raw == positive_label).astype(np.int64)
    out_cols: dict[str, np.ndarray] = {}
    moments: dict[str, dict] = {}
    used_basis: dict[str, dict] = {}
    for name in feature_cols:
        col = table.column(name).to_numpy(zero_copy_only=False).astype(np.float64)
        moments[name] = _moments(col)
        if basis is None:
            # Spark's stddev is the sample stddev (ddof=1),
            # jobs/preprocess.py:33.
            mean = float(np.mean(col))
            std = float(np.std(col, ddof=1)) if len(col) > 1 else 0.0
        else:
            mean = float(basis[name]["mean"])
            std = float(basis[name]["std"])
        used_basis[name] = {"mean": mean, "std": std}
        std = std if std != 0.0 else 1.0
        out_cols[f"{name}_norm"] = (col - mean) / std
    out_cols["label_encoded"] = label_encoded
    return out_cols, moments, used_basis, label_encoded


def _publish_part(parquet_dir: str, part_name: str, out_cols: dict) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    final = os.path.join(parquet_dir, part_name)
    tmp = f"{final}.tmp.{os.getpid()}"
    pq.write_table(pa.table(out_cols), tmp)
    # Atomic: a concurrent reader's directory listing only ever sees
    # complete ``*.parquet`` files (the tmp suffix keeps it out of the
    # glob until the replace).
    os.replace(tmp, final)


def _read_delta_table(input_csv: str, header: str, offset: int, end: int):
    """Parse only the appended tail: the stored header line + the bytes
    in ``[offset, end)`` (end = last complete line), through the same
    pyarrow CSV reader as the full path."""
    import io

    import pyarrow.csv as pacsv

    with open(input_csv, "rb") as f:
        f.seek(offset)
        tail = f.read(end - offset)
    return pacsv.read_csv(io.BytesIO(header.encode() + tail))


def _read_csv_limited(input_csv: str, limit: int | None):
    """Parse the input through pyarrow, bounded to the first ``limit``
    bytes (complete lines only — the incremental mode's concurrent-
    appender guard); ``limit=None`` reads the whole file."""
    import pyarrow.csv as pacsv

    if limit is None:
        return pacsv.read_csv(input_csv)
    import io

    with open(input_csv, "rb") as f:
        return pacsv.read_csv(io.BytesIO(f.read(limit)))


def _incremental_enabled(explicit: bool | None) -> bool:
    if explicit is not None:
        return explicit
    return os.environ.get("DCT_ETL_INCREMENTAL", "1").strip().lower() not in (
        "0", "false", "no",
    )


def preprocess_csv_to_parquet(
    input_csv: str,
    output_dir: str,
    *,
    feature_cols: list[str] | None = None,
    label_col: str = "Rain",
    positive_label: str = "rain",
    parquet_name: str = "data.parquet",
    incremental: bool | None = None,
) -> str:
    """Run the ETL transform; returns the parquet directory path.

    ``incremental=None`` reads ``DCT_ETL_INCREMENTAL`` (default on):
    unchanged input short-circuits to a no-op, append-only growth
    processes only the delta rows (module docstring); anything else —
    including ``incremental=False`` — runs the full transform.
    """
    feature_cols = feature_cols or DEFAULT_FEATURES
    if not os.path.exists(input_csv):
        raise FileNotFoundError(f"Raw data not found at {input_csv}")

    parquet_dir = os.path.join(output_dir, parquet_name)
    inc = _incremental_enabled(incremental)
    state = read_etl_state(output_dir) if inc else {}
    # Incremental mode only ever reads COMPLETE lines: a concurrent
    # appender's unterminated tail waits for the next poll.
    eff = _effective_size(input_csv) if inc else None
    prev_input = state.get("input") or {}
    prev_size = int(prev_input.get("size") or 0)
    digest = None
    if state and os.path.isdir(parquet_dir):
        digest = _digest_input(
            input_csv, prefix_at=prev_size if prev_size else None,
            limit=eff,
        )
        if (
            digest["size"] == prev_size
            and digest["sha256"] == prev_input.get("sha256")
            # A torn/hand-edited stats.json means the published snapshot
            # is not coherent: rebuild rather than no-op over it.
            and read_previous_stats(output_dir) is not None
        ):
            # Unchanged input: the published snapshot is already this
            # content's transform — nothing to parse, nothing to write.
            return parquet_dir
        if (
            digest["size"] > prev_size
            and prev_size > 0
            and digest["prefix_sha256"] == prev_input.get("sha256")
            and prev_input.get("newline_end")
            and state.get("header")
            and state.get("accum")
        ):
            delta_dir = _process_delta(
                input_csv, output_dir, parquet_dir, state, digest,
                feature_cols, label_col, positive_label,
            )
            if delta_dir is not None:
                return delta_dir
    return _process_full(
        input_csv, output_dir, parquet_dir, state, digest,
        feature_cols, label_col, positive_label,
        track_state=inc, limit=eff,
    )


def _header_line(input_csv: str) -> str:
    with open(input_csv, "rb") as f:
        return f.readline().decode()


def _accum_from(moments: dict, label_encoded: np.ndarray) -> dict:
    return {
        "features": moments,
        "label_pos": int(label_encoded.sum()),
        "rows": int(len(label_encoded)),
    }


def _stats_from_accum(accum: dict) -> dict:
    rows = int(accum["rows"])
    return {
        "rows": rows,
        "features": {
            name: _moments_stats(m) for name, m in accum["features"].items()
        },
        "label_rate": (accum["label_pos"] / rows) if rows else 0.0,
    }


def _process_full(
    input_csv: str,
    output_dir: str,
    parquet_dir: str,
    state: dict,
    digest: dict | None,
    feature_cols: list[str],
    label_col: str,
    positive_label: str,
    *,
    track_state: bool,
    limit: int | None = None,
) -> str:
    """The reference-semantics full transform (z-score basis = this
    content's own stats), published with an atomic directory swap so a
    concurrent reader never sees a partial snapshot."""
    table = _read_csv_limited(input_csv, limit)
    out_cols, moments, basis, label_encoded = _transform_columns(
        table, feature_cols, label_col, positive_label
    )
    accum = _accum_from(moments, label_encoded)
    stats = _stats_from_accum(accum)

    # Previous run's raw stats (read BEFORE anything is overwritten):
    # the drift baseline for continuous training's daily re-run.
    prev_stats = read_previous_stats(output_dir)

    # Build the new snapshot beside the live one, then swap: readers of
    # the live directory race only against two renames, never against
    # the parquet write itself (mode("overwrite") semantics preserved —
    # the previous output is gone when this returns).
    build_dir = f"{parquet_dir}.build.{os.getpid()}"
    if os.path.isdir(build_dir):
        shutil.rmtree(build_dir)
    os.makedirs(build_dir)
    _publish_part(build_dir, "part-00000.parquet", out_cols)
    # Spark writes a _SUCCESS marker on commit; downstream checks may rely on it.
    open(os.path.join(build_dir, "_SUCCESS"), "w").close()
    trash_dir = f"{parquet_dir}.old.{os.getpid()}"
    if os.path.isdir(trash_dir):
        shutil.rmtree(trash_dir)
    if os.path.isdir(parquet_dir):
        os.rename(parquet_dir, trash_dir)
    os.rename(build_dir, parquet_dir)
    if os.path.isdir(trash_dir):
        shutil.rmtree(trash_dir)

    persist_stats_and_drift(output_dir, stats, prev_stats)
    if not track_state:
        # A forced non-incremental rebuild rewrote the snapshot under a
        # NEW normalization basis; any earlier incremental state is now
        # a lie — a later incremental call trusting its prefix digest
        # would append delta rows that the rebuild already transformed
        # (duplicated rows under a mixed basis). Invalidate it so the
        # next incremental run starts from a fresh full pass.
        try:
            os.remove(os.path.join(output_dir, ETL_STATE_NAME))
        except OSError:
            pass
        return parquet_dir
    if digest is None:
        digest = _digest_input(input_csv, limit=limit)
    generation = int(state.get("generation") or 0) + 1
    snap_nid = _record_lineage(
        input_csv, parquet_dir, digest, basis, state,
        generation=generation, mode="full", rows=stats["rows"],
    )
    _write_etl_state(output_dir, {
        "version": ETL_STATE_VERSION,
        "generation": generation,
        "mode": "full",
        "input": {
            "size": digest["size"],
            "sha256": digest["sha256"],
            "newline_end": digest["newline_end"],
        },
        "header": _header_line(input_csv),
        "arrival_ts": os.path.getmtime(input_csv),
        "parts": 1,
        "rows": stats["rows"],
        "norm_basis": basis,
        "accum": accum,
        "lineage_node": snap_nid,
    })
    return parquet_dir


def _record_lineage(
    input_csv: str,
    parquet_dir: str,
    digest: dict,
    basis: dict,
    prev_state: dict,
    *,
    generation: int,
    mode: str,
    rows: int,
) -> str | None:
    """Record this generation's provenance into the lineage ledger
    (:mod:`dct_tpu.observability.lineage`): the ingest delta (the raw
    CSV at its already-computed content digest), the frozen
    normalization basis (content-addressed from the basis dict, so a
    delta run under the same basis lands on the SAME node the full run
    minted), and the published snapshot directory — with the edges that
    make "which runs consumed delta X?" a graph walk. Returns the
    snapshot's node id, which the caller stamps into ``etl_state.json``
    so the trainer links its checkpoints to the exact snapshot without
    re-hashing gigabytes of parquet. Best-effort by the ledger's own
    contract: a disabled/dead ledger makes this a no-op returning None.
    """
    from dct_tpu.observability import lineage as _lineage

    lin = _lineage.get_default()
    if not lin.enabled:
        return None
    delta_nid = lin.node(
        "ingest_delta", path=input_csv, sha256=digest["sha256"],
        attrs={"mode": mode, "generation": generation, "rows": rows},
    )
    basis_nid = lin.node(
        "etl_basis", content=basis, attrs={"generation": generation},
    )
    snap_nid = lin.node(
        "dataset_snapshot", path=parquet_dir,
        attrs={"generation": generation, "mode": mode, "rows": rows},
    )
    lin.edge("produced", delta_nid, snap_nid)
    if mode == "full":
        # A full pass derives the basis FROM this delta; a delta run
        # reuses the frozen basis (consumed, below) without re-producing.
        lin.edge("produced", delta_nid, basis_nid)
    lin.edge("consumed", snap_nid, basis_nid)
    # Generation chain: an appended snapshot grew out of the previous
    # one, so ancestry from any checkpoint reaches every delta that
    # ever fed its training data.
    lin.edge("consumed", snap_nid, prev_state.get("lineage_node"))
    return snap_nid


def _process_delta(
    input_csv: str,
    output_dir: str,
    parquet_dir: str,
    state: dict,
    digest: dict,
    feature_cols: list[str],
    label_col: str,
    positive_label: str,
) -> str | None:
    """Append-only growth: transform only the tail rows into a new part
    file under the frozen normalization basis. Returns None when the
    delta would stretch the basis past ``DCT_ETL_REBUILD_TOL`` (the
    caller then runs the full rebuild) — correctness over speed."""
    basis = state.get("norm_basis") or {}
    prev_accum = state.get("accum") or {}
    if set(basis) != set(feature_cols) or set(
        prev_accum.get("features") or {}
    ) != set(feature_cols):
        return None  # schema changed under the state: rebuild
    table = _read_delta_table(
        input_csv, state["header"], int(state["input"]["size"]),
        int(digest["size"]),
    )
    out_cols, delta_moments, _, delta_labels = _transform_columns(
        table, feature_cols, label_col, positive_label, basis=basis
    )
    merged_features = {
        name: _chan_merge(prev_accum["features"][name], delta_moments[name])
        for name in feature_cols
    }
    merged_stats_by_name = {
        name: _moments_stats(m) for name, m in merged_features.items()
    }
    if _basis_stale(basis, merged_stats_by_name, _rebuild_tolerance()):
        return None
    accum = {
        "features": merged_features,
        "label_pos": int(prev_accum["label_pos"]) + int(delta_labels.sum()),
        "rows": int(prev_accum["rows"]) + int(len(delta_labels)),
    }
    stats = _stats_from_accum(accum)
    prev_stats = read_previous_stats(output_dir)

    part_index = int(state.get("parts") or 1)
    _publish_part(parquet_dir, f"part-{part_index:05d}.parquet", out_cols)
    # Ordering: part published BEFORE stats/state, so a reader that saw
    # generation N in the state can always load generation N's rows.
    persist_stats_and_drift(output_dir, stats, prev_stats)
    generation = int(state.get("generation") or 0) + 1
    snap_nid = _record_lineage(
        input_csv, parquet_dir, digest, basis, state,
        generation=generation, mode="delta", rows=stats["rows"],
    )
    _write_etl_state(output_dir, {
        "version": ETL_STATE_VERSION,
        "generation": generation,
        "mode": "delta",
        "input": {
            "size": digest["size"],
            "sha256": digest["sha256"],
            "newline_end": digest["newline_end"],
        },
        "header": state["header"],
        "arrival_ts": os.path.getmtime(input_csv),
        "parts": part_index + 1,
        "rows": stats["rows"],
        "rows_delta": int(len(delta_labels)),
        "norm_basis": basis,
        "accum": accum,
        "lineage_node": snap_nid,
    })
    return parquet_dir


def read_previous_stats(output_dir: str) -> dict | None:
    """The previous run's stats.json, or None when absent/torn — a torn
    baseline (killed mid-write before atomic writes, or hand-edited)
    must not brick the daily ETL over an observability feature."""
    stats_path = os.path.join(output_dir, "stats.json")
    if not os.path.exists(stats_path):
        return None
    try:
        with open(stats_path) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError):
        return None


def persist_stats_and_drift(
    output_dir: str, stats: dict, prev_stats: dict | None
) -> dict | None:
    """Atomically write stats.json and (when a baseline exists) the
    drift_report.json + console warning. Shared by the native and Spark
    ETL paths — both engines compute the same per-feature mean/std, so
    the drift logic lives once. Returns the report (or None)."""
    stats_path = os.path.join(output_dir, "stats.json")
    # Atomic: a run killed mid-write must not leave a torn baseline.
    tmp_stats = stats_path + ".tmp"
    with open(tmp_stats, "w") as f:
        json.dump(stats, f, indent=2)
    os.replace(tmp_stats, stats_path)
    if prev_stats is None:
        return None
    report = detect_drift(prev_stats, stats)
    report_path = os.path.join(output_dir, "drift_report.json")
    tmp_report = report_path + ".tmp"
    with open(tmp_report, "w") as f:
        json.dump(report, f, indent=2)
    os.replace(tmp_report, report_path)
    if report["any_drift"]:
        drifted = [k for k, v in report["features"].items() if v["drifted"]]
        if report["label_drifted"]:
            drifted.append("label_rate")
        print(
            f"⚠ DATA DRIFT vs previous run (threshold "
            f"{report['threshold']}): {', '.join(drifted)} — see "
            f"{report_path}",
            flush=True,
        )
    return report
