"""Native ETL: weather.csv -> normalized parquet, Spark-output-compatible.

Reproduces the exact data semantics of the reference Spark job
(jobs/preprocess.py):

- label encoding ``Rain == "rain" -> 1 else 0`` into ``label_encoded``
  (jobs/preprocess.py:23-25);
- per-column z-score normalization of the five features into ``*_norm``
  columns using mean and *sample* stddev (Spark ``stddev`` = stddev_samp,
  ddof=1) with a divide-by-zero guard (:32-41);
- output restricted to ``[*_norm, label_encoded]`` written as a parquet
  **directory** ``<out>/data.parquet`` containing part files, overwriting any
  previous run (:44-51) — so downstream readers built for Spark output work
  unchanged.

The north star keeps the real Spark cluster for production ETL (see
``dct_tpu/etl/spark_job.py``); this native path is the same transform without
a JVM for single-host runs, tests, and benches. It is vectorized numpy/arrow
on the host — ETL is IO-bound, not a TPU problem.
"""

from __future__ import annotations

import os
import shutil

import numpy as np

DEFAULT_FEATURES = ["Temperature", "Humidity", "Wind_Speed", "Cloud_Cover", "Pressure"]


def preprocess_csv_to_parquet(
    input_csv: str,
    output_dir: str,
    *,
    feature_cols: list[str] | None = None,
    label_col: str = "Rain",
    positive_label: str = "rain",
    parquet_name: str = "data.parquet",
) -> str:
    """Run the full ETL transform; returns the parquet directory path."""
    import pyarrow as pa
    import pyarrow.csv as pacsv
    import pyarrow.parquet as pq

    feature_cols = feature_cols or DEFAULT_FEATURES
    if not os.path.exists(input_csv):
        raise FileNotFoundError(f"Raw data not found at {input_csv}")

    table = pacsv.read_csv(input_csv)

    labels_raw = table.column(label_col).to_numpy(zero_copy_only=False)
    label_encoded = (labels_raw == positive_label).astype(np.int64)

    out_cols: dict[str, np.ndarray] = {}
    for name in feature_cols:
        col = table.column(name).to_numpy(zero_copy_only=False).astype(np.float64)
        mean = float(np.mean(col))
        # Spark's stddev is the sample stddev (ddof=1), jobs/preprocess.py:33.
        std = float(np.std(col, ddof=1)) if len(col) > 1 else 0.0
        std = std if std != 0.0 else 1.0
        out_cols[f"{name}_norm"] = (col - mean) / std
    out_cols["label_encoded"] = label_encoded

    out_table = pa.table(out_cols)

    parquet_dir = os.path.join(output_dir, parquet_name)
    # mode("overwrite") semantics: wipe the previous output directory.
    if os.path.isdir(parquet_dir):
        shutil.rmtree(parquet_dir)
    os.makedirs(parquet_dir, exist_ok=True)
    pq.write_table(out_table, os.path.join(parquet_dir, "part-00000.parquet"))
    # Spark writes a _SUCCESS marker on commit; downstream checks may rely on it.
    open(os.path.join(parquet_dir, "_SUCCESS"), "w").close()
    return parquet_dir
