"""Native ETL: weather.csv -> normalized parquet, Spark-output-compatible.

Reproduces the exact data semantics of the reference Spark job
(jobs/preprocess.py):

- label encoding ``Rain == "rain" -> 1 else 0`` into ``label_encoded``
  (jobs/preprocess.py:23-25);
- per-column z-score normalization of the five features into ``*_norm``
  columns using mean and *sample* stddev (Spark ``stddev`` = stddev_samp,
  ddof=1) with a divide-by-zero guard (:32-41);
- output restricted to ``[*_norm, label_encoded]`` written as a parquet
  **directory** ``<out>/data.parquet`` containing part files, overwriting any
  previous run (:44-51) — so downstream readers built for Spark output work
  unchanged.

The north star keeps the real Spark cluster for production ETL (see
``dct_tpu/etl/spark_job.py``); this native path is the same transform without
a JVM for single-host runs, tests, and benches. It is vectorized numpy/arrow
on the host — ETL is IO-bound, not a TPU problem.

Continuous-training hygiene the reference lacks entirely: each run
persists the raw per-feature statistics beside the parquet
(``stats.json``) and compares them against the PREVIOUS run's
(:func:`detect_drift`), writing ``drift_report.json`` — so a daily
re-train on silently-shifted data is a visible event instead of a
mystery regression in val_loss. Thresholded on the standardized mean
shift (|Δmean|/σ_prev), the std ratio, and the label-rate shift;
``DCT_DRIFT_THRESHOLD`` tunes it.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np

DEFAULT_FEATURES = ["Temperature", "Humidity", "Wind_Speed", "Cloud_Cover", "Pressure"]


def detect_drift(
    prev: dict, new: dict, *, threshold: float | None = None
) -> dict:
    """Compare two runs' raw-data statistics.

    Per feature: ``mean_shift`` = |mean_new - mean_prev| / max(σ_prev,
    1e-12) (standardized, so 'moved by half a previous-σ' means the same
    for every feature) and ``std_ratio`` = σ_new/σ_prev; plus the label
    positive-rate shift. A feature drifts when mean_shift > threshold or
    std_ratio is outside [1/(1+t), 1+t]; the label drifts when its rate
    moves by more than t/2 absolute. Returns a JSON-able report with
    ``any_drift`` for pipeline gates."""
    if threshold is None:
        threshold = float(os.environ.get("DCT_DRIFT_THRESHOLD", "0.5"))
    feats = {}
    any_drift = False
    prev_feats = prev.get("features", {})
    new_feats = new.get("features", {})
    for name in sorted(set(prev_feats) | set(new_feats)):
        p, n = prev_feats.get(name), new_feats.get(name)
        if p is None or n is None:
            # Schema drift (column added/renamed/dropped) IS drift — the
            # exact silently-shifted-data event this detector exists for.
            any_drift = True
            feats[name] = {
                "drifted": True,
                "missing_in": "previous" if p is None else "current",
            }
            continue
        values = (p["mean"], p["std"], n["mean"], n["std"])
        if not all(np.isfinite(v) for v in values):
            # NaN stats (nulls in the raw CSV) would make every
            # comparison False; broken data must read as drifted.
            any_drift = True
            feats[name] = {"drifted": True, "non_finite_stats": True}
            continue
        sigma = max(abs(p["std"]), 1e-12)
        mean_shift = abs(n["mean"] - p["mean"]) / sigma
        std_ratio = (abs(n["std"]) + 1e-12) / sigma
        drifted = bool(
            mean_shift > threshold
            or std_ratio > 1.0 + threshold
            or std_ratio < 1.0 / (1.0 + threshold)
        )
        any_drift |= drifted
        feats[name] = {
            "mean_shift": round(mean_shift, 4),
            "std_ratio": round(std_ratio, 4),
            "drifted": drifted,
        }
    label_shift = abs(
        new.get("label_rate", 0.0) - prev.get("label_rate", 0.0)
    )
    # Label rates live in [0, 1]: clamp the derived threshold so a large
    # sigma-unit knob cannot silently disable label-drift detection.
    label_drifted = bool(label_shift > min(threshold / 2, 0.25))
    any_drift |= label_drifted
    return {
        "threshold": threshold,
        "features": feats,
        "label_rate_shift": round(label_shift, 4),
        "label_drifted": label_drifted,
        "rows_prev": int(prev.get("rows", 0)),
        "rows_new": int(new.get("rows", 0)),
        "any_drift": any_drift,
    }


def preprocess_csv_to_parquet(
    input_csv: str,
    output_dir: str,
    *,
    feature_cols: list[str] | None = None,
    label_col: str = "Rain",
    positive_label: str = "rain",
    parquet_name: str = "data.parquet",
) -> str:
    """Run the full ETL transform; returns the parquet directory path."""
    import pyarrow as pa
    import pyarrow.csv as pacsv
    import pyarrow.parquet as pq

    feature_cols = feature_cols or DEFAULT_FEATURES
    if not os.path.exists(input_csv):
        raise FileNotFoundError(f"Raw data not found at {input_csv}")

    table = pacsv.read_csv(input_csv)

    labels_raw = table.column(label_col).to_numpy(zero_copy_only=False)
    label_encoded = (labels_raw == positive_label).astype(np.int64)

    out_cols: dict[str, np.ndarray] = {}
    stats = {"rows": int(len(label_encoded)), "features": {}}
    for name in feature_cols:
        col = table.column(name).to_numpy(zero_copy_only=False).astype(np.float64)
        mean = float(np.mean(col))
        # Spark's stddev is the sample stddev (ddof=1), jobs/preprocess.py:33.
        std = float(np.std(col, ddof=1)) if len(col) > 1 else 0.0
        stats["features"][name] = {"mean": mean, "std": std}
        std = std if std != 0.0 else 1.0
        out_cols[f"{name}_norm"] = (col - mean) / std
    out_cols["label_encoded"] = label_encoded
    stats["label_rate"] = float(np.mean(label_encoded)) if len(
        label_encoded
    ) else 0.0

    out_table = pa.table(out_cols)

    # Previous run's raw stats (read BEFORE anything is overwritten):
    # the drift baseline for continuous training's daily re-run.
    prev_stats = read_previous_stats(output_dir)

    parquet_dir = os.path.join(output_dir, parquet_name)
    # mode("overwrite") semantics: wipe the previous output directory.
    if os.path.isdir(parquet_dir):
        shutil.rmtree(parquet_dir)
    os.makedirs(parquet_dir, exist_ok=True)
    pq.write_table(out_table, os.path.join(parquet_dir, "part-00000.parquet"))
    # Spark writes a _SUCCESS marker on commit; downstream checks may rely on it.
    open(os.path.join(parquet_dir, "_SUCCESS"), "w").close()

    persist_stats_and_drift(output_dir, stats, prev_stats)
    return parquet_dir


def read_previous_stats(output_dir: str) -> dict | None:
    """The previous run's stats.json, or None when absent/torn — a torn
    baseline (killed mid-write before atomic writes, or hand-edited)
    must not brick the daily ETL over an observability feature."""
    stats_path = os.path.join(output_dir, "stats.json")
    if not os.path.exists(stats_path):
        return None
    try:
        with open(stats_path) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError):
        return None


def persist_stats_and_drift(
    output_dir: str, stats: dict, prev_stats: dict | None
) -> dict | None:
    """Atomically write stats.json and (when a baseline exists) the
    drift_report.json + console warning. Shared by the native and Spark
    ETL paths — both engines compute the same per-feature mean/std, so
    the drift logic lives once. Returns the report (or None)."""
    stats_path = os.path.join(output_dir, "stats.json")
    # Atomic: a run killed mid-write must not leave a torn baseline.
    tmp_stats = stats_path + ".tmp"
    with open(tmp_stats, "w") as f:
        json.dump(stats, f, indent=2)
    os.replace(tmp_stats, stats_path)
    if prev_stats is None:
        return None
    report = detect_drift(prev_stats, stats)
    report_path = os.path.join(output_dir, "drift_report.json")
    tmp_report = report_path + ".tmp"
    with open(tmp_report, "w") as f:
        json.dump(report, f, indent=2)
    os.replace(tmp_report, report_path)
    if report["any_drift"]:
        drifted = [k for k, v in report["features"].items() if v["drifted"]]
        if report["label_drifted"]:
            drifted.append("label_rate")
        print(
            f"⚠ DATA DRIFT vs previous run (threshold "
            f"{report['threshold']}): {', '.join(drifted)} — see "
            f"{report_path}",
            flush=True,
        )
    return report
