"""Spark variant of the ETL transform (import-gated; cluster-side only).

Same semantics as :mod:`dct_tpu.etl.preprocess` and as the reference job
(jobs/preprocess.py:18-51): header+inferSchema CSV read, ``Rain=="rain"->1``
label encoding, per-column mean/sample-stddev z-score with zero-std guard,
output restricted to ``[*_norm, label_encoded]`` written overwrite-mode to
``<out>/data.parquet``. Used when the platform runs the real Spark cluster
(docker-compose topology, SURVEY §2.1); tests cover the native path and the
transform parity between the two.
"""

from __future__ import annotations

import os

from dct_tpu.etl.preprocess import (
    DEFAULT_FEATURES,
    persist_stats_and_drift,
    read_previous_stats,
)


def preprocess_with_spark(
    input_csv: str,
    output_dir: str,
    *,
    feature_cols: list[str] | None = None,
    label_col: str = "Rain",
    positive_label: str = "rain",
    parquet_name: str = "data.parquet",
) -> str:
    from pyspark.sql import SparkSession
    from pyspark.sql.functions import col, count, mean, stddev, when

    feature_cols = feature_cols or DEFAULT_FEATURES
    spark = SparkSession.builder.appName("WeatherPreprocessingTPU").getOrCreate()
    try:
        df = spark.read.csv(input_csv, header=True, inferSchema=True)
        df = df.withColumn(
            "label_encoded", when(col(label_col) == positive_label, 1).otherwise(0)
        )
        # ONE aggregation pass for every statistic (row count, label
        # rate, per-feature mean/stddev) instead of 2 + N actions over
        # the un-cached DataFrame.
        aggs = [count("*").alias("__rows"), mean(col("label_encoded")).alias("__rate")]
        for name in feature_cols:
            aggs.append(mean(col(name)).alias(f"__m_{name}"))
            aggs.append(stddev(col(name)).alias(f"__s_{name}"))
        row = df.select(*aggs).first()

        def _stat(v):
            # Spark returns None for all-null columns: record NaN (like
            # the native path) so detect_drift's non-finite branch flags
            # the broken data instead of seeing a fabricated clean 0.0.
            return float(v) if v is not None else float("nan")

        run_stats: dict = {
            "rows": int(row["__rows"]),
            "label_rate": _stat(row["__rate"]),
            "features": {},
        }
        for name in feature_cols:
            m, s = row[f"__m_{name}"], row[f"__s_{name}"]
            run_stats["features"][name] = {"mean": _stat(m), "std": _stat(s)}
            std_val = s if s else 1.0
            df = df.withColumn(
                f"{name}_norm", (col(name) - (m or 0.0)) / std_val
            )
        final_cols = [f"{c}_norm" for c in feature_cols] + ["label_encoded"]
        # Baseline read BEFORE the overwrite, like the native path.
        prev_stats = read_previous_stats(output_dir)
        out_path = os.path.join(output_dir, parquet_name)
        df.select(final_cols).write.mode("overwrite").parquet(out_path)
        # Same drift machinery as the native engine (driver-side write:
        # output_dir is the shared ./data volume in the compose topology).
        persist_stats_and_drift(output_dir, run_stats, prev_stats)
        return out_path
    finally:
        spark.stop()
