"""Spark variant of the ETL transform (import-gated; cluster-side only).

Same semantics as :mod:`dct_tpu.etl.preprocess` and as the reference job
(jobs/preprocess.py:18-51): header+inferSchema CSV read, ``Rain=="rain"->1``
label encoding, per-column mean/sample-stddev z-score with zero-std guard,
output restricted to ``[*_norm, label_encoded]`` written overwrite-mode to
``<out>/data.parquet``. Used when the platform runs the real Spark cluster
(docker-compose topology, SURVEY §2.1); tests cover the native path and the
transform parity between the two.
"""

from __future__ import annotations

import os

from dct_tpu.etl.preprocess import DEFAULT_FEATURES


def preprocess_with_spark(
    input_csv: str,
    output_dir: str,
    *,
    feature_cols: list[str] | None = None,
    label_col: str = "Rain",
    positive_label: str = "rain",
    parquet_name: str = "data.parquet",
) -> str:
    from pyspark.sql import SparkSession
    from pyspark.sql.functions import col, mean, stddev, when

    feature_cols = feature_cols or DEFAULT_FEATURES
    spark = SparkSession.builder.appName("WeatherPreprocessingTPU").getOrCreate()
    try:
        df = spark.read.csv(input_csv, header=True, inferSchema=True)
        df = df.withColumn(
            "label_encoded", when(col(label_col) == positive_label, 1).otherwise(0)
        )
        for name in feature_cols:
            stats = df.select(
                mean(col(name)).alias("mean"), stddev(col(name)).alias("std")
            ).first()
            std_val = stats["std"] if stats["std"] else 1.0
            df = df.withColumn(f"{name}_norm", (col(name) - stats["mean"]) / std_val)
        final_cols = [f"{c}_norm" for c in feature_cols] + ["label_encoded"]
        out_path = os.path.join(output_dir, parquet_name)
        df.select(final_cols).write.mode("overwrite").parquet(out_path)
        return out_path
    finally:
        spark.stop()
