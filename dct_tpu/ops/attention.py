"""Attention ops: dense, blockwise (flash-style), and ring (sequence-parallel).

The reference has no attention anywhere (5-feature tabular MLP only,
SURVEY §5.7) — long-context support is a capability this framework adds, and
it is designed TPU-first rather than bolted on:

- :func:`dense_attention` — the O(T^2)-memory reference numerics; fine for
  short sequences, and the oracle the other paths are tested against.
- :func:`blockwise_attention` — online-softmax ``lax.scan`` over KV blocks:
  O(T) memory on a single chip, XLA fuses the inner block into MXU matmuls.
- :func:`ring_attention` — sequence parallelism over the mesh's ``seq``
  axis: each device keeps its Q shard and rotates KV shards around the ring
  with ``lax.ppermute`` (ICI neighbor hops — bandwidth-optimal, no
  all-gather), accumulating the same online softmax. Compute on the current
  block overlaps the DMA of the next block's permute in XLA's schedule.

All three share one accumulation kernel (:func:`_online_block`) so their
numerical equivalence is structural; tests assert it on an 8-device mesh.
The Pallas flash kernel (:mod:`dct_tpu.ops.pallas_attention`) slots in per
:func:`select_attention_path` — single-shard on TPU, and as the per-shard
block compute inside the ring.

Causal ring attention additionally supports the STRIPED ("zigzag")
layout: the contiguous P("seq") layout gives device i exactly i+1
visible KV shards, so the lock-stepped ring runs at the tail device's
pace — a ~2x load imbalance. Striping splits the sequence into 2R
chunks and hands device i chunks (i, 2R-1-i); every device then does
exactly two half-chunk blocks of visible work at every ring step
(:func:`striped_layout` derivation), so the causal ring is perfectly
balanced at the cost of one static sequence permutation each way.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

from dct_tpu.parallel.shard_map_compat import pcast_varying, shard_map
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -1e30  # finite "minus infinity": keeps the online max/exp NaN-free


def _online_block(q, k, v, scale, mask, m, l, o):
    """Fold one KV block into the running online-softmax state.

    q [..., Tq, D] · k,v [..., Tk, D] · mask broadcastable to [..., Tq, Tk]
    (True = attend) · m,l [..., Tq] f32 · o [..., Tq, D] f32.
    """
    s = jnp.einsum(
        "...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        # A fully-masked row would otherwise get p=exp(0)=1 per entry.
        p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    # P·V with operands in V's dtype (bf16 on the product path — f32 MXU
    # rate is a fraction of bf16's; accumulation stays f32 via
    # preferred_element_type). f32 inputs are untouched: p is already f32.
    o_new = o * alpha[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _finalize(l, o, dtype):
    return (o / jnp.maximum(l, 1e-20)[..., None]).astype(dtype)


def _check_window(window: int | None, causal: bool) -> None:
    """Op-layer window validation. ``None`` means full attention; "off"
    must never be spelled 0 here — a 0 band would make every row fully
    masked and softmax silently uniform over ALL positions (causality
    broken). The '0 = off' convention lives in the CONFIG layer
    (registry normalizes attn_window<=0 to None)."""
    if window is None:
        return
    if not causal:
        raise ValueError("window requires causal attention")
    if window < 1:
        raise ValueError(
            f"window must be >= 1 (got {window}); pass None for full "
            "causal attention"
        )


def expand_kv(q, k, v):
    """Grouped-query attention (GQA) KV expansion: K/V carry
    ``n_kv_heads`` heads with ``H % n_kv_heads == 0``; each KV head
    serves ``H/n_kv_heads`` consecutive query heads (the fused
    projection's group-major layout). Returns (k, v) broadcast to the
    full H — XLA fuses the broadcast into the downstream matmuls, and
    the paths where materializing would cost real bandwidth (the Pallas
    kernel, the SP engines' collectives) expand later or never
    (grouped index maps)."""
    h, hkv = q.shape[-3], k.shape[-3]
    if h == hkv:
        return k, v
    if h % hkv:
        raise ValueError(
            f"GQA needs q heads ({h}) divisible by kv heads ({hkv})"
        )
    group = h // hkv
    k = jnp.repeat(k, group, axis=-3)
    v = jnp.repeat(v, group, axis=-3)
    return k, v


def dense_attention(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    window: int | None = None,
):
    """Reference numerics: full [Tq, Tk] score matrix. q,k,v [B, H, T, D]
    (K/V may carry fewer GQA heads — :func:`expand_kv`).

    ``window`` (causal-only): position t attends to at most the last
    ``window`` positions [t-window+1, t] — sliding-window local
    attention (Mistral/Longformer-style), the standard long-context
    complement to sequence parallelism."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    _check_window(window, causal)
    k, v = expand_kv(q, k, v)
    s = jnp.einsum(
        "...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        pos_q = jnp.arange(tq)[:, None]
        pos_k = jnp.arange(tk)[None, :]
        mask = pos_q >= pos_k
        if window is not None:
            mask &= pos_q - pos_k < window
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


def _blockwise_stats(q, k, v, *, block_size: int, causal: bool,
                     scale: float | None, window: int | None = None,
                     q_offset: int = 0):
    """Shared blockwise scan returning the raw online-softmax state
    (m, l, o) — finalized by the callers into output (and optionally lse).

    ``q_offset`` shifts the q positions relative to k's (both default to
    0-based): the windowed flash ring passes the static inter-shard
    distance here so its partial-band shards reuse this O(T*block)-memory
    scan instead of materializing a full [Tq, Tk] mask."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    _check_window(window, causal)
    k, v = expand_kv(q, k, v)
    t = k.shape[-2]
    if t % block_size:
        raise ValueError(f"seq len {t} not a multiple of block {block_size}")
    n_blocks = t // block_size
    tq = q.shape[-2]

    # [n_blocks, ..., block, D] scan layout.
    ks = jnp.moveaxis(k.reshape(*k.shape[:-2], n_blocks, block_size, k.shape[-1]), -3, 0)
    vs = jnp.moveaxis(v.reshape(*v.shape[:-2], n_blocks, block_size, v.shape[-1]), -3, 0)

    q_pos = q_offset + jnp.arange(tq)

    def body(carry, blk):
        m, l, o = carry
        kb, vb, b_idx = blk
        mask = None
        if causal:
            k_pos = b_idx * block_size + jnp.arange(block_size)
            mask = q_pos[:, None] >= k_pos[None, :]
            if window is not None:
                # Sliding window: blocks fully outside every row's window
                # contribute an all-False mask (their p rows zero out);
                # XLA's scan keeps the shape static — the win is HBM and
                # numerics, not skipped FLOPs (the Pallas kernel's tile
                # skip is the FLOPs lever, single-shard TPU only).
                mask &= q_pos[:, None] - k_pos[None, :] < window
        m, l, o = _online_block(q, kb, vb, scale, mask, m, l, o)
        return (m, l, o), None

    m0 = jnp.full(q.shape[:-1], _NEG, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)
    (m, l, o), _ = lax.scan(body, (m0, l0, o0), (ks, vs, jnp.arange(n_blocks)))
    return m, l, o


def blockwise_attention(
    q, k, v, *, block_size: int = 512, causal: bool = False,
    scale: float | None = None, window: int | None = None,
):
    """O(T)-memory attention on one device: scan KV in blocks of
    ``block_size`` through the shared online-softmax kernel. q,k,v
    [B, H, T, D]; T must be a multiple of block_size (pad upstream).
    ``window``: causal sliding-window local attention."""
    m, l, o = _blockwise_stats(
        q, k, v, block_size=block_size, causal=causal, scale=scale,
        window=window,
    )
    return _finalize(l, o, q.dtype)


def blockwise_attention_lse(
    q, k, v, *, block_size: int = 512, causal: bool = False,
    scale: float | None = None, window: int | None = None,
    q_offset: int = 0,
):
    """Blockwise attention returning (o, lse [..., T] f32) — the JAX-level
    twin of :func:`dct_tpu.ops.pallas_attention.flash_attention_lse`, used
    as its rematerialized backward (incl. the windowed/offset variants the
    ring's partial-band shards run)."""
    m, l, o = _blockwise_stats(
        q, k, v, block_size=block_size, causal=causal, scale=scale,
        window=window, q_offset=q_offset,
    )
    return _finalize(l, o, q.dtype), m + jnp.log(jnp.maximum(l, 1e-20))


def flash_interpret_mode() -> bool | None:
    """Resolve whether the Pallas flash kernel is usable here, and how.

    Returns False (real Mosaic kernel), True (interpret mode), or None
    (don't use flash). Policy, overridable via ``DCT_FLASH``:

    - ``auto`` (default): Mosaic on the TPU backend; None elsewhere —
      interpret mode is orders of magnitude slower than XLA's fused
      blockwise path, so CPU rigs fall back unless they opt in.
    - ``interpret``: force interpret mode (CPU test rigs).
    - ``on``/``1``: Mosaic on TPU, interpret elsewhere.
    - ``off``/``0``: never.
    """
    mode = os.environ.get("DCT_FLASH", "auto").strip().lower()
    on_tpu = jax.default_backend() == "tpu"
    if mode in ("off", "0", "false", "no"):
        return None
    if mode == "interpret":
        return True
    if mode in ("on", "1", "true", "yes"):
        return False if on_tpu else True
    return False if on_tpu else None


def _resolve_flash(use_flash: bool | None) -> tuple[bool, bool | None]:
    """Shared tri-state resolution for the SP engines: returns
    (flash_on, interpret). ``use_flash`` None follows the
    :func:`flash_interpret_mode` policy; True forces flash (interpret
    everywhere except a real TPU backend); False disables it."""
    interpret = flash_interpret_mode()
    if use_flash is None:
        return interpret is not None, interpret
    if use_flash:
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return True, interpret
    return False, interpret


def sp_engine() -> str:
    """The sequence-parallel engine policy (``DCT_SP_ENGINE``):
    'ring' (default — KV shards rotate with ppermute, O(T/sp) memory) or
    'a2a' (Ulysses-style head<->seq all_to_all exchange)."""
    engine = os.environ.get("DCT_SP_ENGINE", "ring").strip().lower()
    if engine not in ("ring", "a2a"):
        raise ValueError(f"DCT_SP_ENGINE={engine!r} must be 'ring' or 'a2a'")
    return engine


def select_attention_path(
    t: int, *, mesh: Mesh | None = None, block_size: int = 512,
    flash_block: int = 128, flash_min_len: int = 256,
) -> str:
    """The attention-path policy, exposed for tests and the bench:
    'ring' | 'a2a' | 'flash' | 'blockwise' | 'dense'. ``t`` is the
    (single-shard) sequence length."""
    if mesh is not None and mesh.shape.get("seq", 1) > 1:
        return sp_engine()
    if (
        flash_interpret_mode() is not None
        and t >= flash_min_len
        and t % flash_block == 0
    ):
        return "flash"
    if t > block_size and t % block_size == 0:
        return "blockwise"
    return "dense"


def striped_layout(t: int, ring_size: int):
    """Striped ("zigzag") sequence layout for balanced causal ring
    attention.

    Splits ``t`` positions into ``2*ring_size`` chunks; device i holds
    chunks (i, 2R-1-i) concatenated. Under a causal mask, chunk x sees
    chunk y fully iff y < x and diagonally iff y == x, so at ring step s
    every device's visible work is exactly two half-shard blocks:

    - step 0 (src == my): diag(A_my) + full(B_my, A_my) + diag(B_my)
    - src < my:            full(A_my, A_src) + full(B_my, A_src)
    - src > my:            full(B_my, A_src) + full(B_my, B_src)

    (A_i = chunk i, B_i = chunk 2R-1-i; B_my sees every A_src because
    2R-1-my >= R > src, and never the other way.) Returns ``(perm,
    inv)`` int arrays: ``x[..., perm, :]`` reorders a contiguous
    sequence into striped layout, ``o[..., inv, :]`` undoes it.
    """
    if t % (2 * ring_size):
        raise ValueError(
            f"striped layout needs seq len {t} % {2 * ring_size} == 0"
        )
    c = t // (2 * ring_size)
    order = []
    for i in range(ring_size):
        order.extend(range(i * c, (i + 1) * c))
        j = 2 * ring_size - 1 - i
        order.extend(range(j * c, (j + 1) * c))
    perm = np.asarray(order, np.int32)
    inv = np.argsort(perm).astype(np.int32)
    return perm, inv


def _merge_lse(o, lse, o_j, lse_j):
    """Fold a finalized (o_j, lse_j) attention block into the running
    (o, lse) pair: softmax-weighted combine — the online-softmax update
    factored across already-normalized results."""
    lse_new = jnp.logaddexp(lse, lse_j)
    w = jnp.exp(lse - lse_new)[..., None]
    w_j = jnp.exp(lse_j - lse_new)[..., None]
    return o * w + o_j.astype(jnp.float32) * w_j, lse_new


def _ring_window_steps(window: int | None, t_local: int, ring_size: int) -> int:
    """How many CONTIGUOUS-layout ring steps can contribute under a causal
    sliding window: step s >= 1 consumes the shard ``s`` hops back, whose
    minimum q-k distance is (s-1)*t_local + 1 — once that reaches
    ``window`` every later shard is fully out of band for EVERY device,
    so both the block compute and the ppermute hops stop. This is the
    windowed ring's asymptotic win: O(window) work and communication per
    device instead of O(T)."""
    if window is None:
        return ring_size
    return min(ring_size, (window - 1 + t_local - 1) // t_local + 1)


def _ring_body_flash(q, k, v, *, axis_name: str, ring_size: int,
                     causal: bool, scale: float | None, interpret: bool,
                     block_q: int = 128, block_k: int = 128,
                     window: int | None = None):
    """Ring attention whose per-shard block compute is the Pallas flash
    kernel. Runs inside shard_map on LOCAL shards [B, h_local, T_local, D].

    Causal structure over ring steps (my = this device's seq index,
    src = origin of the current KV shard = (my - step) mod ring):
    step 0 is always the diagonal shard (standard causal mask, offsets
    cancel); for step >= 1 the shard is either fully visible (src < my,
    i.e. my >= step) or fully masked — so only two STATIC kernel variants
    are needed, selected by a traced ``lax.cond``. Fully-masked steps
    contribute (o=0, lse=-inf) and vanish in the merge.

    ``window`` (causal sliding window) refines the step analysis with
    STATIC per-step distance bounds (q-k distance at step s spans
    [(s-1)L+1, (s+1)L-1], L = T_local): fully-in-band shards run the
    plain flash kernel, partial band shards run the SAME kernel with its
    in-kernel band mask and the static inter-shard distance as
    ``q_offset`` (out-of-band tiles skip compute and DMA), and
    fully-out-of-band steps are not executed at all —
    :func:`_ring_window_steps` truncates the ring, so far KV shards are
    neither computed NOR communicated."""
    from dct_tpu.ops.pallas_attention import flash_attention_lse

    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    my = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]
    n_steps = _ring_window_steps(window, t_local, ring_size)

    def call(q_, k_, v_, causal_, window_=None, q_offset=0):
        return flash_attention_lse(
            q_, k_, v_, block_q, block_k, causal_, scale, interpret,
            window_, q_offset,
        )

    k_cur, v_cur = k, v
    o = None
    for step in range(n_steps):  # static unroll: ring_size is mesh shape
        if step == 0:
            if window is not None and window < t_local:
                o_j, lse_j = call(q, k_cur, v_cur, True, window, 0)
                o, lse = o_j.astype(jnp.float32), lse_j
            else:
                o_j, lse_j = call(q, k_cur, v_cur, causal)
                o, lse = o_j.astype(jnp.float32), lse_j
        else:
            if causal:
                d_max = (step + 1) * t_local - 1
                if window is not None and d_max >= window:
                    # Partial band shard: windowed kernel, q shifted by
                    # the static inter-shard distance.
                    o_j, lse_j = lax.cond(
                        my >= step,
                        lambda kc=k_cur, vc=v_cur, s=step: call(
                            q, kc, vc, True, window, s * t_local
                        ),
                        lambda: (
                            jnp.zeros(q.shape, q.dtype),
                            jnp.full(q.shape[:-1], _NEG, jnp.float32),
                        ),
                    )
                else:
                    o_j, lse_j = lax.cond(
                        my >= step,
                        lambda kc=k_cur, vc=v_cur: call(q, kc, vc, False),
                        lambda: (
                            jnp.zeros(q.shape, q.dtype),
                            jnp.full(q.shape[:-1], _NEG, jnp.float32),
                        ),
                    )
            else:
                o_j, lse_j = call(q, k_cur, v_cur, False)
            o, lse = _merge_lse(o, lse, o_j, lse_j)
        if step < n_steps - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return o.astype(q.dtype)


def _ring_body_flash_striped(q, k, v, *, axis_name: str, ring_size: int,
                             scale: float | None, interpret: bool,
                             block_q: int = 128, block_k: int = 128):
    """Balanced CAUSAL ring attention on the striped layout, flash
    per-shard compute. Local shards are [B, h, L, D] in striped order
    (first half = chunk ``my``, second half = chunk ``2R-1-my``; see
    :func:`striped_layout` for the three-case visibility analysis).
    Every ring step costs exactly two half-chunk flash blocks on every
    device — the causal ring's tail-device bottleneck is gone."""
    from dct_tpu.ops.pallas_attention import flash_attention_lse

    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    my = lax.axis_index(axis_name)
    half = q.shape[-2] // 2
    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]

    def call(q_, k_, v_, causal_):
        return flash_attention_lse(
            q_, k_, v_, block_q, block_k, causal_, scale, interpret
        )

    q1, q2 = q[..., :half, :], q[..., half:, :]
    k_cur, v_cur = k, v

    # Step 0: the diagonal shard. A_my is causal over itself; B_my sees
    # all of A_my plus its own causal diagonal.
    k1, v1 = k_cur[..., :half, :], v_cur[..., :half, :]
    k2, v2 = k_cur[..., half:, :], v_cur[..., half:, :]
    o1_0, lse1 = call(q1, k1, v1, True)
    o2a, lse2a = call(q2, k1, v1, False)
    o2b, lse2b = call(q2, k2, v2, True)
    o1 = o1_0.astype(jnp.float32)
    o2, lse2 = _merge_lse(o2a.astype(jnp.float32), lse2a, o2b, lse2b)

    for step in range(1, ring_size):  # static unroll: ring_size is static
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)

        def visible_low(kc=k_cur, vc=v_cur):
            # src < my: both halves of q see A_src fully, B_src never.
            oa, la = call(q, kc[..., :half, :], vc[..., :half, :], False)
            return (
                oa[..., :half, :], la[..., :half],
                oa[..., half:, :], la[..., half:],
            )

        def visible_high(kc=k_cur, vc=v_cur):
            # src > my: A_my sees nothing, B_my sees the whole shard.
            ob, lb = call(q2, kc, vc, False)
            return (
                jnp.zeros(q1.shape, q.dtype),
                jnp.full(q1.shape[:-1], _NEG, jnp.float32),
                ob, lb,
            )

        c1o, c1l, c2o, c2l = lax.cond(my >= step, visible_low, visible_high)
        o1, lse1 = _merge_lse(o1, lse1, c1o, c1l)
        o2, lse2 = _merge_lse(o2, lse2, c2o, c2l)

    return jnp.concatenate([o1, o2], axis=-2).astype(q.dtype)


def _ring_body(q, k, v, *, axis_name: str, ring_size: int, causal: bool,
               scale: float | None, vary_axes: tuple = (),
               striped: bool = False, window: int | None = None):
    """Per-shard ring attention (runs inside shard_map).

    q,k,v are the LOCAL shards [B, h_local, T_local, D]. Each of the
    ``ring_size`` steps consumes the KV shard that originated on device
    ``(my_index - step) mod ring_size`` and then forwards it to the next
    neighbor — a classic ICI ring pipeline. With ``striped`` the local
    shard is in :func:`striped_layout` order and the causal mask is
    built from the striped GLOBAL positions instead of contiguous ones.

    ``window`` (causal sliding window, VERDICT r3 item 6) adds the
    ``q_pos - k_pos < window`` band to the mask — on GLOBAL positions, so
    it is correct for both layouts. Contiguous rings also truncate to
    :func:`_ring_window_steps` hops (far shards are neither computed nor
    communicated); striped rings keep all hops — each device's second
    chunk has near neighbors arriving late in the rotation — and instead
    skip the block compute of shards the band fully masks."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    my = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    n_steps = (
        ring_size if striped else _ring_window_steps(window, t_local, ring_size)
    )

    def positions(dev):
        if not striped:
            return dev * t_local + jnp.arange(t_local)
        c = t_local // 2
        return jnp.concatenate([
            dev * c + jnp.arange(c),
            (2 * ring_size - 1 - dev) * c + jnp.arange(c),
        ])

    q_pos = positions(my)
    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]

    # pcast-to-varying: the accumulators inherit q's device-varying axes
    # from the first iteration on; typing them that way up front keeps
    # every step's accumulator type fixed.
    axes = tuple(vary_axes) or (axis_name,)
    m = pcast_varying(jnp.full(q.shape[:-1], _NEG, jnp.float32), axes)
    l = pcast_varying(jnp.zeros(q.shape[:-1], jnp.float32), axes)
    o = pcast_varying(jnp.zeros(q.shape, jnp.float32), axes)
    k_cur, v_cur = k, v
    for step in range(n_steps):  # static unroll: ring_size is mesh shape
        src = (my - step) % ring_size
        mask = None
        if causal:
            k_pos = positions(src)
            d = q_pos[:, None] - k_pos[None, :]
            mask = d >= 0
            if window is not None:
                mask &= d < window
        # GQA: the ring rotates the GROUPED kv shards (ICI payload stays
        # at n_kv_heads); expansion to full heads happens per-use INSIDE
        # the branch that computes, so band-skipped steps pay neither the
        # matmuls nor the group-times KV materialization.
        if window is not None and (striped or step > 0):
            # Skip the QK/AV matmuls of shards the band fully masks (the
            # striped rotation interleaves near and far shards, so which
            # steps those are is traced, not static); the mask alone
            # would zero their contribution but still pay their FLOPs.
            # Step 0 of a contiguous ring is always the visible diagonal.
            m, l, o = lax.cond(
                jnp.any(mask),
                lambda kc=k_cur, vc=v_cur, mk=mask, m=m, l=l, o=o: (
                    _online_block(q, *expand_kv(q, kc, vc), scale, mk,
                                  m, l, o)
                ),
                lambda m=m, l=l, o=o: (m, l, o),
            )
        else:
            ke, ve = expand_kv(q, k_cur, v_cur)
            m, l, o = _online_block(q, ke, ve, scale, mask, m, l, o)
        if step < n_steps - 1:  # the truncated ring skips the far hops
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return _finalize(l, o, q.dtype)


def _is_init_trace_escape(q, b: int, n_data: int) -> bool:
    """Single-sourced policy for the SP engines' batch-1 dense escape.

    The batch-1 init trace (flax shape inference, jitted by
    create_train_state) cannot tile the data axis; dense attention is
    numerically identical there. Gated to (batch 1, tracer) so any OTHER
    undersized batch — eager misuse, or a jitted loader that skipped
    BatchLoader's divisibility guarantee — raises the engine's sizing
    error instead of silently replicating an O(T^2) global computation
    per device (ADVICE r3). Residual risk: a genuinely batch-1 jitted
    train step over a populated data axis would take this escape, but
    such a step cannot tile the mesh at all and BatchLoader refuses to
    produce it."""
    return b == 1 and b < n_data and isinstance(q, jax.core.Tracer)


def ring_attention(
    q, k, v, *, mesh: Mesh, causal: bool = False, scale: float | None = None,
    seq_axis: str = "seq", data_axis: str = "data", model_axis: str = "model",
    use_flash: bool | None = None, striped: bool | None = None,
    window: int | None = None,
):
    """Sequence-parallel attention over ``mesh[seq_axis]``.

    q,k,v: GLOBAL [B, H, T, D] arrays (jit-sharded); internally shard_mapped
    to [B, H/model, T/seq, D] per device. Batch rides ``data_axis``, heads
    ride ``model_axis`` — so DP x TP x SP compose in one op.

    ``use_flash``: True forces the Pallas flash per-shard block compute,
    False disables it, None (default) follows the
    :func:`flash_interpret_mode` policy. Interpret-vs-Mosaic is always
    resolved from the backend; the JAX-level online-softmax body is the
    fallback when flash is off or the local shard is not block-aligned.

    ``striped``: causal-only. True runs the :func:`striped_layout` ring
    (perfect per-step load balance — see module docstring); None follows
    the ``DCT_RING_STRIPED`` env policy — ``auto`` (default) enables it
    whenever the flash path is on and the half-chunk is kernel-aligned
    (that is where balance pays: the flash causal ring skips invisible
    shards, so the contiguous layout runs at the tail device's pace),
    ``on`` forces it for causal rings (like ``striped=True``), ``off``
    keeps the contiguous layout (the A/B baseline); False keeps the
    contiguous layout.

    ``window`` (causal sliding window): supported on every ring variant —
    the contiguous layouts truncate the ring to the in-band hops
    (:func:`_ring_window_steps`, O(window) work and communication per
    device instead of O(T)); the striped flash body has no band tiles,
    so windowed striped rings route to the JAX-level masked body.
    """
    ring_size = mesh.shape[seq_axis]
    b, h, t, _ = q.shape
    _check_window(window, causal)
    if striped and not causal:
        # Validate BEFORE any fallback: a non-causal layer misconfigured
        # with striped=True must fail at trace time, not pass the batch-1
        # init trace and surprise on the first real batch.
        raise ValueError("striped ring layout only applies to causal")
    if _is_init_trace_escape(q, b, mesh.shape[data_axis]):
        return dense_attention(q, k, v, causal=causal, scale=scale,
                               window=window)
    h_kv = k.shape[1]
    if (
        b % mesh.shape[data_axis]
        or h % mesh.shape[model_axis]
        or h_kv % mesh.shape[model_axis]
        or t % ring_size
    ):
        # Anything else is a sizing bug: silently falling back to dense
        # would discard sequence parallelism (and its O(T/P) memory bound)
        # on every step with no sign beyond the OOM/slowdown.
        raise ValueError(
            f"ring_attention shapes B={b}, H={h} (kv heads {h_kv}), T={t} "
            f"do not tile mesh axes data={mesh.shape[data_axis]}, "
            f"model={mesh.shape[model_axis]}, seq={ring_size}; adjust "
            "batch/heads/seq_len or the mesh"
        )
    spec = P(data_axis, model_axis, seq_axis, None)
    flash_on, interpret = _resolve_flash(use_flash)
    t_local = t // ring_size
    half = t_local // 2

    def flash_aligned(n: int) -> bool:
        # Mosaic tiles want 128-multiples. Interpret mode takes any size
        # as long as every extent the striped body passes (half-chunk Tq,
        # whole-shard Tq/Tk) divides its clamped block min(128, extent).
        if not interpret:
            return n % 128 == 0
        divisible = lambda e: e >= 1 and e % min(128, e) == 0
        return divisible(n) and divisible(t_local)
    if striped is None:
        # DCT_RING_STRIPED: "auto" (default — striped whenever the causal
        # flash ring is kernel-aligned), "0"/"off" (force contiguous,
        # the on-chip A/B baseline), "1"/"on" (striped even for the
        # JAX-level body).
        mode = os.environ.get("DCT_RING_STRIPED", "auto").strip().lower()
        if mode in ("0", "off", "false", "no"):
            striped = False
        elif mode in ("1", "on", "true", "yes"):
            # Forced on behaves like striped=True for causal rings
            # (below it raises on an odd t_local rather than silently
            # measuring the contiguous layout); non-causal rings have no
            # striped concept and are unaffected.
            striped = bool(causal and ring_size > 1)
        else:
            # Windowed rings skip out-of-band shards, so the contiguous
            # layout's causal imbalance mostly vanishes and the striped
            # flash body has no band support — auto keeps contiguous.
            striped = bool(
                causal
                and window is None
                and ring_size > 1
                and t_local % 2 == 0
                and flash_on
                and flash_aligned(half)
            )
    elif striped:
        if t_local % 2:
            raise ValueError(
                f"striped ring needs T/ring ({t_local}) even; got T={t}, "
                f"ring={ring_size}"
            )
    if striped:
        perm, inv = striped_layout(t, ring_size)
        if window is None and flash_on and flash_aligned(half):
            fn = functools.partial(
                _ring_body_flash_striped,
                axis_name=seq_axis,
                ring_size=ring_size,
                scale=scale,
                interpret=bool(interpret),
            )
            vma_kw = {"check_vma": False}
        else:
            fn = functools.partial(
                _ring_body,
                axis_name=seq_axis,
                ring_size=ring_size,
                causal=True,
                scale=scale,
                vary_axes=(data_axis, model_axis, seq_axis),
                striped=True,
                window=window,
            )
            vma_kw = {}
        qs, ks, vs = (jnp.take(a, perm, axis=-2) for a in (q, k, v))
        out = shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            **vma_kw,
        )(qs, ks, vs)
        return jnp.take(out, inv, axis=-2)
    if flash_on and t_local % 128 == 0 and t_local >= 128:
        fn = functools.partial(
            _ring_body_flash,
            axis_name=seq_axis,
            ring_size=ring_size,
            causal=causal,
            scale=scale,
            interpret=bool(interpret),
            window=window,
        )
        # check_vma=False: pallas interpret mode evaluates the kernel
        # jaxpr with non-varying internal consts, tripping the vma checker
        # (jax suggests exactly this workaround); numerics are unaffected.
        return shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)
    fn = functools.partial(
        _ring_body,
        axis_name=seq_axis,
        ring_size=ring_size,
        causal=causal,
        scale=scale,
        vary_axes=(data_axis, model_axis, seq_axis),
        window=window,
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def a2a_attention(
    q, k, v, *, mesh: Mesh, causal: bool = False, scale: float | None = None,
    seq_axis: str = "seq", data_axis: str = "data", model_axis: str = "model",
    use_flash: bool | None = None, block_size: int = 512,
    window: int | None = None,
):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism over
    ``mesh[seq_axis]`` — the second SP engine beside :func:`ring_attention`.

    One ``lax.all_to_all`` trades each device's sequence shard for a HEAD
    shard: [B, H/tp, T/sp, D] -> [B, H/(tp*sp), T, D]. Every device then
    holds the FULL sequence for its head subset and runs the best
    single-shard kernel (Pallas flash / blockwise / dense) with exact
    causal semantics — no per-step visibility bookkeeping, no striping
    needed for balance (causal work is identical per head). A second
    all_to_all restores the sequence layout.

    Trade-off vs the ring: two collectives total instead of sp-1 ppermute
    hops (latency win, and the a2a rides ICI), but the full [T] sequence
    must fit one device's memory for H/(tp*sp) heads, and heads must tile
    ``tp*sp``. Select per workload with ``DCT_SP_ENGINE`` (ring | a2a) —
    ring for the longest sequences (O(T/sp) memory), a2a when heads are
    plentiful and T fits.

    q,k,v: GLOBAL [B, H, T, D] arrays (jit-sharded); batch rides
    ``data_axis``, heads ``model_axis`` — DP x TP x SP compose in one op.
    """
    sp = mesh.shape[seq_axis]
    b, h, t, _ = q.shape
    if _is_init_trace_escape(q, b, mesh.shape[data_axis]):
        return dense_attention(
            q, k, v, causal=causal, scale=scale, window=window
        )
    tp = mesh.shape[model_axis]
    h_kv = k.shape[1]
    h_local = h // tp if h % tp == 0 else 0
    hkv_local = h_kv // tp if h_kv % tp == 0 else 0
    if (
        b % mesh.shape[data_axis]
        or h % tp
        or h_kv % tp
        or t % sp
        or h_local % sp
        or hkv_local % sp
    ):
        alternative = "or use DCT_SP_ENGINE=ring"
        raise ValueError(
            f"a2a_attention shapes B={b}, H={h} (kv heads {h_kv}), T={t} "
            f"do not tile mesh axes data={mesh.shape[data_axis]}, "
            f"model={tp}, seq={sp} (the seq axis must divide the heads "
            f"per TP shard: H/tp={h_local}, kv/tp={hkv_local}, sp={sp}); "
            f"adjust heads/seq_len or the mesh, {alternative}"
        )
    spec = P(data_axis, model_axis, seq_axis, None)
    flash_on, interpret = _resolve_flash(use_flash)

    def _kernel(ql, kl, vl):
        # Full-sequence single-shard compute on [B_l, H_l/sp, T, D] —
        # each device sees every position for its heads, so windowing is
        # just the single-shard (in-kernel) band mask.
        if flash_on and t % 128 == 0 and t >= 128:
            from dct_tpu.ops.pallas_attention import flash_attention

            return flash_attention(
                ql, kl, vl, causal=causal, scale=scale,
                interpret=bool(interpret), window=window,
            )
        if t > block_size and t % block_size == 0:
            return blockwise_attention(
                ql, kl, vl, block_size=block_size, causal=causal,
                scale=scale, window=window,
            )
        return dense_attention(
            ql, kl, vl, causal=causal, scale=scale, window=window
        )

    def body(ql, kl, vl):
        # seq shard -> head shard: [B_l, H_l, T_l, D] -> [B_l, H_l/sp, T, D]
        ql, kl, vl = (
            lax.all_to_all(a, seq_axis, split_axis=1, concat_axis=2,
                           tiled=True)
            for a in (ql, kl, vl)
        )
        out = _kernel(ql, kl, vl)
        # head shard -> seq shard (the inverse exchange).
        return lax.all_to_all(
            out, seq_axis, split_axis=2, concat_axis=1, tiled=True
        )

    # check_vma=False for the same reason as the flash ring: interpret-
    # mode pallas internals trip the varying-axes checker spuriously.
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def make_attention_fn(mesh: Mesh | None = None, *, causal: bool = False,
                      block_size: int = 512, window: int | None = None):
    """Pick the attention path per :func:`select_attention_path`: ring (or
    the all-to-all engine, ``DCT_SP_ENGINE=a2a``) when the ``seq`` axis is
    populated, the Pallas flash kernel for long single-shard sequences on
    TPU, blockwise/dense otherwise.

    ``window`` (causal sliding-window local attention) composes with
    every path: the single-shard kernels mask, the a2a engine windows its
    full-sequence per-head compute, and the ring engine truncates to the
    in-band hops (O(window) work/communication per device — the engine of
    choice for exactly the long sequences where windowing matters)."""
    _check_window(window, causal)
    if mesh is not None and mesh.shape.get("seq", 1) > 1:
        if sp_engine() == "a2a":
            return functools.partial(
                a2a_attention, mesh=mesh, causal=causal, window=window
            )
        return functools.partial(
            ring_attention, mesh=mesh, causal=causal, window=window
        )

    def attn(q, k, v):
        t = q.shape[-2]
        # Tunable kernel tiles; the selection check uses the SAME values,
        # so a non-dividing override degrades to blockwise instead of
        # crashing inside the kernel.
        bq = int(os.environ.get("DCT_FLASH_BLOCK_Q", "128"))
        bk = int(os.environ.get("DCT_FLASH_BLOCK_K", "128"))
        path = select_attention_path(
            t, block_size=block_size, flash_block=max(bq, bk)
        )
        if path == "flash" and t % bq == 0 and t % bk == 0:
            from dct_tpu.ops.pallas_attention import flash_attention

            # Windowed calls stay kernel-resident: the band mask lives in
            # the kernel and out-of-band tiles skip compute + DMA.
            return flash_attention(
                q, k, v, block_q=bq, block_k=bk, causal=causal,
                interpret=bool(flash_interpret_mode()), window=window,
            )
        # 'flash' whose override blocks do not divide t degrades here too.
        if t > block_size and t % block_size == 0:
            return blockwise_attention(
                q, k, v, block_size=block_size, causal=causal, window=window
            )
        return dense_attention(q, k, v, causal=causal, window=window)

    return attn
