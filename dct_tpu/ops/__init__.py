from dct_tpu.ops.losses import (  # noqa: F401
    masked_cross_entropy,
    masked_accuracy,
    softmax_probs,
)
from dct_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    blockwise_attention_lse,
    dense_attention,
    flash_interpret_mode,
    make_attention_fn,
    ring_attention,
    select_attention_path,
)
