from dct_tpu.ops.losses import (  # noqa: F401
    masked_cross_entropy,
    masked_accuracy,
    softmax_probs,
)
