"""Loss and metric ops.

The reference computes ``F.cross_entropy`` (mean over the batch) for train
and val, plus argmax accuracy (jobs/train_lightning_ddp.py:66-85). Here the
same math is expressed as *weighted sums plus a weight total*, for two
TPU-native reasons:

1. fixed-shape padded batches: padding rows carry weight 0, so the weighted
   mean equals torch's mean over only-real rows;
2. SPMD: a weighted (sum, count) pair reduces correctly across devices and
   processes with a single ``psum`` regardless of how rows are sharded —
   the global mean is exact even when ranks hold different numbers of real
   rows (torch's ``sync_dist=True`` mean-of-per-rank-means is only
   approximate in that case; jobs/train_lightning_ddp.py:70,83-84).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_cross_entropy(logits, labels, weight):
    """Returns (weighted_loss_sum, weight_sum); the mean is sum / count."""
    logits = jnp.asarray(logits, jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, labels[..., None].astype(jnp.int32), axis=-1)
    nll = jnp.squeeze(nll, axis=-1)
    w = jnp.asarray(weight, jnp.float32)
    return jnp.sum(nll * w), jnp.sum(w)


def masked_accuracy(logits, labels, weight):
    """Returns (weighted_correct_sum, weight_sum)."""
    preds = jnp.argmax(jnp.asarray(logits, jnp.float32), axis=-1)
    correct = (preds == labels).astype(jnp.float32)
    w = jnp.asarray(weight, jnp.float32)
    return jnp.sum(correct * w), jnp.sum(w)


def masked_binary_counts(logits, labels, weight, *, positive: int = 1):
    """Weighted (tp, fp, fn) sums for the ``positive`` class.

    The building blocks of precision/recall/F1 as GLOBAL sums — exact
    under any sharding/padding, same design as the (sum, count) metric
    pairs (module docstring). Works for per-position/multi-horizon label
    shapes: argmax is over the trailing class axis and ``weight`` must
    already broadcast to the label shape."""
    preds = jnp.argmax(jnp.asarray(logits, jnp.float32), axis=-1)
    w = jnp.asarray(weight, jnp.float32)
    is_pos_pred = (preds == positive).astype(jnp.float32)
    is_pos_label = (labels == positive).astype(jnp.float32)
    tp = jnp.sum(is_pos_pred * is_pos_label * w)
    fp = jnp.sum(is_pos_pred * (1.0 - is_pos_label) * w)
    fn = jnp.sum((1.0 - is_pos_pred) * is_pos_label * w)
    return tp, fp, fn


def precision_recall_f1(tp: float, fp: float, fn: float):
    """Host-side finalization of the global count sums."""
    precision = tp / (tp + fp) if (tp + fp) > 0 else 0.0
    recall = tp / (tp + fn) if (tp + fn) > 0 else 0.0
    f1 = (
        2.0 * precision * recall / (precision + recall)
        if (precision + recall) > 0
        else 0.0
    )
    return precision, recall, f1


def softmax_probs(logits):
    return jax.nn.softmax(jnp.asarray(logits, jnp.float32), axis=-1)
