"""Pallas TPU flash-attention kernel: the single-chip attention hot path.

The JAX-level paths in :mod:`dct_tpu.ops.attention` rely on XLA fusion;
this kernel takes manual control of the memory hierarchy per the Pallas TPU
playbook: each grid step holds one Q block in VMEM, streams KV blocks
VMEM-resident through the MXU (``jnp.dot`` with f32 accumulation), and keeps
the online-softmax running stats in registers/VMEM — the score matrix never
exists in HBM, so memory is O(T·D) instead of O(T²).

Backward uses ``jax.custom_vjp`` with recompute-from-inputs through the
numerically-identical :func:`~dct_tpu.ops.attention.blockwise_attention`
(flash-style rematerialization: store only q,k,v, not the score matrix).

CPU rigs run the same kernel with ``interpret=True`` (tests); on TPU it
compiles to Mosaic. Reference note: the reference has no kernels of any
kind (pure torch CPU, SURVEY §2.2) — this file is capability the TPU build
adds at the layer the reference delegates to libtorch.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      causal: bool, scale: float):
    q = q_ref[:].astype(jnp.float32) * scale  # [bq, D]
    bq = q.shape[0]
    t = k_ref.shape[0]
    n_kv = t // block_k
    qi = pl.program_id(1)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, block_k]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            keep = q_pos >= k_pos
            s = jnp.where(keep, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(keep, p, 0.0)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), _NEG, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, block_q: int, block_k: int, causal: bool,
               scale: float | None, interpret: bool):
    b, h, t, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(
            f"seq len {t} must be a multiple of block_q={block_q} and "
            f"block_k={block_k} (pad upstream)"
        )
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((None, t, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((None, t, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(q, k, v, block_q=128, block_k=128, causal=False,
                    scale=None, interpret=False):
    """Flash attention; q,k,v [B, H, T, D] -> [B, H, T, D]."""
    return _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, interpret=interpret,
    )


def _vjp_fwd(q, k, v, block_q, block_k, causal, scale, interpret):
    out = _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, interpret=interpret,
    )
    return out, (q, k, v)


def _vjp_bwd(block_q, block_k, causal, scale, interpret, res, g):
    # Rematerialized backward: differentiate the numerically-identical
    # blockwise path from the saved inputs (no score matrix was stored).
    from dct_tpu.ops.attention import blockwise_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, block_size=block_k, causal=causal, scale=scale
        ),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
