"""Pallas TPU flash-attention kernel: the single-chip attention hot path.

The JAX-level paths in :mod:`dct_tpu.ops.attention` rely on XLA fusion;
this kernel takes manual control of the memory hierarchy per the Pallas TPU
playbook. The grid is ``(batch*heads, q_blocks, kv_blocks)`` with the KV
block as the innermost (sequential) dimension, so VMEM residency per grid
step is one ``[block_q, D]`` Q tile plus one ``[block_k, D]`` K/V tile —
O(block) regardless of sequence length — while the online-softmax running
stats (m, l, acc) persist in VMEM scratch across the KV sweep. The score
matrix never exists in HBM, so memory is O(T·D) instead of O(T²); with
``causal=True`` KV blocks entirely above the diagonal skip their MXU work,
and with ``window`` set the sliding-window band also skips every block
entirely behind the band (compute AND DMA, in forward and both backward
kernels) — O(T·window) FLOPs instead of the causal O(T²/2). ``q_offset``
statically shifts the q positions so the windowed ring's partial-band
shards (q-k distance = step·T_local) reuse the same kernel.

The running stats use the same online update as
:func:`dct_tpu.ops.attention._online_block`; they are re-expressed here in
2-D keepdims layout ([block_q, 1] rows, lane-broadcast scratch tiles)
because Mosaic wants >=2-D vector layouts in VMEM — tests pin the two
implementations to the same dense oracle so they cannot drift silently.

Backward is a pair of FlashAttention-2-style Pallas kernels (dK/dV with
the Q sweep innermost, dQ with the KV sweep innermost): the forward saves
only (q, k, v, o, lse) and each backward block recovers its softmax
weights from the lse — O(T·D) memory end to end, with ``delta`` =
rowsum(dO⊙O) recomputed in-kernel rather than shipped through HBM.
``DCT_FLASH_BWD=remat`` swaps in the older differentiate-through-
blockwise escape hatch.

CPU rigs run the same kernel with ``interpret=True`` (tests); on TPU it
compiles to Mosaic. Reference note: the reference has no kernels of any
kind (pure torch CPU, SURVEY §2.2) — this file is capability the TPU build
adds at the layer the reference delegates to libtorch.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dct_tpu.ops.attention import _NEG

# Lane width of the m/l scratch tiles: the stats are per-Q-row scalars, but
# Mosaic lays vectors out in (sublane, lane) tiles, so they live broadcast
# across a full 128-lane row (the official TPU flash kernels do the same).
_STATS_LANES = 128


def _kv_flat_row(bh, h: int, h_kv: int):
    """Flat [b*h] Q row -> flat [b*h_kv] KV row under the group-major GQA
    layout (q head g*group + j reads kv head g). The single source of the
    head mapping for the forward AND backward kernels' index maps."""
    if h == h_kv:
        return bh
    group = h // h_kv
    return (bh // h) * h_kv + (bh % h) // group


def _compiler_params():
    """Shared grid semantics for all three kernels: dims 0/1 are
    parallel (each (row, block) instance owns its scratch lifecycle —
    init at its inner sweep's first step, finalize at its last), only
    the innermost accumulation sweep is order-dependent. One helper so
    forward and backward cannot drift."""
    try:
        return pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        return None


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, block_k: int,
                      n_kv: int, causal: bool, scale: float,
                      with_lse: bool, window: int | None = None,
                      q_offset: int = 0):
    if with_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        m_ref, l_ref, acc_ref = rest
    qi = pl.program_id(1)
    j = pl.program_id(2)
    bq = q_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _block():
        # MXU operands stay in the INPUT dtype (bf16 on the product
        # path): upcasting q/k/v to f32 before the dots would run the
        # matmuls at f32 MXU rate — a fraction of bf16 throughput, and
        # the likely reason the kernel lost to XLA blockwise on-chip in
        # r2. bf16xbf16 products accumulate in f32 on the MXU (each
        # product is exactly representable), so only the p·V cast below
        # changes numerics, the same trade the official TPU flash
        # kernels make. The scale moves AFTER the dot so it applies in
        # f32 regardless of input dtype.
        q = q_ref[...]  # [bq, D]
        k = k_ref[...]  # [block_k, D]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, block_k] f32
        if causal:
            # ``q_offset`` shifts the q positions (the windowed ring's
            # static inter-shard distance); k positions stay 0-based.
            q_pos = q_offset + qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            keep = q_pos >= k_pos
            if window is not None:
                # Sliding window band: attend iff 0 <= q_pos-k_pos < window.
                keep &= q_pos - k_pos < window
            s = jnp.where(keep, s, _NEG)
        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            # A fully-masked row would otherwise get p=exp(0)=1 per entry
            # (same guard as attention._online_block).
            p = jnp.where(keep, p, 0.0)
        l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # KV block j overlaps the triangle iff its first key position
        # j*block_k is <= the block's last query position (qi+1)*bq - 1;
        # blocks fully above the diagonal skip all compute (their DMA is
        # also elided — the index map refetches the resident block).
        work = j * block_k < q_offset + (qi + 1) * bq
        if window is not None:
            # ...and entirely-behind-the-band blocks (every distance
            # >= window) skip too: this is where windowed flash recovers
            # O(T*window) FLOPs from the O(T^2) causal sweep.
            work &= q_offset + qi * bq - (j + 1) * block_k + 1 < window
        pl.when(work)(_block)
    else:
        _block()

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[...] = (acc_ref[...] / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
        if with_lse:
            # log-sum-exp per Q row, lane-broadcast ([block_q, LANES] like
            # the running stats) — callers slice lane 0.
            lse_ref[...] = jnp.broadcast_to(
                m_ref[:, :1] + jnp.log(jnp.maximum(l, 1e-20)), lse_ref.shape
            )


def _flash_fwd(q, k, v, *, block_q: int, block_k: int, causal: bool,
               scale: float | None, interpret: bool, with_lse: bool = False,
               window: int | None = None, q_offset: int = 0):
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    tk = k.shape[2]  # rectangular Tq != Tk supported (striped ring blocks)
    if causal and tk != t:
        raise ValueError(
            f"causal flash needs square Tq==Tk, got {t} vs {tk}"
        )
    if window is not None and not causal:
        raise ValueError("flash window requires causal attention")
    if q_offset and not causal:
        # The offset only participates in the causal position math; a
        # non-causal caller would silently get unshifted full attention.
        raise ValueError("flash q_offset requires causal attention")
    if h % h_kv:
        raise ValueError(
            f"GQA needs q heads ({h}) divisible by kv heads ({h_kv})"
        )
    # GQA: KV stay at their n_kv_heads in HBM — the grid runs per Q head
    # and the KV index maps divide by the group size, so each KV head's
    # tiles are fetched once per group sweep instead of being repeated
    # H/h_kv times through memory.
    group = h // h_kv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    if t % block_q or tk % block_k:
        raise ValueError(
            f"seq lens q={t}, kv={tk} must be multiples of "
            f"block_q={block_q} and block_k={block_k} (pad upstream)"
        )
    n_kv = tk // block_k
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h_kv, tk, d)
    vf = v.reshape(b * h_kv, tk, d)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, n_kv=n_kv, causal=causal,
        scale=scale, with_lse=with_lse, window=window, q_offset=q_offset,
    )
    def kv_bh(bh):
        return _kv_flat_row(bh, h, h_kv)

    if causal:
        # Skipped blocks would otherwise still be DMA'd: clamp the index
        # map so they re-address a needed block (already resident -> the
        # fetch is elided). Above-diagonal blocks clamp down (~half the
        # KV HBM traffic for plain causal); with a window, behind-the-band
        # blocks also clamp up, so KV traffic is O(T*window/block) total.
        def kv_index(bh, i, j):
            last_needed = (q_offset + (i + 1) * block_q - 1) // block_k
            jj = jnp.minimum(j, last_needed)
            if window is not None:
                first_needed = jnp.maximum(
                    0, (q_offset + i * block_q - window + 1) // block_k
                )
                jj = jnp.maximum(jj, jnp.minimum(first_needed, n_kv - 1))
            return (kv_bh(bh), jj, 0)
    else:
        def kv_index(bh, i, j):
            return (kv_bh(bh), j, 0)
    compiler_params = _compiler_params()
    # Under a vma-checked shard_map the outputs must declare the inputs'
    # device-varying axes explicitly; outside shard_map (and on jax
    # versions without vma typing) this resolves to no kwarg at all.
    try:
        vma = frozenset().union(*(jax.typeof(a).vma for a in (q, k, v)))
    except AttributeError:  # pragma: no cover - older jax
        vma = frozenset()
    vma_kw = {"vma": vma} if vma else {}
    out_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((b * h, t, d), q.dtype, **vma_kw)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec(
                (None, block_q, _STATS_LANES), lambda bh, i, j: (bh, i, 0)
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct(
                (b * h, t, _STATS_LANES), jnp.float32, **vma_kw
            )
        )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q, n_kv),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((None, block_k, d), kv_index),
            pl.BlockSpec((None, block_k, d), kv_index),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shape if with_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, d), jnp.float32),  # acc
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(qf, kf, vf)
    if with_lse:
        o, lse = out
        return o.reshape(b, h, t, d), lse[:, :, 0].reshape(b, h, t)
    return out.reshape(b, h, t, d)


def _bwd_block(q, k, v, do, lse, delta, scale, keep):
    """Shared per-(i,j) backward math: returns (p, ds) with
    p = softmax weights recovered from the forward lse, ds = the score
    cotangent. q,do [bq,d] · k,v [bk,d] · lse,delta [bq,1]. MXU operands
    stay in the input dtype (see the forward's dtype note); p/ds come
    back f32 and are cast at their consuming matmuls."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [bq, bk] f32
    p = jnp.exp(s - lse)
    if keep is not None:
        p = jnp.where(keep, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [bq, bk] f32
    ds = p * (dp - delta) * scale
    return p, ds


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *,
                           block_q: int, n_q: int, causal: bool,
                           scale: float, window: int | None = None,
                           group: int = 1):
    """dK/dV: grid (b*h_kv, kv blocks, group*n_q). The innermost sweep
    runs the GROUP's q heads back to back (i = member*n_q + qi) into one
    sequential accumulator — that is how GQA stays kernel-resident here:
    a q-head-parallel grid would race grouped dk/dv. With group == 1 this
    is exactly the classic per-head sweep."""
    j = pl.program_id(1)
    i = pl.program_id(2)
    qi = i % n_q  # q block WITHIN the current group member's sweep
    bk = k_ref.shape[0]

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _block():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        o = o_ref[...]
        lse = lse_ref[:, :1]
        # delta_i = rowsum(dO ⊙ O): recomputed per block (cheap VPU work,
        # upcast — elementwise f32 is free relative to the matmuls)
        # instead of shipping a [bh, T] side input through HBM.
        delta = jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        keep = None
        if causal:
            bq = q.shape[0]
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = q_pos >= k_pos
            if window is not None:
                keep &= q_pos - k_pos < window
        p, ds = _bwd_block(q, k, v, do, lse, delta, scale, keep)
        # dV_j += P^T dO_i ; dK_j += dS^T Q_i  (contract over the q rows)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # q block qi contributes to kv block j iff its last query position
        # reaches the block's first key position (and, windowed, iff its
        # first query is still inside the band of the block's last key).
        work = (qi + 1) * block_q > j * bk
        if window is not None:
            work &= qi * block_q - (j + 1) * bk + 1 < window
        pl.when(work)(_block)
    else:
        _block()

    @pl.when(i == group * n_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                         dq_ref, dq_acc, *, block_k: int, n_kv: int,
                         causal: bool, scale: float,
                         window: int | None = None):
    i = pl.program_id(1)
    j = pl.program_id(2)
    bq = q_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _block():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        o = o_ref[...]
        lse = lse_ref[:, :1]
        delta = jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        keep = None
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            keep = q_pos >= k_pos
            if window is not None:
                keep &= q_pos - k_pos < window
        _, ds = _bwd_block(q, k, v, do, lse, delta, scale, keep)
        # dQ_i += dS K_j
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        work = j * block_k < (i + 1) * bq
        if window is not None:
            work &= i * bq - (j + 1) * block_k + 1 < window
        pl.when(work)(_block)
    else:
        _block()

    @pl.when(j == n_kv - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, *, block_q: int, block_k: int,
               causal: bool, scale: float | None, interpret: bool,
               window: int | None = None):
    """FlashAttention-2-style backward: two Pallas kernels (dK/dV with the
    Q sweep innermost; dQ with the KV sweep innermost). The score matrix
    is recovered blockwise from the forward's lse — nothing O(T^2) ever
    touches HBM in the backward either.

    GQA runs kernel-resident in BOTH directions: dQ reads the grouped KV
    through divided index maps (like the forward), and dK/dV grids over
    the b*h_kv KV heads with the group's q heads swept sequentially into
    one accumulator (a q-head-parallel grid would race); dk/dv come back
    at the grouped head count."""
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    n_q = t // block_q
    n_kv = t // block_k
    flat = lambda a: a.reshape(b * h, t, d)
    qf, of, dof = map(flat, (q, o, do))
    kf = k.reshape(b * h_kv, t, d)
    vf = v.reshape(b * h_kv, t, d)
    # Forward lse [B,H,T] -> lane-broadcast [bh, T, LANES] (Mosaic wants
    # >=2-D vector tiles; lane 0 is read back in-kernel).
    lsef = jnp.broadcast_to(
        lse.reshape(b * h, t, 1), (b * h, t, _STATS_LANES)
    )
    try:
        vma = frozenset().union(*(jax.typeof(a).vma for a in (q, k, v)))
    except AttributeError:  # pragma: no cover - older jax
        vma = frozenset()
    vma_kw = {"vma": vma} if vma else {}

    # Same DMA-elision trick as the forward: clamp skipped blocks'
    # addresses onto a needed (resident) block so their fetch is elided.
    # dK/dV sweeps i = member*n_q + qi per kv block j (grid row is a KV
    # head): causal needs qi >= j*bk/bq, a window needs
    # qi*bq <= window + (j+1)*bk - 2; the flat q row is the member's head.
    def q_row(bh, i):
        if group == 1:
            return bh
        return (bh // h_kv) * h + (bh % h_kv) * group + i // n_q

    def q_index(bh, j, i):
        qi = i % n_q
        if causal:
            qi = jnp.maximum(qi, (j * block_k) // block_q)
            if window is not None:
                i_last = (window + (j + 1) * block_k - 2) // block_q
                qi = jnp.minimum(qi, jnp.maximum(i_last, 0))
        return (q_row(bh, i), qi, 0)

    q_spec = pl.BlockSpec((None, block_q, d), q_index)
    kv_spec = pl.BlockSpec((None, block_k, d), lambda bh, j, i: (bh, j, 0))
    lse_spec = pl.BlockSpec((None, block_q, _STATS_LANES), q_index)
    compiler_params = _compiler_params()

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel, block_q=block_q, n_q=n_q,
            causal=causal, scale=scale, window=window, group=group,
        ),
        grid=(b * h_kv, n_kv, group * n_q),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h_kv, t, d), k.dtype, **vma_kw),
            jax.ShapeDtypeStruct((b * h_kv, t, d), v.dtype, **vma_kw),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),  # dk accumulator
            pltpu.VMEM((block_k, d), jnp.float32),  # dv accumulator
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(qf, kf, vf, of, dof, lsef)

    kv_row = lambda bh: _kv_flat_row(bh, h, h_kv)

    # dQ sweeps kv blocks j per q block i — same clamp as the forward's
    # kv_index (above-diagonal down, behind-the-band up), KV rows divided
    # to the grouped head.
    if causal:
        def kv_index2(bh, i, j):
            jj = jnp.minimum(j, ((i + 1) * block_q - 1) // block_k)
            if window is not None:
                j_first = jnp.maximum(
                    0, (i * block_q - window + 1) // block_k
                )
                jj = jnp.maximum(jj, jnp.minimum(j_first, n_kv - 1))
            return (kv_row(bh), jj, 0)
    else:
        def kv_index2(bh, i, j):
            return (kv_row(bh), j, 0)
    q_spec2 = pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_spec2 = pl.BlockSpec((None, block_k, d), kv_index2)
    lse_spec2 = pl.BlockSpec(
        (None, block_q, _STATS_LANES), lambda bh, i, j: (bh, i, 0)
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_k=block_k, n_kv=n_kv,
            causal=causal, scale=scale, window=window,
        ),
        grid=(b * h, n_q, n_kv),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, q_spec2, lse_spec2],
        out_specs=q_spec2,
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype, **vma_kw),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=compiler_params,
        interpret=interpret,
    )(qf, kf, vf, of, dof, lsef)

    unflat = lambda a: a.reshape(b, h, t, d)
    return unflat(dq), dk.reshape(b, h_kv, t, d), dv.reshape(b, h_kv, t, d)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8)
)
def flash_attention(q, k, v, block_q=128, block_k=128, causal=False,
                    scale=None, interpret=False, window=None):
    """Flash attention; q,k,v [B, H, T, D] -> [B, H, T, D].

    ``window`` (causal-only sliding window): the band mask lives in the
    kernel and fully-out-of-band KV tiles skip compute AND DMA — the
    causal O(T^2/2) sweep becomes O(T*window)."""
    return _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, interpret=interpret, window=window,
    )


def _vjp_fwd(q, k, v, block_q, block_k, causal, scale, interpret, window):
    out, lse = _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, interpret=interpret, with_lse=True, window=window,
    )
    return out, (q, k, v, out, lse)


def _vjp_bwd(block_q, block_k, causal, scale, interpret, window, res, g):
    q, k, v, o, lse = res
    rectangular = q.shape[-2] != k.shape[-2]  # bwd kernels assume square
    if rectangular or os.environ.get(
        "DCT_FLASH_BWD", "kernel"
    ).strip().lower() == "remat":
        # Escape hatch: differentiate the numerically-identical blockwise
        # path instead of running the backward kernels.
        from dct_tpu.ops.attention import blockwise_attention

        block = min(block_k, k.shape[-2])
        _, vjp = jax.vjp(
            lambda q_, k_, v_: blockwise_attention(
                q_, k_, v_, block_size=block, causal=causal, scale=scale,
                window=window,
            ),
            q, k, v,
        )
        return vjp(g)
    return _flash_bwd(
        q, k, v, o, lse, g, block_q=block_q, block_k=block_k,
        causal=causal, scale=scale, interpret=interpret, window=window,
    )


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def flash_attention_lse(q, k, v, block_q=128, block_k=128, causal=False,
                        scale=None, interpret=False, window=None,
                        q_offset=0):
    """Flash attention that also returns the per-row log-sum-exp:
    (o [B,H,T,D], lse [B,H,T] f32). The lse makes finalized outputs
    MERGEABLE — ring attention combines per-KV-shard flash results with
    softmax weights ``exp(lse_j - logaddexp_j lse_j)``, which is exactly
    the online-softmax accumulation factored across kernel calls.

    ``window``/``q_offset``: causal sliding-window band with the q
    positions shifted by a STATIC offset — the windowed ring passes its
    per-step inter-shard distance here, so partial-band shards run
    kernel-resident with out-of-band tiles skipped."""
    return _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, interpret=interpret, with_lse=True, window=window,
        q_offset=q_offset,
    )


def _vjp_lse_fwd(q, k, v, block_q, block_k, causal, scale, interpret,
                 window, q_offset):
    out = _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, interpret=interpret, with_lse=True, window=window,
        q_offset=q_offset,
    )
    return out, (q, k, v)


def _vjp_lse_bwd(block_q, block_k, causal, scale, interpret, window,
                 q_offset, res, g):
    # Rematerialize through the numerically-identical JAX-level blockwise
    # path, which carries the SAME (o, lse) pair — so cotangents w.r.t.
    # the lse output (the ring merge weights depend on it) flow correctly.
    from dct_tpu.ops.attention import blockwise_attention_lse

    q, k, v = res
    # Static KV front-slice: with an offset band (the windowed ring's
    # partial shards), keys at j <= q_offset - window are behind the band
    # for EVERY q row — scanning them in the remat backward would waste
    # the forward's O(T*window) bound on zeroed blocks (code-review r4).
    # Their dk/dv are exactly zero, restored by the front pad below.
    j0 = 0
    if window is not None and q_offset:
        j0 = max(0, q_offset - window + 1)
        j0 -= j0 % max(block_k, 1)
        j0 = min(j0, k.shape[-2])  # fully-out-of-band shard: empty slice
    k_sl = k[..., j0:, :] if j0 else k
    v_sl = v[..., j0:, :] if j0 else v
    if k_sl.shape[-2] == 0:
        return (
            jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v)
        )
    block = min(block_k, k_sl.shape[-2])
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention_lse(
            q_, k_, v_, block_size=block, causal=causal, scale=scale,
            window=window, q_offset=q_offset - j0,
        ),
        q, k_sl, v_sl,
    )
    dq, dk, dv = vjp(g)
    if j0:
        pad = [(0, 0)] * (k.ndim - 2) + [(j0, 0), (0, 0)]
        dk = jnp.pad(dk, pad)
        dv = jnp.pad(dv, pad)
    return dq, dk, dv


flash_attention_lse.defvjp(_vjp_lse_fwd, _vjp_lse_bwd)
