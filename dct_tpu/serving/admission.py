"""Priority admission control: overload degrades to bounded p99, not
collapse.

Past the saturation knee a queue-everything server converts every extra
arrival into queue wait for ALL traffic: p99 grows without bound while
throughput stays flat (the collapse the PR 7 loadgen measures past the
knee). The fix is an old one — admit what you can serve inside the
latency budget, shed the rest FAST. A shed request costs the client one
jittered backoff (cheap, explicit, retryable); an admitted request keeps
a bounded queue ahead of it, so its p99 stays a function of the budget
instead of the overload magnitude.

Mechanics (consulted by both HTTP handler modes before enqueueing to
the :class:`~dct_tpu.serving.batching.MicroBatcher`):

- **Priority classes** ``high`` / ``normal`` / ``low``, read from the
  request header named by ``DCT_SERVE_PRIORITY_HEADER`` (default
  ``x-dct-priority``; unknown/absent = ``normal``). Each class owns a
  FRACTION of the queue budget: low sheds first, normal next, high
  only at the hard cap — so during overload the queue drains toward
  the traffic the operator declared most valuable.
- **Queue budget** in rows (``DCT_SERVE_ADMIT_MAX_QUEUE``) and a
  **queue-wait budget** (``DCT_SERVE_ADMIT_WAIT_MS``) estimated from
  the batcher's recent service rate — depth catches burst overload
  before the rate window sees it, the wait estimate catches a SLOWED
  server (degraded capacity at normal depth).
- **Deadline awareness**: a request carrying ``x-dct-deadline-ms``
  is shed — whatever its class — when the queue-wait estimate already
  exceeds its deadline: serving it late is work the client will
  discard.
- **Shed shape**: HTTP 429 with a ``Retry-After`` whose value is
  backoff-shaped by the PR 3 retry policy (:class:`Retrier.delay`:
  exponential in the class's consecutive-shed run, jittered so a
  synchronized client herd de-synchronizes) — overload pushes retries
  OUT instead of inviting an immediate second wave.

Evidence: ``dct_serve_admitted_total{class}`` /
``dct_serve_shed_total{class}`` counters on the serving registry (so
they aggregate fleet-wide on one ``/metrics`` scrape), and throttled
``admission.shed`` events — one per class per
:attr:`~AdmissionController.event_interval_s`, carrying the shed count
since the last record, never a per-request disk append on the overload
hot path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from dct_tpu.resilience.retry import Retrier

#: Priority classes, most-valuable first, with the fraction of the
#: queue/wait budget each may fill before it sheds.
CLASS_BUDGET_FRACTIONS = {"high": 1.0, "normal": 0.8, "low": 0.5}

#: Request header naming the caller's latency deadline (milliseconds).
DEADLINE_HEADER = "x-dct-deadline-ms"


@dataclass
class AdmissionDecision:
    admitted: bool
    cls: str = "normal"
    reason: str = ""
    retry_after_s: float = 0.0
    queue_rows: int = 0
    est_wait_ms: float | None = None


class AdmissionController:
    """Per-server admission gate (thread-safe; one instance per server
    object, shared by every handler thread)."""

    def __init__(
        self,
        *,
        max_queue_rows: int = 256,
        wait_budget_ms: float = 500.0,
        priority_header: str = "x-dct-priority",
        retry_after_s: float = 0.25,
        retrier: Retrier | None = None,
        metrics_registry=None,
        emit=None,
        event_interval_s: float = 1.0,
        clock=time.monotonic,
    ):
        self.max_queue_rows = max(1, int(max_queue_rows))
        self.wait_budget_s = max(0.0, float(wait_budget_ms)) / 1e3
        self.priority_header = str(priority_header).lower()
        self.event_interval_s = float(event_interval_s)
        # Retry-After shaping: the PR 3 retry policy's delay curve over
        # the class's consecutive-shed run (capped — a long overload
        # should not push Retry-After to minutes).
        self._retrier = retrier or Retrier(
            backoff_s=max(0.01, float(retry_after_s)), jitter=0.25
        )
        self._emit = emit
        self._clock = clock
        self._lock = threading.Lock()
        # class -> consecutive sheds (resets on an admit of that class)
        self._shed_run: dict[str, int] = {}
        self._lifetime_sheds = 0
        # class -> (sheds since last event, last event time)
        self._event_acc: dict[str, list] = {}
        self._admitted = self._shed = None
        if metrics_registry is not None:
            self._admitted = metrics_registry.counter(
                "dct_serve_admitted_total",
                "Requests admitted past admission control, by priority "
                "class.",
            )
            self._shed = metrics_registry.counter(
                "dct_serve_shed_total",
                "Requests shed (429) by admission control, by priority "
                "class.",
            )

    @classmethod
    def from_config(cls, serving, *, metrics_registry=None, emit=None):
        """Controller from a :class:`~dct_tpu.config.ServingConfig`."""
        return cls(
            max_queue_rows=serving.admit_max_queue,
            wait_budget_ms=serving.admit_wait_ms,
            priority_header=serving.priority_header,
            retry_after_s=serving.retry_after_s,
            metrics_registry=metrics_registry,
            emit=emit,
        )

    # -- request side ---------------------------------------------------

    def parse_class(self, headers) -> str:
        raw = (headers.get(self.priority_header) or "").strip().lower()
        return raw if raw in CLASS_BUDGET_FRACTIONS else "normal"

    def parse_deadline_s(self, headers) -> float | None:
        raw = (headers.get(DEADLINE_HEADER) or "").strip()
        try:
            ms = float(raw)
        except ValueError:
            return None
        return ms / 1e3 if ms > 0 else None

    def decide(
        self,
        cls: str,
        queue_rows: int,
        est_wait_s: float | None,
        *,
        deadline_s: float | None = None,
    ) -> AdmissionDecision:
        """One admission decision; mutates counters/shed-runs and may
        emit a throttled ``admission.shed`` event."""
        frac = CLASS_BUDGET_FRACTIONS.get(cls, 0.8)
        reason = ""
        if queue_rows >= self.max_queue_rows * frac:
            reason = "queue_depth"
        elif (
            est_wait_s is not None
            and self.wait_budget_s > 0
            and est_wait_s > self.wait_budget_s * frac
        ):
            reason = "queue_wait"
        elif (
            deadline_s is not None
            and est_wait_s is not None
            and est_wait_s > deadline_s
        ):
            # The caller's own deadline is tighter than our budget:
            # admitting work the client will discard starves live work.
            reason = "deadline"
        wait_ms = (
            round(est_wait_s * 1e3, 3) if est_wait_s is not None else None
        )
        if not reason:
            with self._lock:
                self._shed_run[cls] = 0
            if self._admitted is not None:
                self._admitted.inc(1.0, {"class": cls})
            return AdmissionDecision(
                True, cls=cls, queue_rows=queue_rows, est_wait_ms=wait_ms
            )
        with self._lock:
            run = self._shed_run.get(cls, 0) + 1
            self._shed_run[cls] = run
            self._lifetime_sheds += 1
        retry_after = self._retrier.delay(min(run, 6))
        if self._shed is not None:
            self._shed.inc(1.0, {"class": cls})
        self._maybe_emit(cls, reason, queue_rows, wait_ms, retry_after)
        return AdmissionDecision(
            False, cls=cls, reason=reason, retry_after_s=retry_after,
            queue_rows=queue_rows, est_wait_ms=wait_ms,
        )

    def _maybe_emit(self, cls, reason, queue_rows, wait_ms, retry_after):
        """Throttled shed evidence: the first shed of an episode lands
        immediately, then one record per ``event_interval_s`` per class
        carrying the count since the last — never per-request appends."""
        if self._emit is None:
            return
        now = self._clock()
        with self._lock:
            acc = self._event_acc.setdefault(cls, [0, None])
            acc[0] += 1
            if acc[1] is not None and now - acc[1] < self.event_interval_s:
                return
            count, acc[0], acc[1] = acc[0], 0, now
        try:
            self._emit(
                "admission", "admission.shed",
                priority=cls, reason=reason, count=count,
                queue_rows=queue_rows, est_wait_ms=wait_ms,
                retry_after_s=round(retry_after, 3),
            )
        except Exception:  # noqa: BLE001 — telemetry never fails a shed
            pass

    def shed_counts(self) -> dict:
        """Un-emitted shed counts per class (tests/diagnostics)."""
        with self._lock:
            return {k: v[0] for k, v in self._event_acc.items()}

    def shed_total(self) -> float:
        """Lifetime sheds across every class — the autoscaler's
        shed-rate signal (delta between polls). Counted locally so the
        signal works with or without a metrics registry attached."""
        with self._lock:
            return float(self._lifetime_sheds)
