"""Closed-loop serving autoscaler: capacity follows the queue, not a
human.

PR 7 gave the tier capacity knobs (``DCT_SERVE_PROCS`` /
``DCT_SERVE_WORKERS``), PR 8 gave it saturation *senses* (queue-depth
histograms, SLO burn rates) — this module closes the loop. A controller
thread polls the overload signals and scales the serving capacity
between ``DCT_SERVE_SCALE_MIN`` and ``DCT_SERVE_SCALE_MAX``:

- **pool mode** (``jobs/serve.py``, ``DCT_SERVE_PROCS > 1``): the
  scaled axis is ServerPool PROCESSES — scale-up forks a fresh
  SO_REUSEPORT worker (which spins from the package's warmed AOT store
  when the compile cache is armed, so time-to-capacity is the PR 9
  sub-second first-score, not a fresh compile), scale-down SIGTERMs the
  newest child into a graceful drain (finish in-flight requests, clean
  exit — never the child-death failure path).
- **in-process mode** (``processes <= 1``): the axis is the
  micro-batcher's scoring WORKER threads
  (:meth:`~dct_tpu.serving.batching.MicroBatcher.set_workers`).

Control shape — the two classic anti-flap guards, both mandatory:

- **hysteresis**: a scale decision needs ``DCT_SERVE_SCALE_HYSTERESIS``
  CONSECUTIVE polls agreeing (an oscillating signal crossing the
  threshold every other poll never scales);
- **cooldown**: after any scale event, no further event for
  ``DCT_SERVE_SCALE_COOLDOWN_S`` (new capacity needs a window to absorb
  the queue before it is judged insufficient).

Signals per poll (pluggable ``signal_fn`` so unit tests can script
them): batcher queue depth (rows; pool mode reads the fleet
``dct_serve_queue_depth`` histogram delta off the PR 8 metrics plane),
whether any SLO is burning, and the admission controller's shed rate —
sheds mean admission is already cutting traffic, the strongest "add
capacity" evidence there is.

Evidence: ``autoscale.scale_up`` / ``autoscale.scale_down`` events and
a ``dct_serve_procs`` gauge (``dct_serve_workers`` in in-process mode)
published to the metrics plane so ONE aggregated scrape shows capacity
next to the queue depth that drove it.
"""

from __future__ import annotations

import os
import threading
import time


def emit_default(component: str, event: str, **fields) -> None:
    """Late-bound emit through the process-default event log (resolved
    per call, like the server's — env-built sinks and monkeypatched
    tests both see their own log)."""
    from dct_tpu.observability import events as _events

    _events.get_default().emit(component, event, **fields)


class WorkerScaleTarget:
    """Scale axis = the micro-batcher's scoring threads."""

    gauge_name = "dct_serve_workers"

    def __init__(self, batcher):
        self._batcher = batcher

    def current(self) -> int:
        return self._batcher.workers

    def scale_to(self, n: int) -> None:
        self._batcher.set_workers(n)


class PoolScaleTarget:
    """Scale axis = ServerPool processes (jobs/serve.py)."""

    gauge_name = "dct_serve_procs"

    def __init__(self, pool):
        self._pool = pool

    def current(self) -> int:
        return self._pool.size()

    def scale_to(self, n: int) -> None:
        cur = self._pool.size()
        if n > cur:
            self._pool.scale_up(n - cur)
        elif n < cur:
            self._pool.scale_down(cur - n)


class Autoscaler:
    """The controller. ``observe()`` is the pure-ish decision step the
    unit tests drive directly; ``start()`` runs it on a poll thread."""

    def __init__(
        self,
        target,
        *,
        min_size: int = 1,
        max_size: int = 4,
        poll_s: float = 1.0,
        up_queue_rows: float = 32.0,
        down_queue_rows: float = 2.0,
        hysteresis_polls: int = 2,
        cooldown_s: float = 5.0,
        signal_fn=None,
        emit=None,
        registry=None,
        clock=time.monotonic,
    ):
        self.target = target
        self.min_size = max(1, int(min_size))
        self.max_size = max(self.min_size, int(max_size))
        self.poll_s = max(0.05, float(poll_s))
        self.up_queue_rows = float(up_queue_rows)
        self.down_queue_rows = float(down_queue_rows)
        self.hysteresis_polls = max(1, int(hysteresis_polls))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.signal_fn = signal_fn
        self._emit = emit
        self._clock = clock
        self._above = 0
        self._below = 0
        self._last_scale: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.events = 0  # lifetime scale events (tests/diagnostics)
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                target.gauge_name,
                "Current serving capacity units under autoscaler "
                "control.", agg="last",
            )
            self._gauge.set(float(target.current()))

    @classmethod
    def from_config(cls, target, serving, **kw):
        """Autoscaler from a :class:`~dct_tpu.config.ServingConfig`."""
        return cls(
            target,
            min_size=serving.scale_min,
            max_size=serving.scale_max,
            poll_s=serving.scale_poll_s,
            up_queue_rows=serving.scale_up_queue,
            down_queue_rows=serving.scale_down_queue,
            hysteresis_polls=serving.scale_hysteresis,
            cooldown_s=serving.scale_cooldown_s,
            **kw,
        )

    # -- decision step --------------------------------------------------

    def observe(
        self,
        queue_rows: float,
        *,
        slo_burning: bool = False,
        shed_rate: float = 0.0,
    ) -> str | None:
        """One poll: fold the signals into the hysteresis counters and
        apply at most one size step. Returns "up" / "down" / None."""
        overload = (
            queue_rows >= self.up_queue_rows
            or slo_burning
            or shed_rate > 0
        )
        idle = (
            queue_rows <= self.down_queue_rows
            and not slo_burning
            and shed_rate <= 0
        )
        self._above = self._above + 1 if overload else 0
        self._below = self._below + 1 if idle else 0
        now = self._clock()
        in_cooldown = (
            self._last_scale is not None
            and now - self._last_scale < self.cooldown_s
        )
        size = self.target.current()
        if self._gauge is not None:
            self._gauge.set(float(size))
        direction = None
        if (
            overload
            and self._above >= self.hysteresis_polls
            and not in_cooldown
            and size < self.max_size
        ):
            direction = "up"
            new = size + 1
        elif (
            idle
            and self._below >= self.hysteresis_polls
            and not in_cooldown
            and size > self.min_size
        ):
            direction = "down"
            new = size - 1
        if direction is None:
            return None
        self.target.scale_to(new)
        self._last_scale = now
        self._above = self._below = 0
        self.events += 1
        if self._gauge is not None:
            self._gauge.set(float(new))
        if self._emit is not None:
            try:
                self._emit(
                    "autoscale", f"autoscale.scale_{direction}",
                    size_from=size, size_to=new,
                    queue_rows=round(float(queue_rows), 1),
                    slo_burning=bool(slo_burning),
                    shed_rate=round(float(shed_rate), 3),
                )
            except Exception:  # noqa: BLE001 — telemetry never blocks scaling
                pass
        return direction

    # -- poll loop ------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._loop, name="dct-serve-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self.signal_fn is None:
                # No signal source = no evidence: HOLD. A blind
                # controller reading "queue 0" forever would otherwise
                # drain a loaded pool to the floor.
                continue
            try:
                sig = self.signal_fn()
                self.observe(
                    float(sig.get("queue_rows", 0.0)),
                    slo_burning=bool(sig.get("slo_burning", False)),
                    shed_rate=float(sig.get("shed_rate", 0.0)),
                )
            except Exception:  # noqa: BLE001 — a flaky signal source must
                # not kill the control loop; the next poll retries.
                continue

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


# ----------------------------------------------------------------------
# Signal sources.

def batcher_signal_fn(server):
    """In-process signals straight off the server's own batcher and
    admission controller (no metrics plane needed)."""
    state = {"sheds": 0.0}

    def signal() -> dict:
        out = {"queue_rows": float(server.batcher.queued_rows())}
        admission = getattr(server, "admission", None)
        if admission is not None:
            total = admission.shed_total()
            out["shed_rate"] = max(0.0, total - state["sheds"])
            state["sheds"] = total
        return out

    return signal


def pool_signal_fn(metrics_dir: str, *, stale_s: float | None = None,
                   slo_monitor=None, history=None,
                   signal_window_s: float = 30.0, clock=time.time):
    """Fleet signals for the pool parent, read off the PR 8 metrics
    plane: average queue depth behind recent flushes (histogram delta
    between polls), shed-counter delta, and — when an
    :class:`~dct_tpu.observability.slo.SLOMonitor` is supplied —
    whether any SLO is burning on the merged view.

    When a :class:`~dct_tpu.observability.timeseries.HistoryReader` is
    supplied (the ISSUE 17 store armed via ``DCT_TS_DIR``), the
    queue-depth and shed-rate windows come from the on-disk history —
    one source of truth for "what happened over the last
    ``signal_window_s`` seconds", shared with the anomaly detector and
    the SLO monitor — and the in-memory between-poll deltas are only
    the no-data fallback."""
    from dct_tpu.observability import aggregate

    if stale_s is None:
        stale_s = aggregate.DEFAULT_STALE_S
    state: dict = {"q": None, "sheds": None}

    def signal() -> dict:
        merged = aggregate.merge_snapshots(
            aggregate.read_snapshots(
                metrics_dir, stale_s=stale_s, clock=clock
            )
        )
        out = {"queue_rows": 0.0, "shed_rate": 0.0, "slo_burning": False}
        from_history_q = from_history_s = False
        if history is not None:
            try:
                q = history.hist_mean(
                    "dct_serve_queue_depth", window_s=signal_window_s
                )
                if q is not None:
                    out["queue_rows"] = q
                    from_history_q = True
                d = history.counter_delta(
                    "dct_serve_shed_total", window_s=signal_window_s
                )
                if d is not None:
                    out["shed_rate"] = max(0.0, d)
                    from_history_s = True
            except Exception:  # noqa: BLE001 — a torn segment or racing
                pass  # compaction falls back to the in-memory deltas
        hist = merged.histogram_total("dct_serve_queue_depth")
        if hist is not None:
            prev = state["q"]
            state["q"] = (hist["count"], hist["sum"])
            if prev is not None and not from_history_q:
                d_count = hist["count"] - prev[0]
                d_sum = hist["sum"] - prev[1]
                if d_count > 0:
                    out["queue_rows"] = d_sum / d_count
        sheds = merged.total("dct_serve_shed_total")
        if sheds is not None:
            prev = state["sheds"]
            state["sheds"] = sheds
            if prev is not None and not from_history_s:
                out["shed_rate"] = max(0.0, sheds - prev)
        if slo_monitor is not None:
            try:
                states = slo_monitor.evaluate(merged)
                out["slo_burning"] = any(s["alerting"] for s in states)
            except Exception:  # noqa: BLE001 — a malformed spec must not
                pass  # kill the control loop; depth/sheds still steer
        return out

    return signal


def controller_publisher(registry, *, proc: str | None = None):
    """A metrics-plane snapshot publisher for the controller process
    (the pool parent has no serving registry of its own), or None when
    the plane is unarmed."""
    from dct_tpu.config import ObservabilityConfig

    obs = ObservabilityConfig.from_env()
    if not obs.metrics_dir:
        return None
    from dct_tpu.observability.aggregate import SnapshotPublisher

    return SnapshotPublisher(
        registry, obs.metrics_dir,
        proc=proc or f"serve-ctl-{os.getpid()}",
        interval_s=obs.metrics_publish_s,
    )
