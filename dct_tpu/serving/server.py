"""Local HTTP inference server: the Azure Managed Online Endpoint
contract, runnable anywhere, dependency-free.

The reference serves ONLY through an Azure endpoint (its generated
score.py runs inside azureml-inference-server,
dags/azure_manual_deploy.py:54-125) — there is no way to exercise the
request/response contract without a cloud deployment. This server wraps
the same :mod:`dct_tpu.serving.runtime` scoring body behind the same
wire contract on stdlib ``http.server``:

- ``POST /score``   — ``{"data": ...}`` -> ``{"probabilities": ...}``
  (exactly the reference's run() contract; multi-horizon causal
  checkpoints return per-horizon probability lists)
- ``GET /healthz``  — 200 once the model is loaded (the endpoint analog
  of the compose healthchecks, docker-compose.yml:48-52)
- ``GET /metrics``  — Prometheus text exposition of the per-slot
  request/error counters and latency histograms
  (:mod:`dct_tpu.observability.prometheus`), scrapeable by any
  Prometheus-compatible agent

Status-code policy, shared by both server modes: anything that is the
REQUEST's fault (malformed JSON/envelope, validate_payload failures,
a pinned slot that does not exist) is 4xx; anything past validation
(broken checkpoint/package, shape-mismatched weights) is 500 — blaming
the request for a server defect sends operators debugging the wrong
side. Responses are strict JSON (``allow_nan=False``).

Two modes:

- :func:`make_server` — serve one checkpoint (weights load once).
- :func:`make_endpoint_server` — serve a LOCAL rollout endpoint
  (:class:`dct_tpu.deploy.local.LocalEndpointClient`): requests route by
  the live traffic map (weighted random, like the Azure scoring URI
  during a canary), ``?slot=`` pins a slot (the
  ``azureml-model-deployment`` header analog), mirror traffic shadows a
  copy to the shadow slot AFTER the live response is sent, and the
  persisted control-plane state is re-read per request so the deploy
  DAG's stage transitions apply live, mid-serve. Weights cache by
  package dir (immutable once written); only the small state JSON is
  re-read per request.
"""

from __future__ import annotations

import json
import os
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dct_tpu.serving.score_gen import weights_from_checkpoint
from dct_tpu.serving.runtime import (
    forward_numpy,
    softmax_numpy,
    validate_payload,
)


_untraced_recorder = None


def _serve_recorder():
    """Serving request spans are OPT-IN (``DCT_SERVE_TRACE=1``): a
    per-request disk append (plus a shared recorder lock) has no place
    on the default hot path of a heavy-traffic server — ``/metrics``
    stays the always-on serving surface. With tracing off a disabled
    recorder (no file, no lock contention on emission) is returned."""
    global _untraced_recorder
    from dct_tpu.config import _env
    from dct_tpu.observability import spans as _spans

    # THE bool cast (config._env): serving trace enablement must parse
    # every spelling exactly like the other DCT_* boolean knobs.
    if _env("DCT_SERVE_TRACE", False, bool):
        return _spans.get_default()
    if _untraced_recorder is None:
        _untraced_recorder = _spans.SpanRecorder(None, trace_id="untraced")
    return _untraced_recorder


_pkg_trace_ids: dict = {}


def _package_trace_id(package_dir: str | None) -> str | None:
    """The shipped training cycle's run-correlation ID for a deployed
    package (memoized — packages are immutable once written): endpoint
    serving spans adopt it so the serving leg lands on the SAME cycle
    trace as the training run, like the rollout stage spans do."""
    if not package_dir:
        return None
    if package_dir not in _pkg_trace_ids:
        from dct_tpu.deploy.rollout import package_run_correlation_id

        _pkg_trace_ids[package_dir] = package_run_correlation_id(
            package_dir
        )
    return _pkg_trace_ids[package_dir]


class _JsonHandler(BaseHTTPRequestHandler):
    """Shared JSON plumbing: strict replies, quiet logs, envelope parse."""

    def _reply(self, code: int, payload: dict) -> None:
        try:
            # Strict JSON: a bare NaN/Infinity token in a 200 body would
            # be unparsable by spec-compliant clients.
            body = json.dumps(payload, allow_nan=False).encode()
        except ValueError:
            code = 500
            body = b'{"error": "non-finite values in response"}'
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_metrics(self) -> None:
        """``GET /metrics``: Prometheus text exposition of the server's
        slot metrics (scrapers require the versioned content type),
        plus the promotion-gate counters (``dct_deploy_gate_decisions_
        total`` / ``dct_drift_psi``) when a gate ledger exists — the
        gate runs in DAG task processes, so the long-lived serving
        process is the natural scrape surface for its decisions."""
        from dct_tpu.evaluation.gates import render_gate_metrics
        from dct_tpu.observability.prometheus import CONTENT_TYPE

        body = (
            self.server.slot_metrics.prometheus_text()
            + render_gate_metrics()
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet by default; DCT_SERVE_LOG=1
        if os.environ.get("DCT_SERVE_LOG"):
            super().log_message(fmt, *args)

    def _read_data_envelope(self):
        """Parse the request body as ``{"data": ...}``; replies 400 and
        returns None on anything malformed."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict) or "data" not in payload:
                raise ValueError('payload must be {"data": [...]}')
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return None
        return payload["data"]

    def _score(self, weights: dict, meta: dict, data,
               slot: str = "default", trace_id: str | None = None):
        """validate (400) -> forward (500) -> probabilities dict.

        Returns (result_or_None, server_fault): a None result with
        server_fault=False was the request's fault (400 already sent);
        with server_fault=True a 500 was sent — callers tracking
        per-slot health must count only the latter as slot errors.

        Each call records a ``serving.score`` span (the request-handling
        leg of the cycle trace, status-attributed) when serving traces
        are enabled via ``DCT_SERVE_TRACE``."""
        with _serve_recorder().for_trace(trace_id).span(
            "serving.score", component="serving", slot=slot,
        ) as sp:
            try:
                x = validate_payload(meta, data)
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": str(e)})
                sp.set(status=400)
                return None, False
            try:
                probs = softmax_numpy(forward_numpy(weights, meta, x))
                import numpy as _np

                if not _np.isfinite(probs).all():
                    # Finite validated input producing NaN probabilities
                    # is a broken checkpoint; surface it as the 500 it
                    # is rather than letting the strict-JSON backstop
                    # downgrade the reply after the fact.
                    raise ArithmeticError("non-finite probabilities")
            except Exception as e:  # noqa: BLE001 — past validation, ANY
                # failure (incl. a shape-mismatched weight raising
                # ValueError in a matmul) is a broken checkpoint/export:
                # a SERVER error.
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                sp.set(status=500)
                return None, True
            sp.set(status=200, rows=int(x.shape[0]))
            return {"probabilities": probs.tolist()}, False


class ScoreHandler(_JsonHandler):
    """Single-checkpoint mode; the loaded model rides on the server
    object (ThreadingHTTPServer => scoring must be thread-safe: it is —
    pure numpy on read-only weights)."""

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path == "/metrics":
            self._reply_metrics()
            return
        if self.path != "/healthz":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        meta = self.server.model_meta
        self._reply(
            200,
            {
                "status": "ok",
                "model": meta.get("model", "weather_mlp"),
                "input_dim": int(meta.get("input_dim", 0)),
                "horizon": int(meta.get("horizon", 1)),
            },
        )

    def do_POST(self):  # noqa: N802 (http.server API)
        import time

        if self.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        data = self._read_data_envelope()
        if data is None:
            return
        t0 = time.perf_counter()
        result, server_fault = self._score(
            self.server.model_weights, self.server.model_meta, data
        )
        # Single-checkpoint mode has one implicit slot; same /metrics
        # series shape as the endpoint mode so dashboards carry over.
        self.server.slot_metrics.record(
            "default", time.perf_counter() - t0, ok=not server_fault
        )
        if result is not None:
            self._reply(200, result)


def make_server(ckpt_path: str, *, host: str = "127.0.0.1", port: int = 0):
    """Load the checkpoint and return a ready (unstarted)
    ThreadingHTTPServer; ``port=0`` binds an ephemeral port
    (``server.server_address[1]`` after construction)."""
    weights, meta = weights_from_checkpoint(ckpt_path)
    server = ThreadingHTTPServer((host, port), ScoreHandler)
    server.model_weights = weights
    server.model_meta = meta
    server.slot_metrics = _SlotMetrics()
    return server


class _PackageCache:
    """Thread-safe weights cache keyed by package dir.

    ThreadingHTTPServer handles each request on its own thread, so the
    cache needs a lock; and deployments retire as rollouts proceed, so
    entries whose package dir no longer backs ANY current deployment are
    evicted on the next load — a long-lived endpoint server must not
    accumulate a full weight set for every package ever served.

    Concurrent first requests for the same package may both run the
    loader (load happens outside the lock — package IO must not stall
    other slots' cache hits); the first store wins and the duplicate is
    dropped, which is benign for immutable read-only packages.

    Eviction is GENERATION-GATED: each request carries the state file's
    mtime from before its snapshot read, and only the newest generation
    observed may evict. A straggler request holding a pre-transition
    snapshot can therefore never evict a package a newer deployment just
    made live (which would force a full reload — a latency spike on
    exactly the canary slot mid-rollout).
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._entries: dict = {}
        self._generation = -1
        self._live: set = set()

    def get_or_load(
        self, pkg: str, loader, live_pkgs, generation: int = 0
    ) -> tuple:
        with self._lock:
            if generation >= self._generation:
                self._generation = generation
                self._live = set(live_pkgs)
                for stale in set(self._entries) - self._live:
                    del self._entries[stale]
            cached = self._entries.get(pkg)
        if cached is None:
            loaded = loader()
            with self._lock:
                if pkg in self._live or generation >= self._generation:
                    cached = self._entries.setdefault(pkg, loaded)
                else:
                    # A newer generation retired this package while the
                    # straggler was loading: serve it this once, but do
                    # NOT resurrect it into the cache (ADVICE r3).
                    cached = loaded
        return cached


class _SlotMetrics:
    """Thread-safe per-slot request metrics: what an operator watches
    during a canary (the Azure endpoint surfaces the same per-deployment
    request/latency series). Bounded memory: a sliding window of the
    last 1024 latencies per slot — p50/p99 reflect recent traffic, not
    all-time history — plus an all-time cumulative latency histogram in
    Prometheus bucket layout for ``GET /metrics`` (fixed size: bucket
    counters only, no samples retained)."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._by_slot: dict = {}

    def record(self, slot: str, seconds: float, ok: bool) -> None:
        from dct_tpu.observability.prometheus import HistogramAccumulator

        with self._lock:
            m = self._by_slot.setdefault(
                slot,
                {
                    "requests": 0,
                    "errors": 0,
                    "lat": [],
                    "hist": HistogramAccumulator(),
                },
            )
            m["requests"] += 1
            if not ok:
                m["errors"] += 1
            m["hist"].observe(seconds)
            lat = m["lat"]
            lat.append(seconds)
            if len(lat) > 1024:
                del lat[: len(lat) - 1024]

    def snapshot(self) -> dict:
        import statistics

        with self._lock:
            out = {}
            for slot, m in self._by_slot.items():
                lat = sorted(m["lat"])
                entry = {"requests": m["requests"], "errors": m["errors"]}
                if lat:
                    entry["p50_ms"] = round(
                        statistics.median(lat) * 1e3, 3
                    )
                    entry["p99_ms"] = round(
                        lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3,
                        3,
                    )
                out[slot] = entry
            return out

    def prometheus_text(self) -> str:
        """Text exposition (0.0.4) of every slot's series. Histogram
        state is deep-copied under the lock; rendering happens outside
        it so a slow scrape never blocks request recording."""
        import copy

        from dct_tpu.observability.prometheus import MetricFamily, render

        with self._lock:
            slots = {
                slot: {
                    "requests": m["requests"],
                    "errors": m["errors"],
                    "hist": copy.deepcopy(m["hist"]),
                }
                for slot, m in self._by_slot.items()
            }
        req = MetricFamily(
            "dct_requests_total", "counter",
            "Scoring requests served, by deployment slot.",
        )
        err = MetricFamily(
            "dct_request_errors_total", "counter",
            "Server-fault scoring errors, by deployment slot "
            "(client 4xx never counts against a slot).",
        )
        lat = MetricFamily(
            "dct_request_latency_seconds", "histogram",
            "End-to-end scoring latency, by deployment slot.",
        )
        for slot in sorted(slots):
            m = slots[slot]
            req.add(m["requests"], {"slot": slot})
            err.add(m["errors"], {"slot": slot})
            m["hist"].samples_into(lat, {"slot": slot})
        return render([req, err, lat])


class EndpointScoreHandler(_JsonHandler):
    """Rollout-endpoint mode (see module docstring)."""

    def _client(self):
        from dct_tpu.deploy.local import LocalEndpointClient

        # Fresh read of the persisted state: rollout stages run in other
        # processes and must take effect without a server restart. The
        # mtime is taken BEFORE the read, so the generation can only
        # under-state the snapshot's age — stale cache evictions are
        # skipped, never wrongly applied (_PackageCache docstring).
        state_path = self.server.state_path
        try:
            generation = os.stat(state_path).st_mtime_ns
        except OSError:
            generation = 0
        self._state_generation = generation
        return LocalEndpointClient(state_path=state_path)

    def _load_slot(self, client, slot: str):
        """(weights, meta) via the server-lifetime package cache —
        packages are immutable once written, so only the state JSON
        needs the per-request re-read. Retired packages evict."""
        name = self.server.endpoint_name
        deployments = client.endpoints[name].deployments
        return self.server.package_cache.get_or_load(
            deployments[slot].package_dir,
            lambda: client.load_slot(name, slot),
            [d.package_dir for d in deployments.values()],
            generation=getattr(self, "_state_generation", 0),
        )

    def do_GET(self):  # noqa: N802 (http.server API)
        import urllib.parse

        route = urllib.parse.urlparse(self.path).path
        if route == "/metrics":
            self._reply_metrics()
            return
        if route != "/healthz":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        client = self._client()
        name = self.server.endpoint_name
        if not client.endpoint_exists(name):
            self._reply(503, {"error": f"endpoint {name} not provisioned"})
            return
        self._reply(
            200,
            {
                "status": "ok",
                "endpoint": name,
                "traffic": client.get_traffic(name),
                "mirror_traffic": client.get_mirror_traffic(name),
                "deployments": client.list_deployments(name),
                "metrics": self.server.slot_metrics.snapshot(),
            },
        )

    def do_POST(self):  # noqa: N802 (http.server API)
        import random
        import time
        import urllib.parse

        parsed = urllib.parse.urlparse(self.path)
        if parsed.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        data = self._read_data_envelope()
        if data is None:
            return
        client = self._client()
        name = self.server.endpoint_name
        live = {
            k: v for k, v in client.get_traffic(name).items() if v > 0
        }
        pinned = urllib.parse.parse_qs(parsed.query).get("slot")
        if pinned:
            slot = pinned[0]
        elif live:
            # Weighted random routing — the canary's 10% is a real 10%.
            slot = random.choices(
                list(live), weights=list(live.values())
            )[0]
        else:
            self._reply(503, {"error": f"endpoint {name} has no live traffic"})
            return
        if slot not in client.list_deployments(name):
            # A request naming a nonexistent slot is the CLIENT's fault
            # (Azure's model-deployment header behaves the same).
            self._reply(404, {"error": f"no deployment {slot!r} on {name}"})
            return
        t0 = time.perf_counter()
        try:
            weights, meta = self._load_slot(client, slot)
        except Exception as e:  # noqa: BLE001 — unreadable package:
            self.server.slot_metrics.record(
                slot, time.perf_counter() - t0, ok=False
            )
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        result, server_fault = self._score(
            weights, meta, data, slot=slot,
            trace_id=_package_trace_id(
                client.endpoints[name].deployments[slot].package_dir
            ),
        )
        # Only SERVER faults count against the slot: a client's bad
        # payload (400) must not spike the canary's error series and
        # trigger a rollback of a healthy deployment.
        self.server.slot_metrics.record(
            slot, time.perf_counter() - t0, ok=not server_fault
        )
        if result is None:
            return
        self._reply(200, {**result, "slot": slot})
        # Mirror (shadow) traffic AFTER the live response is flushed —
        # a slow or broken shadow must never touch live latency (exactly
        # Azure's mirror semantics: the caller never sees it). Outcomes
        # ARE recorded under the shadow slot: evaluating the shadow is
        # what mirror traffic exists for.
        for shadow, pct in client.get_mirror_traffic(name).items():
            if (
                pct > 0
                and shadow != slot
                and shadow in client.list_deployments(name)
                and random.random() * 100 < pct
            ):
                ts = time.perf_counter()
                try:
                    import numpy as _np

                    w_s, m_s = self._load_slot(client, shadow)
                    p_s = softmax_numpy(
                        forward_numpy(w_s, m_s, validate_payload(m_s, data))
                    )
                    shadow_ok = bool(_np.isfinite(p_s).all())
                    if shadow_ok and result is not None:
                        # Mirror capture: the paired live/shadow
                        # responses are the prediction-disagreement
                        # evidence the shadow->canary promotion gate
                        # scores (evaluation.drift). Append-only JSONL,
                        # best-effort, after the live reply flushed.
                        client.append_mirror_record({
                            "ts": round(time.time(), 6),
                            "endpoint": name,
                            "live_slot": slot,
                            "shadow_slot": shadow,
                            "live_probs": result["probabilities"],
                            "shadow_probs": p_s.tolist(),
                        })
                except Exception:  # noqa: BLE001 — shadow failures are
                    shadow_ok = False  # invisible to the caller by design
                self.server.slot_metrics.record(
                    shadow, time.perf_counter() - ts, ok=shadow_ok
                )


def make_endpoint_server(
    endpoint: str, *, state_path: str | None = None,
    host: str = "127.0.0.1", port: int = 0,
):
    """HTTP server over the local rollout endpoint ``endpoint`` whose
    control-plane state lives at ``state_path`` (default: the
    DCT_LOCAL_ENDPOINT_STATE env the rollout DAG uses)."""
    server = ThreadingHTTPServer((host, port), EndpointScoreHandler)
    server.endpoint_name = endpoint
    server.state_path = state_path or os.environ.get(
        "DCT_LOCAL_ENDPOINT_STATE"
    )
    server.package_cache = _PackageCache()
    server.slot_metrics = _SlotMetrics()
    return server


def serve_forever(ckpt_path: str, *, host: str = "0.0.0.0",
                  port: int = 8901) -> None:
    server = make_server(ckpt_path, host=host, port=port)
    meta = server.model_meta
    print(
        f"serving {meta.get('model', 'weather_mlp')} from {ckpt_path} on "
        f"http://{host}:{port} (POST /score, GET /healthz)",
        flush=True,
    )
    server.serve_forever()
