"""Local HTTP inference server: the Azure Managed Online Endpoint
contract, runnable anywhere, dependency-free.

The reference serves ONLY through an Azure endpoint (its generated
score.py runs inside azureml-inference-server,
dags/azure_manual_deploy.py:54-125) — there is no way to exercise the
request/response contract without a cloud deployment. This server wraps
the same :func:`dct_tpu.serving.runtime.score_payload` body behind the
same wire contract on stdlib ``http.server``:

- ``POST /score``   — ``{"data": ...}`` -> ``{"probabilities": ...}``
  (exactly the reference's run() contract; multi-horizon causal
  checkpoints return per-horizon probability lists)
- ``GET /healthz``  — 200 ``{"status": "ok", "model": ..., "horizon": ...}``
  once the model is loaded (the endpoint analog of the compose
  healthchecks, docker-compose.yml:48-52)

Errors mirror the score.py behavior: a malformed payload returns 400
with the validation message rather than a 500.
"""

from __future__ import annotations

import json
import os
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dct_tpu.serving.score_gen import weights_from_checkpoint
from dct_tpu.serving.runtime import (
    forward_numpy,
    softmax_numpy,
    validate_payload,
)


class ScoreHandler(BaseHTTPRequestHandler):
    """Per-request handler; the loaded model rides on the server object
    (ThreadingHTTPServer => score_payload must be thread-safe: it is —
    pure numpy on read-only weights)."""

    def _reply(self, code: int, payload: dict) -> None:
        try:
            # Strict JSON: a bare NaN/Infinity token in a 200 body would
            # be unparsable by spec-compliant clients.
            body = json.dumps(payload, allow_nan=False).encode()
        except ValueError:
            code = 500
            body = b'{"error": "non-finite values in response"}'
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet by default; DCT_SERVE_LOG=1
        if os.environ.get("DCT_SERVE_LOG"):
            super().log_message(fmt, *args)

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path != "/healthz":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        meta = self.server.model_meta
        self._reply(
            200,
            {
                "status": "ok",
                "model": meta.get("model", "weather_mlp"),
                "input_dim": int(meta.get("input_dim", 0)),
                "horizon": int(meta.get("horizon", 1)),
            },
        )

    def do_POST(self):  # noqa: N802 (http.server API)
        if self.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict) or "data" not in payload:
                raise ValueError('payload must be {"data": [...]}')
        except (ValueError, TypeError) as e:  # malformed JSON / envelope
            self._reply(400, {"error": str(e)})
            return
        meta = self.server.model_meta
        try:
            # Wrong shape, ragged/non-numeric rows, non-finite features:
            # the client's fault.
            x = validate_payload(meta, payload["data"])
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            probs = softmax_numpy(
                forward_numpy(self.server.model_weights, meta, x)
            )
        except Exception as e:  # noqa: BLE001 — past validation, ANY
            # failure (incl. a shape-mismatched weight raising ValueError
            # in a matmul) is a broken checkpoint/export: a SERVER error.
            # Blaming the request would send operators debugging the
            # wrong side.
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, {"probabilities": probs.tolist()})


def make_server(ckpt_path: str, *, host: str = "127.0.0.1", port: int = 0):
    """Load the checkpoint and return a ready (unstarted)
    ThreadingHTTPServer; ``port=0`` binds an ephemeral port
    (``server.server_address[1]`` after construction)."""
    weights, meta = weights_from_checkpoint(ckpt_path)
    server = ThreadingHTTPServer((host, port), ScoreHandler)
    server.model_weights = weights
    server.model_meta = meta
    return server


def serve_forever(ckpt_path: str, *, host: str = "0.0.0.0",
                  port: int = 8901) -> None:
    server = make_server(ckpt_path, host=host, port=port)
    meta = server.model_meta
    print(
        f"serving {meta.get('model', 'weather_mlp')} from {ckpt_path} on "
        f"http://{host}:{port} (POST /score, GET /healthz)",
        flush=True,
    )
    server.serve_forever()
