"""Local HTTP inference server: the Azure Managed Online Endpoint
contract, runnable anywhere, dependency-free.

The reference serves ONLY through an Azure endpoint (its generated
score.py runs inside azureml-inference-server,
dags/azure_manual_deploy.py:54-125) — there is no way to exercise the
request/response contract without a cloud deployment. This server wraps
the same :mod:`dct_tpu.serving.runtime` scoring body behind the same
wire contract on stdlib ``http.server``:

- ``POST /score``   — ``{"data": ...}`` -> ``{"probabilities": ...}``
  (exactly the reference's run() contract; multi-horizon causal
  checkpoints return per-horizon probability lists)
- ``GET /healthz``  — 200 once the model is loaded (the endpoint analog
  of the compose healthchecks, docker-compose.yml:48-52)
- ``GET /metrics``  — Prometheus text exposition of the per-slot
  request/error counters and latency histograms
  (:mod:`dct_tpu.observability.prometheus`), scrapeable by any
  Prometheus-compatible agent

Status-code policy, shared by both server modes: anything that is the
REQUEST's fault (malformed JSON/envelope, validate_payload failures,
a pinned slot that does not exist) is 4xx; anything past validation
(broken checkpoint/package, shape-mismatched weights) is 500 — blaming
the request for a server defect sends operators debugging the wrong
side. Responses are strict JSON (``allow_nan=False``).

Two modes:

- :func:`make_server` — serve one checkpoint (weights load once).
- :func:`make_endpoint_server` — serve a LOCAL rollout endpoint
  (:class:`dct_tpu.deploy.local.LocalEndpointClient`): requests route by
  the live traffic map (weighted random, like the Azure scoring URI
  during a canary), ``?slot=`` pins a slot (the
  ``azureml-model-deployment`` header analog), mirror traffic shadows a
  copy to the shadow slot AFTER the live response is sent, and the
  persisted control-plane state is re-read per request so the deploy
  DAG's stage transitions apply live, mid-serve. Weights cache by
  package dir (immutable once written); only the small state JSON is
  re-read per request.
"""

from __future__ import annotations

import json
import os
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dct_tpu.serving.batching import MicroBatcher, ScoringError
from dct_tpu.serving.score_gen import weights_from_checkpoint
from dct_tpu.serving.runtime import (
    parse_envelope_array,
    validate_payload,
)


_untraced_recorder = None


def _serve_recorder():
    """Serving request spans are OPT-IN (``DCT_SERVE_TRACE=1``): a
    per-request disk append (plus a shared recorder lock) has no place
    on the default hot path of a heavy-traffic server — ``/metrics``
    stays the always-on serving surface. With tracing off a disabled
    recorder (no file, no lock contention on emission) is returned."""
    global _untraced_recorder
    from dct_tpu.config import _env
    from dct_tpu.observability import spans as _spans

    # THE bool cast (config._env): serving trace enablement must parse
    # every spelling exactly like the other DCT_* boolean knobs.
    if _env("DCT_SERVE_TRACE", False, bool):
        return _spans.get_default()
    if _untraced_recorder is None:
        _untraced_recorder = _spans.SpanRecorder(None, trace_id="untraced")
    return _untraced_recorder


_pkg_trace_ids: dict = {}


def _package_trace_id(package_dir: str | None) -> str | None:
    """The shipped training cycle's run-correlation ID for a deployed
    package (memoized — packages are immutable once written): endpoint
    serving spans adopt it so the serving leg lands on the SAME cycle
    trace as the training run, like the rollout stage spans do."""
    if not package_dir:
        return None
    if package_dir not in _pkg_trace_ids:
        from dct_tpu.deploy.rollout import package_run_correlation_id

        _pkg_trace_ids[package_dir] = package_run_correlation_id(
            package_dir
        )
    return _pkg_trace_ids[package_dir]


_pkg_lineage: dict = {}


def _package_lineage_node(package_dir: str | None, **attrs) -> str | None:
    """The deployed package's content-addressed lineage node id,
    recorded — together with a ``model_load`` node and its
    ``served_by`` edge — on this process's first sighting of the
    package and memoized after (packages are immutable once written, so
    the one-time directory hash never lands on the request hot path
    twice). Surfaced in ``/healthz`` so "which artifact is this process
    serving?" is answerable without touching the box; None when the
    lineage ledger is disabled."""
    if not package_dir:
        return None
    if package_dir in _pkg_lineage:
        return _pkg_lineage[package_dir]
    from dct_tpu.observability import lineage as _lineage

    lin = _lineage.get_default()
    if not lin.enabled:
        return None
    pkg_nid = lin.node("deploy_package", path=package_dir)
    load_nid = lin.node(
        "model_load",
        content={"package": pkg_nid, "pid": os.getpid()},
        attrs={
            "package_dir": os.path.abspath(package_dir),
            "pid": os.getpid(), **attrs,
        },
    )
    lin.edge("served_by", pkg_nid, load_nid)
    _pkg_lineage[package_dir] = pkg_nid
    return pkg_nid


class _JsonHandler(BaseHTTPRequestHandler):
    """Shared JSON plumbing: strict replies, quiet logs, envelope parse.

    HTTP/1.1 so keep-alive connections work (every reply carries an
    exact Content-Length): under load a connection-per-request front
    end spends more wall time in TCP setup + thread spawn than in
    scoring — measured ~3x of the small-payload request cost. Nagle is
    off (``disable_nagle_algorithm``): small JSON replies on a
    keep-alive connection otherwise sit out the peer's delayed-ACK
    timer — a measured ~44 ms p50 on a ~0.1 ms scoring path."""

    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True

    def _reply(self, code: int, payload: dict,
               headers: dict | None = None) -> None:
        try:
            # Strict JSON: a bare NaN/Infinity token in a 200 body would
            # be unparsable by spec-compliant clients.
            body = json.dumps(payload, allow_nan=False).encode()
        except ValueError:
            code = 500
            body = b'{"error": "non-finite values in response"}'
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _admit(self) -> bool:
        """Admission gate (docs/SERVING.md §elasticity): True when the
        request may proceed to scoring. Otherwise a fast 429 with a
        backoff-shaped ``Retry-After`` has already been sent — shedding
        happens BEFORE parsing, validation and enqueueing, so a shed
        request costs the raw body read (keep-alive framing demands
        that much) and nothing else; an overloaded server spends its
        cycles on admitted traffic."""
        ctl = getattr(self.server, "admission", None)
        if ctl is None:
            return True
        queued, est_wait = self.server.batcher.saturation()
        decision = ctl.decide(
            ctl.parse_class(self.headers),
            queued,
            est_wait,
            deadline_s=ctl.parse_deadline_s(self.headers),
        )
        if decision.admitted:
            return True
        import math

        # The HTTP header speaks RFC delta-seconds (integer — a
        # fractional value is ignored by standard retry stacks, which
        # would re-arrive unthrottled); the JSON body carries the
        # precise jittered value for clients that can use it (the
        # repo's loadgen prefers it).
        self._reply(
            429,
            {
                "error": "overloaded: request shed by admission control",
                "priority": decision.cls,
                "reason": decision.reason,
                "retry_after_s": round(decision.retry_after_s, 3),
            },
            headers={
                "Retry-After": str(
                    max(1, math.ceil(decision.retry_after_s))
                ),
            },
        )
        return False

    def _reply_metrics(self) -> None:
        """``GET /metrics``: Prometheus text exposition of the server's
        slot metrics (scrapers require the versioned content type),
        plus the promotion-gate counters (``dct_deploy_gate_decisions_
        total`` / ``dct_drift_psi``) when a gate ledger exists — the
        gate runs in DAG task processes, so the long-lived serving
        process is the natural scrape surface for its decisions.

        With the metrics plane armed (``DCT_METRICS_DIR``), the scrape
        is FLEET-WIDE: this process publishes its own snapshot, merges
        every live sibling snapshot (pool workers, trainer coordinator,
        supervisor — docs/OBSERVABILITY.md "Metrics plane"), renders
        totals plus per-process ``proc``-labelled series, and runs the
        SLO monitor over the aggregated view (``dct_slo_*`` gauges;
        burn-rate transitions emit ``slo.alert`` events)."""
        from dct_tpu.evaluation.gates import render_gate_metrics
        from dct_tpu.observability.prometheus import CONTENT_TYPE

        publisher = getattr(self.server, "metrics_publisher", None)
        if publisher is None:
            text = self.server.slot_metrics.prometheus_text()
        else:
            from dct_tpu.observability import aggregate

            publisher.publish()
            text, merged = aggregate.aggregate_text(
                publisher.directory,
                stale_s=getattr(self.server, "metrics_stale_s",
                                aggregate.DEFAULT_STALE_S),
            )
            monitor = getattr(self.server, "slo_monitor", None)
            if monitor is not None:
                text += monitor.render(merged)
        from dct_tpu.observability.lineage import render_lineage_metrics

        body = (
            text + render_gate_metrics() + render_lineage_metrics()
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet by default; DCT_SERVE_LOG=1
        if os.environ.get("DCT_SERVE_LOG"):
            super().log_message(fmt, *args)

    def _reply_profile(self, query: str) -> None:
        """``GET /debug/profile?seconds=N``: capture a ``jax.profiler``
        trace of THIS scoring process for N seconds (clamped to
        [0.05, 60]) and reply with the TensorBoard-loadable trace dir —
        the serving half of the flight recorder
        (:mod:`dct_tpu.observability.capture`). The capture brackets
        live traffic without touching it; only one capture runs at a
        time per process (a second request gets 409, never a torn
        trace). In a multi-process pool the kernel routes the request
        to ONE worker — the captured process's pid is in the reply."""
        import urllib.parse

        from dct_tpu.observability import capture as _capture

        import math

        qs = urllib.parse.parse_qs(query)
        try:
            seconds = float((qs.get("seconds") or ["1.0"])[0])
        except ValueError:
            seconds = float("nan")
        if not math.isfinite(seconds):
            # nan/inf slip through min/max (not NaN-safe) and a NaN in
            # the 200 body would be invalid strict JSON.
            self._reply(400, {"error": "seconds must be a finite number"})
            return
        seconds = min(max(seconds, 0.05), 60.0)
        from dct_tpu.config import ProfileConfig

        trace_dir = os.path.join(
            ProfileConfig.from_env().trace_dir, f"serve-{os.getpid()}"
        )
        try:
            out = _capture.capture_profile(
                trace_dir, seconds, emit=_emit_default
            )
        except _capture.CaptureBusy as e:
            self._reply(409, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — a capture failure is a
            # server fault (profiler unavailable, unwritable dir); the
            # scoring path is untouched either way.
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(
            200,
            {"trace_dir": out, "seconds": seconds, "pid": os.getpid()},
        )

    def _read_body(self) -> bytes:
        """Drain the raw request body — keep-alive framing demands the
        body be consumed even for a request that will be shed (an
        unread body would be parsed as the next request's head)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length) or b"{}"
        except (ValueError, TypeError):
            # A bogus Content-Length parses as an empty envelope; the
            # parse step's 400 contract reports it.
            return b"{}"

    def _parse_data_envelope(self, body: bytes):
        """Parse an already-read body as ``{"data": ...}``; replies 400
        and returns None on anything malformed.

        Fast path (``DCT_SERVE_FAST_PARSE``, default on): a rectangular
        numeric envelope parses straight into a float32 ndarray from the
        raw bytes — no intermediate Python lists or boxed floats
        (:func:`~dct_tpu.serving.runtime.parse_envelope_array`);
        anything irregular falls back to ``json.loads``, whose error
        reporting stays the 400 contract."""
        try:
            if getattr(self.server, "fast_parse", False):
                arr = parse_envelope_array(body)
                if arr is not None:
                    return arr
            payload = json.loads(body)
            if (
                not isinstance(payload, dict)
                or payload.get("data") is None
            ):
                raise ValueError('payload must be {"data": [...]}')
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return None
        return payload["data"]

    def _score(self, weights: dict, meta: dict, data,
               slot: str = "default", trace_id: str | None = None):
        """validate (400) -> forward (500) -> probabilities dict.

        Returns (result_or_None, server_fault): a None result with
        server_fault=False was the request's fault (400 already sent);
        with server_fault=True a 500 was sent — callers tracking
        per-slot health must count only the latter as slot errors.

        Each call records a ``serving.score`` span (the request-handling
        leg of the cycle trace, status-attributed) when serving traces
        are enabled via ``DCT_SERVE_TRACE``.

        Scoring goes through the server's shared :class:`MicroBatcher`:
        this request merges with compatible in-flight requests into one
        stacked forward (bit-identical to scoring it alone —
        serving/batching.py), and the non-finite-probabilities check is
        attributed per request inside the flush."""
        with _serve_recorder().for_trace(trace_id).span(
            "serving.score", component="serving", slot=slot,
        ) as sp:
            try:
                x = validate_payload(meta, data)
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": str(e)})
                sp.set(status=400)
                return None, False
            try:
                probs = self.server.batcher.score(
                    weights, meta, x, slot=slot
                )
            except Exception as e:  # noqa: BLE001 — past validation, ANY
                # failure (incl. a shape-mismatched weight raising
                # ValueError in a matmul, or a non-finite output from a
                # broken checkpoint) is a SERVER error.
                msg = (
                    str(e) if isinstance(e, ScoringError)
                    else f"{type(e).__name__}: {e}"
                )
                self._reply(500, {"error": msg})
                sp.set(status=500)
                return None, True
            sp.set(status=200, rows=int(x.shape[0]))
            return {"probabilities": probs.tolist()}, False


class ScoreHandler(_JsonHandler):
    """Single-checkpoint mode; the loaded model rides on the server
    object (ThreadingHTTPServer => scoring must be thread-safe: it is —
    pure numpy on read-only weights)."""

    def do_GET(self):  # noqa: N802 (http.server API)
        import urllib.parse

        parsed = urllib.parse.urlparse(self.path)
        if parsed.path == "/metrics":
            self._reply_metrics()
            return
        if parsed.path == "/debug/profile":
            self._reply_profile(parsed.query)
            return
        if parsed.path != "/healthz":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        meta = self.server.model_meta
        self._reply(
            200,
            {
                "status": "ok",
                "model": meta.get("model", "weather_mlp"),
                "input_dim": int(meta.get("input_dim", 0)),
                "horizon": int(meta.get("horizon", 1)),
                # The served artifact's lineage node id (None for
                # in-memory weights or a disabled ledger): the operator
                # joins /healthz straight to `lineage trace`.
                "lineage": getattr(self.server, "lineage_node", None),
            },
        )

    def do_POST(self):  # noqa: N802 (http.server API)
        import time

        if self.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        body = self._read_body()
        if not self._admit():
            return
        data = self._parse_data_envelope(body)
        if data is None:
            return
        t0 = time.perf_counter()
        result, server_fault = self._score(
            self.server.model_weights, self.server.model_meta, data
        )
        # Single-checkpoint mode has one implicit slot; same /metrics
        # series shape as the endpoint mode so dashboards carry over.
        self.server.slot_metrics.record(
            "default", time.perf_counter() - t0, ok=not server_fault
        )
        if result is not None:
            self._reply(200, result)


class _BatchedHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer owning a :class:`MicroBatcher`: connection
    handling stays thread-per-request (the arrival side), scoring
    funnels through the shared worker pool (the dispatch side).
    ``server_close`` drains and joins the workers."""

    _reuse_port = False

    def server_bind(self):  # noqa: N802 (socketserver API)
        if self._reuse_port:
            import socket as _socket

            self.socket.setsockopt(
                _socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1
            )
        super().server_bind()

    def server_close(self):  # noqa: N802 (http.server API)
        super().server_close()
        autoscaler = getattr(self, "autoscaler", None)
        if autoscaler is not None:
            autoscaler.close()
        batcher = getattr(self, "batcher", None)
        if batcher is not None:
            batcher.close()
        publisher = getattr(self, "metrics_publisher", None)
        if publisher is not None:
            # A cleanly-closed server leaves the fleet: retire its
            # snapshot so the still-alive pid does not keep yesterday's
            # counts in every later scrape of the same metrics dir.
            publisher.close()
        monitor = getattr(self, "history_monitor", None)
        if monitor is not None:
            monitor.close()


class _ReusePortHTTPServer(_BatchedHTTPServer):
    """SO_REUSEPORT variant for the multi-process pool: N processes
    listen on ONE port and the kernel load-balances connections across
    them — N GILs instead of one."""

    _reuse_port = True


class ServerPool:
    """Multi-process serving pool: ``processes`` forked children each
    run a full server (HTTP front end + micro-batcher + package cache)
    listening on the SAME port via ``SO_REUSEPORT``.

    One Python process tops out at its GIL: past a handful of handler
    threads, added connections buy convoy latency, not throughput. The
    pool multiplies the ceiling by the process count. Each child owns
    its :class:`_PackageCache` — caches are per-process but read the
    same persisted control-plane state and immutable package dirs, so
    rollout stage flips still apply live and atomically in every child.

    ``processes <= 1`` degrades to an in-process server on a background
    thread (no fork — the safe default inside already-threaded hosts);
    forking is for dedicated serving entry points (jobs/serve.py) and
    bench rigs. The pool reserves its port with a bound-but-unlistened
    ``SO_REUSEPORT`` socket (receives no connections; only parks the
    port number) so ``port=0`` works like the single-server modes.

    **Self-healing** (docs/SERVING.md §elasticity): with a
    ``restart_policy`` (a PR 3 :class:`~dct_tpu.resilience.supervisor.
    RestartPolicy`), an unexpected child death is classified with the
    PR 3 exit-code classifier, put on the event log
    (``serve.pool_child_death``) and healed by an exponential-backoff
    respawn (``serve.pool_respawn``) — the kernel keeps routing new
    connections to the surviving SO_REUSEPORT siblings meanwhile, so
    admitted traffic sees at most one torn connection (which keep-alive
    clients retry). The restart budget circuit-breaks
    (``serve.pool_circuit_open`` + ``wait() == 1``) when deaths outrun
    it — a pool that cannot hold capacity must page, not flap forever.
    Without a policy, the original contract stands: the FIRST child
    death tears the pool down with exit 1.

    **Elastic scaling**: :meth:`scale_up` forks fresh workers (warm AOT
    spin-up when the compile cache is armed); :meth:`scale_down`
    SIGTERMs the newest child into a graceful drain — children install
    a drain handler (finish in-flight requests, ``server_close``, exit
    0), and :meth:`wait` distinguishes a deliberately-drained child
    from a crashed one, so scale-down never trips the failure path.
    """

    def __init__(self, build_server, *, processes: int = 1,
                 host: str = "127.0.0.1", port: int = 0,
                 restart_policy=None, emit=None):
        import socket as _socket
        import threading

        self.host = host
        self.pids: list[int] = []
        self.restart_policy = restart_policy
        self.restarts_used = 0
        self.circuit_open = False
        self._build_server = build_server
        self._emit = emit
        self._lock = threading.Lock()
        self._draining: set[int] = set()
        self._index: dict[int, int] = {}
        self._spawned = 0
        self._closing = False
        self._thread = None
        self._server = None
        self._reserve = _socket.socket()
        self._reserve.setsockopt(
            _socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1
        )
        self._reserve.setsockopt(
            _socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1
        )
        self._reserve.bind((host, port))
        self.port = self._reserve.getsockname()[1]

        if processes <= 1:
            self._server = build_server(host, self.port, reuse_port=True)
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True
            )
            self._thread.start()
            return
        for _ in range(int(processes)):
            self._spawn()

    def _emit_event(self, event: str, **fields) -> None:
        try:
            (self._emit or _emit_default)("serve", event, **fields)
        except Exception:  # noqa: BLE001 — telemetry never fails the pool
            pass

    def _spawn(self) -> int:
        """Fork one serving child at the next pool index (-1 when the
        pool is closing — a scale-up racing close() must not fork a
        child nothing will ever reap). The child exports its index as
        ``DCT_SERVE_PROC_INDEX`` AND ``DCT_PROCESS_ID`` (the fault
        plan's rank slot — so ``crash_worker@proc1`` binds to pool
        worker 1) and installs a SIGTERM drain handler: finish
        in-flight requests, close the server (batcher drained, metrics
        snapshot retired), exit 0.

        The fork AND the pid bookkeeping happen under the pool lock:
        the ``wait()`` reaper classifies a death only for pids it
        knows, so a child that crashes instantly must not be reapable
        before its pid is on the books (an unknown-pid death would be
        ignored and the stale pid counted as live capacity forever)."""
        import signal
        import threading as _threading

        with self._lock:
            if self._closing:
                return -1
            index = self._spawned
            self._spawned += 1
            pid = os.fork()
            if pid != 0:
                # Parent: on the books BEFORE the lock drops, so the
                # reaper's membership check (which also takes this
                # lock) cannot see an instantly-dead child's pid as
                # unknown. The child's copy of the held lock dies with
                # the child (it never touches pool state).
                self.pids.append(pid)
                self._index[pid] = index
                return pid

        # ---- forked child from here on: serve until SIGTERM, drain --
        code = 0
        try:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            try:
                # A SIGKILLed pool parent cannot clean up: without
                # this, its children keep serving as orphans and
                # hold the port forever (observed via an OOM-style
                # hard kill). Linux parent-death signal turns that
                # into an ordinary graceful drain; elsewhere this
                # is a no-op and orphan cleanup stays operational.
                import ctypes

                libc = ctypes.CDLL(None, use_errno=True)
                libc.prctl(1, signal.SIGTERM, 0, 0, 0)  # PR_SET_PDEATHSIG
                if os.getppid() == 1:
                    os._exit(0)  # parent died before prctl landed
            except Exception:  # noqa: BLE001 — best-effort, non-Linux
                pass
            os.environ["DCT_SERVE_PROC_INDEX"] = str(index)
            os.environ["DCT_PROCESS_ID"] = str(index)
            server = self._build_server(
                self.host, self.port, reuse_port=True
            )

            def _drain(signum, frame):
                # shutdown() blocks until serve_forever returns, so
                # it must not run on the signal-interrupted main
                # thread (that IS serve_forever's thread).
                _threading.Thread(
                    target=server.shutdown, daemon=True
                ).start()

            signal.signal(signal.SIGTERM, _drain)
            server.serve_forever()
            server.server_close()
        except BaseException:  # noqa: BLE001 — a child must
            # never fall back into the parent's code; it reports
            # (stderr + nonzero exit, which wait() surfaces) and
            # dies.
            import traceback

            traceback.print_exc()
            code = 1
        finally:
            os._exit(code)
        raise RuntimeError("unreachable")  # keeps the int contract honest

    def size(self) -> int:
        """Live (non-draining) child count — the autoscaler's view. 1
        in in-process mode (one server thread is the whole pool)."""
        if self._server is not None:
            return 1
        with self._lock:
            return len([p for p in self.pids if p not in self._draining])

    def scale_up(self, n: int = 1) -> list[int]:
        """Fork ``n`` fresh workers onto the shared port; returns their
        pids. New children spin from the same warmed AOT/package state
        as the originals (the compile cache is process-shared on disk),
        so time-to-capacity is bounded by spin-up, not compilation."""
        spawned = []
        for _ in range(max(0, int(n))):
            pid = self._spawn()
            if pid < 0:  # closing: nothing would ever reap the child
                break
            self._emit_event(
                "serve.pool_spawn", pid=pid,
                index=self._index.get(pid), size=self.size(),
            )
            spawned.append(pid)
        return spawned

    def scale_down(self, n: int = 1) -> list[int]:
        """Gracefully drain the ``n`` newest workers (never below one):
        mark them draining, SIGTERM them, and let :meth:`wait` reap the
        clean exits WITHOUT tripping the child-death failure path."""
        import signal

        victims: list[int] = []
        with self._lock:
            live = [p for p in self.pids if p not in self._draining]
            while live and len(live) > 1 and len(victims) < max(0, int(n)):
                pid = live.pop()  # newest first
                victims.append(pid)
                self._draining.add(pid)
        for pid in victims:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        return victims

    def wait(self) -> int:
        """Block until the pool stops serving.

        In-process mode joins the server thread (returns 0 once
        :meth:`close` shuts it down). Forked mode supervises the
        children: a deliberately-drained child (scale-down / close) is
        reaped silently; an unexpected death either tears the pool down
        (no restart policy — exit 1, the original contract) or is
        classified and respawned under the policy's backoff until the
        budget circuit-breaks (exit 1). Returns 0 only for a clean
        close/drain."""
        import time as _time

        from dct_tpu.resilience.supervisor import (
            FREE_RESTARTS,
            classify_failure,
        )

        if self._server is not None:
            if self._thread is not None:
                self._thread.join()
            return 0
        if not self.pids:
            return 1
        while True:
            try:
                pid, status = os.waitpid(-1, 0)
            except OSError:
                # No children left to reap: a clean close() got them
                # all first. Anything else is an inconsistency.
                return 0 if self._closing else 1
            code = os.waitstatus_to_exitcode(status)
            with self._lock:
                known = pid in self.pids
                if known:
                    self.pids.remove(pid)
                index = self._index.pop(pid, None)
                draining = pid in self._draining
                self._draining.discard(pid)
                closing = self._closing
                remaining = len(self.pids)
            if not known:
                continue
            if closing:
                if remaining == 0:
                    return 0
                continue
            if draining:
                # A scaled-down child finished its drain: expected,
                # logged, NOT a failure — whatever its exit code (a
                # SIGTERM that landed before the drain handler was
                # installed shows as a signal death; the intent was
                # still ours).
                self._emit_event(
                    "serve.pool_drained", pid=pid, code=code,
                    index=index, size=remaining,
                )
                if remaining == 0:
                    return 0
                continue
            classification = classify_failure([code])
            if classification == "success":
                # A serving child has no business exiting cleanly on
                # its own; lost capacity is lost capacity.
                classification = "crash"
            self._emit_event(
                "serve.pool_child_death", pid=pid, code=code,
                classification=classification, index=index,
                size=remaining,
            )
            if self.restart_policy is None:
                self.close()
                return 1
            if not self.restart_policy.allows(
                self.restarts_used, classification
            ):
                self.circuit_open = True
                self._emit_event(
                    "serve.pool_circuit_open",
                    restarts_used=self.restarts_used,
                    classification=classification,
                )
                self.close()
                return 1
            delay = self.restart_policy.delay(self.restarts_used)
            if classification not in FREE_RESTARTS:
                self.restarts_used += 1
            _time.sleep(delay)
            new_pid = self._spawn()
            if new_pid < 0:  # close() won the race mid-backoff
                continue
            self._emit_event(
                "serve.pool_respawn", pid=new_pid, died=pid,
                backoff_s=round(delay, 3),
                restarts_used=self.restarts_used,
                classification=classification,
            )

    def close(self) -> None:
        import signal

        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            if self._thread is not None:
                self._thread.join(10.0)
            self._server = None
            self._thread = None
        with self._lock:
            self._closing = True
            pids = list(self.pids)
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        for pid in pids:
            try:
                os.waitpid(pid, 0)
            except OSError:
                pass  # the wait() loop reaped it first
        with self._lock:
            self.pids = [p for p in self.pids if p not in pids]
            self._draining.clear()
        try:
            self._reserve.close()
        except OSError:
            pass

    def __enter__(self) -> "ServerPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _emit_default(component: str, event: str, **fields) -> None:
    """Late-bound emit through the process-default event log (the SLO
    monitor's alert sink; resolved per call so env-built sinks and
    monkeypatched tests both see their own log)."""
    from dct_tpu.observability import events as _events

    _events.get_default().emit(component, event, **fields)


def _arm_metrics_plane(server) -> None:
    """Attach the cross-process metrics plane to a freshly-built server
    when ``DCT_METRICS_DIR`` is configured: a snapshot publisher over
    the slot metrics' registry (throttled on the request path, timer-
    refreshed when idle) and the SLO monitor evaluated at scrape time.
    A malformed ``DCT_SLO_SPEC`` disables SLO monitoring loudly
    (stderr) instead of killing the serving process."""
    from dct_tpu.config import ObservabilityConfig

    obs = ObservabilityConfig.from_env()
    if not obs.metrics_dir:
        return
    from dct_tpu.observability.aggregate import SnapshotPublisher
    from dct_tpu.observability.slo import (
        SLOMonitor,
        SLOSpecError,
        parse_slo_spec,
    )

    server.metrics_publisher = SnapshotPublisher(
        server.slot_metrics.registry,
        obs.metrics_dir,
        proc=f"serve-{os.getpid()}",
        interval_s=obs.metrics_publish_s,
    )
    server.slot_metrics.publisher = server.metrics_publisher
    server.metrics_stale_s = obs.metrics_stale_s
    # The ISSUE 17 detection plane: history reader + anomaly detector +
    # incident assembler + poll thread, self-armed off DCT_TS_DIR (None
    # otherwise). Its gauges land on the same registry the publisher
    # already snapshots, so dct_anomaly_* reach every scrape for free.
    from dct_tpu.observability import detect as _detect

    server.history_monitor = _detect.arm_from_env(
        registry=server.slot_metrics.registry, emit=_emit_default,
    )
    try:
        specs = parse_slo_spec(obs.slo_spec)
    except SLOSpecError as e:
        import sys as _sys

        print(f"[serving] DCT_SLO_SPEC disabled: {e}",
              file=_sys.stderr, flush=True)
        return
    if specs:
        monitor = server.history_monitor
        server.slo_monitor = SLOMonitor(
            specs,
            fast_window_s=obs.slo_fast_window_s,
            slow_window_s=obs.slo_slow_window_s,
            burn_threshold=obs.slo_burn_threshold,
            emit=_emit_default,
            events_path=(
                os.path.join(obs.events_dir, "events.jsonl")
                if obs.enabled and obs.events_dir else None
            ),
            # Armed: burn windows come from the on-disk history and an
            # alert edge triggers an incident bundle.
            history=monitor.reader if monitor is not None else None,
            on_alert=(
                monitor.incidents.on_slo_alert
                if monitor is not None and monitor.incidents is not None
                else None
            ),
        )


def _new_score_server(handler_cls, host: str, port: int, serving=None,
                      reuse_port: bool = False):
    """Shared construction for both server modes: metrics, the
    micro-batcher (wired to the metrics' batch/queue histograms), the
    fast-parse flag, and the metrics plane (snapshot publisher + SLO
    monitor) when ``DCT_METRICS_DIR`` arms it — all from
    :class:`ServingConfig` / :class:`ObservabilityConfig` (env-driven
    unless an explicit serving config is passed)."""
    if serving is None:
        from dct_tpu.config import ServingConfig

        serving = ServingConfig.from_env()
    cls = _ReusePortHTTPServer if reuse_port else _BatchedHTTPServer
    server = cls((host, port), handler_cls)
    server.slot_metrics = _SlotMetrics()
    server.batcher = MicroBatcher(
        max_batch=serving.max_batch,
        window_ms=serving.batch_window_ms,
        workers=serving.workers,
        engine=serving.engine,
        metrics=server.slot_metrics,
    )
    server.fast_parse = serving.fast_parse
    server.admission = None
    server.autoscaler = None
    if serving.admit:
        from dct_tpu.serving.admission import AdmissionController

        if serving.workers <= 0:
            import sys as _sys

            # Inline scoring (workers=0) has no queue: queued_rows is
            # structurally 0 and the wait estimate never materializes,
            # so the gate can never fire. Say so instead of letting the
            # operator believe overload protection is armed.
            print(
                "[serving] DCT_SERVE_ADMIT=1 with DCT_SERVE_WORKERS=0: "
                "inline scoring has no queue to bound — admission "
                "control cannot shed in this mode",
                file=_sys.stderr, flush=True,
            )
        server.admission = AdmissionController.from_config(
            serving,
            metrics_registry=server.slot_metrics.registry,
            emit=_emit_default,
        )
    if serving.autoscale and serving.processes <= 1:
        # In-process mode: the autoscaler's capacity axis is the
        # batcher's scoring threads. In pool mode (processes > 1) each
        # child must NOT run its own controller — the pool parent
        # scales processes instead (jobs/serve.py).
        from dct_tpu.serving.autoscale import (
            Autoscaler,
            WorkerScaleTarget,
            batcher_signal_fn,
        )

        server.autoscaler = Autoscaler.from_config(
            WorkerScaleTarget(server.batcher), serving,
            signal_fn=batcher_signal_fn(server), emit=_emit_default,
            registry=server.slot_metrics.registry,
        ).start()
    _arm_metrics_plane(server)
    return server


def make_server_from_weights(
    weights: dict, meta: dict, *, host: str = "127.0.0.1", port: int = 0,
    serving=None, reuse_port: bool = False,
):
    """Single-model server over an in-memory (weights, meta) pair — the
    checkpoint-free construction the loadgen selftest and hermetic tests
    use (numpy + stdlib only, no checkpoint IO)."""
    server = _new_score_server(
        ScoreHandler, host, port, serving, reuse_port
    )
    server.model_weights = weights
    server.model_meta = meta
    return server


def make_server(ckpt_path: str, *, host: str = "127.0.0.1", port: int = 0,
                serving=None, reuse_port: bool = False):
    """Load the checkpoint and return a ready (unstarted) HTTP server;
    ``port=0`` binds an ephemeral port (``server.server_address[1]``
    after construction)."""
    weights, meta = weights_from_checkpoint(ckpt_path)
    # Checkpoint-mode AOT root: the sibling aot/ dir of the models dir
    # the checkpoint lives in — shared with the trainer's store, so a
    # serving worker over a raw checkpoint still spins up pre-compiled
    # when the compile cache is armed (serving/batching.py).
    meta["_aot_dir"] = os.path.join(
        os.path.dirname(os.path.abspath(ckpt_path)), "aot"
    )
    server = make_server_from_weights(
        weights, meta, host=host, port=port, serving=serving,
        reuse_port=reuse_port,
    )
    # Model-load lineage: the served checkpoint's node (same id the
    # trainer minted — content addressing) plus this process's load
    # sighting; the node id rides on the server for /healthz.
    from dct_tpu.observability import lineage as _lineage

    lin = _lineage.get_default()
    if lin.enabled:
        ckpt_nid = lin.node("checkpoint", path=ckpt_path)
        load_nid = lin.node(
            "model_load",
            content={"artifact": ckpt_nid, "pid": os.getpid()},
            attrs={
                "ckpt": os.path.abspath(ckpt_path),
                "pid": os.getpid(), "mode": "checkpoint",
            },
        )
        lin.edge("served_by", ckpt_nid, load_nid)
        server.lineage_node = ckpt_nid
    return server


class _PackageCache:
    """Thread-safe weights cache keyed by package dir.

    ThreadingHTTPServer handles each request on its own thread, so the
    cache needs a lock; and deployments retire as rollouts proceed, so
    entries whose package dir no longer backs ANY current deployment are
    evicted on the next load — a long-lived endpoint server must not
    accumulate a full weight set for every package ever served.

    Concurrent first requests for the same package may both run the
    loader (load happens outside the lock — package IO must not stall
    other slots' cache hits); the first store wins and the duplicate is
    dropped, which is benign for immutable read-only packages.

    Eviction is GENERATION-GATED: each request carries the state file's
    mtime from before its snapshot read, and only the newest generation
    observed may evict. A straggler request holding a pre-transition
    snapshot can therefore never evict a package a newer deployment just
    made live (which would force a full reload — a latency spike on
    exactly the canary slot mid-rollout).
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._entries: dict = {}
        self._generation = -1
        self._live: set = set()

    def get_or_load(
        self, pkg: str, loader, live_pkgs, generation: int = 0
    ) -> tuple:
        with self._lock:
            if generation >= self._generation:
                self._generation = generation
                self._live = set(live_pkgs)
                for stale in set(self._entries) - self._live:
                    del self._entries[stale]
            cached = self._entries.get(pkg)
        if cached is None:
            loaded = loader()
            with self._lock:
                if pkg in self._live or generation >= self._generation:
                    cached = self._entries.setdefault(pkg, loaded)
                else:
                    # A newer generation retired this package while the
                    # straggler was loading: serve it this once, but do
                    # NOT resurrect it into the cache (ADVICE r3).
                    cached = loaded
        return cached


#: Size buckets for the batcher's batch-rows / queue-depth histograms
#: (powers of two up to 4x the default max batch).
_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


class _SlotMetrics:
    """Thread-safe per-slot request metrics: what an operator watches
    during a canary (the Azure endpoint surfaces the same per-deployment
    request/latency series).

    Since ISSUE 8 the state lives in a
    :class:`dct_tpu.observability.metrics.MetricsRegistry` — the common
    shape the cross-process metrics plane publishes and merges — instead
    of an ad-hoc dict-of-dicts; the surface (``record`` /
    ``observe_batch`` / ``snapshot`` / ``prometheus_text``) and the
    metric names are unchanged. A sliding window of the last 1024
    latencies per slot rides alongside for the ``/healthz`` p50/p99
    snapshot (recent traffic, not all-time history); the cumulative
    registry histogram feeds ``GET /metrics``.

    The micro-batcher feeds three server-wide histograms through
    :meth:`observe_batch` — flushed batch rows, requests merged per
    flush, and the queue depth left behind — the saturation evidence an
    operator reads off ``/metrics`` (batch size hugging 1 = idle; rows
    pinned at the cap with queue depth climbing = past the knee).

    When a :class:`~dct_tpu.observability.aggregate.SnapshotPublisher`
    is attached, every ``record`` offers it a throttled publish (one
    clock read inside the throttle window — hot-path safe)."""

    def __init__(self):
        import threading

        from dct_tpu.observability.metrics import MetricsRegistry

        self._lock = threading.Lock()
        self._by_slot: dict = {}
        self.publisher = None
        self.registry = MetricsRegistry()
        self._req = self.registry.counter(
            "dct_requests_total",
            "Scoring requests served, by deployment slot.",
        )
        self._err = self.registry.counter(
            "dct_request_errors_total",
            "Server-fault scoring errors, by deployment slot "
            "(client 4xx never counts against a slot).",
        )
        self._lat = self.registry.histogram(
            "dct_request_latency_seconds",
            "End-to-end scoring latency, by deployment slot.",
        )
        hist = self.registry.histogram
        self._batch_rows_h = hist(
            "dct_serve_batch_rows",
            "Rows scored per micro-batch flush (server-wide).",
            buckets=_SIZE_BUCKETS,
        )
        self._batch_requests_h = hist(
            "dct_serve_batch_requests",
            "Logical requests merged per micro-batch flush.",
            buckets=_SIZE_BUCKETS,
        )
        self._queue_depth_h = hist(
            "dct_serve_queue_depth",
            "Rows still queued behind each flush (saturation signal).",
            buckets=_SIZE_BUCKETS,
        )
        # READ handles (tests/diagnostics); all mutation goes through
        # the Histogram objects above so it serializes under the
        # registry lock with snapshot()/render() — an accumulator
        # mutated under a different lock could be snapshotted torn
        # (non-monotone cumulative counts mid-increment).
        self._batch_rows = self._batch_rows_h.accumulator()
        self._batch_requests = self._batch_requests_h.accumulator()
        self._queue_depth = self._queue_depth_h.accumulator()

    def observe_batch(
        self, rows: int, requests: int, queue_depth: int
    ) -> None:
        """One micro-batch flush: ``rows`` scored as one dispatch for
        ``requests`` logical requests, ``queue_depth`` rows still
        queued behind it."""
        self._batch_rows_h.observe(rows)
        self._batch_requests_h.observe(requests)
        self._queue_depth_h.observe(queue_depth)
        if self.publisher is not None:
            # Flushes mutate plane-visible histograms too — offer the
            # throttled publish here as well, so a batcher-heavy but
            # record-light window (mirror traffic) still stays fresh.
            try:
                self.publisher.maybe_publish()
            except Exception:  # noqa: BLE001 — telemetry never fails a flush
                pass

    def record(self, slot: str, seconds: float, ok: bool) -> None:
        labels = {"slot": slot}
        with self._lock:
            m = self._by_slot.setdefault(
                slot, {"requests": 0, "errors": 0, "lat": []}
            )
            m["requests"] += 1
            if not ok:
                m["errors"] += 1
            lat = m["lat"]
            lat.append(seconds)
            if len(lat) > 1024:
                del lat[: len(lat) - 1024]
        self._req.inc(1.0, labels)
        # inc(0) materializes the slot's error series at 0, so a clean
        # slot still renders an explicit zero (rate() needs the sample).
        self._err.inc(0.0 if ok else 1.0, labels)
        self._lat.observe(seconds, labels)
        if self.publisher is not None:
            try:
                self.publisher.maybe_publish()
            except Exception:  # noqa: BLE001 — telemetry never fails serving
                pass

    def snapshot(self) -> dict:
        import statistics

        with self._lock:
            out = {}
            for slot, m in self._by_slot.items():
                lat = sorted(m["lat"])
                entry = {"requests": m["requests"], "errors": m["errors"]}
                if lat:
                    entry["p50_ms"] = round(
                        statistics.median(lat) * 1e3, 3
                    )
                    entry["p99_ms"] = round(
                        lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3,
                        3,
                    )
                out[slot] = entry
            return out

    def prometheus_text(self) -> str:
        """Text exposition (0.0.4) of this process's series (the
        metrics plane's aggregated body is built in ``_reply_metrics``
        from the published snapshots instead)."""
        return self.registry.render()


class EndpointScoreHandler(_JsonHandler):
    """Rollout-endpoint mode (see module docstring)."""

    def _client(self):
        from dct_tpu.deploy.local import LocalEndpointClient

        # Fresh read of the persisted state: rollout stages run in other
        # processes and must take effect without a server restart. The
        # mtime is taken BEFORE the read, so the generation can only
        # under-state the snapshot's age — stale cache evictions are
        # skipped, never wrongly applied (_PackageCache docstring).
        state_path = self.server.state_path
        try:
            generation = os.stat(state_path).st_mtime_ns
        except OSError:
            generation = 0
        self._state_generation = generation
        return LocalEndpointClient(state_path=state_path)

    def _load_slot(self, client, slot: str):
        """(weights, meta) via the server-lifetime package cache —
        packages are immutable once written, so only the state JSON
        needs the per-request re-read. Retired packages evict."""
        name = self.server.endpoint_name
        deployments = client.endpoints[name].deployments
        # First sighting records the served_by lineage hop; memoized
        # after, so the hot path pays a dict hit.
        _package_lineage_node(
            deployments[slot].package_dir, endpoint=name, slot=slot,
        )
        return self.server.package_cache.get_or_load(
            deployments[slot].package_dir,
            lambda: client.load_slot(name, slot),
            [d.package_dir for d in deployments.values()],
            generation=getattr(self, "_state_generation", 0),
        )

    def do_GET(self):  # noqa: N802 (http.server API)
        import urllib.parse

        parsed = urllib.parse.urlparse(self.path)
        route = parsed.path
        if route == "/metrics":
            self._reply_metrics()
            return
        if route == "/debug/profile":
            self._reply_profile(parsed.query)
            return
        if route != "/healthz":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        client = self._client()
        name = self.server.endpoint_name
        if not client.endpoint_exists(name):
            self._reply(503, {"error": f"endpoint {name} not provisioned"})
            return
        deployments = client.endpoints[name].deployments
        self._reply(
            200,
            {
                "status": "ok",
                "endpoint": name,
                "traffic": client.get_traffic(name),
                "mirror_traffic": client.get_mirror_traffic(name),
                "deployments": client.list_deployments(name),
                "metrics": self.server.slot_metrics.snapshot(),
                # Per-slot lineage node ids (content-addressed package
                # identity): the one-command join from "what is this
                # endpoint serving?" to `lineage trace <id>`.
                "lineage": {
                    slot: _package_lineage_node(
                        d.package_dir, endpoint=name, slot=slot,
                    )
                    for slot, d in deployments.items()
                },
            },
        )

    def do_POST(self):  # noqa: N802 (http.server API)
        import random
        import time
        import urllib.parse

        parsed = urllib.parse.urlparse(self.path)
        if parsed.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        body = self._read_body()
        if not self._admit():
            return
        data = self._parse_data_envelope(body)
        if data is None:
            return
        client = self._client()
        name = self.server.endpoint_name
        live = {
            k: v for k, v in client.get_traffic(name).items() if v > 0
        }
        pinned = urllib.parse.parse_qs(parsed.query).get("slot")
        if pinned:
            slot = pinned[0]
        elif live:
            # Weighted random routing — the canary's 10% is a real 10%.
            slot = random.choices(
                list(live), weights=list(live.values())
            )[0]
        else:
            self._reply(503, {"error": f"endpoint {name} has no live traffic"})
            return
        if slot not in client.list_deployments(name):
            # A request naming a nonexistent slot is the CLIENT's fault
            # (Azure's model-deployment header behaves the same).
            self._reply(404, {"error": f"no deployment {slot!r} on {name}"})
            return
        t0 = time.perf_counter()
        try:
            weights, meta = self._load_slot(client, slot)
        except Exception as e:  # noqa: BLE001 — unreadable package:
            self.server.slot_metrics.record(
                slot, time.perf_counter() - t0, ok=False
            )
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        result, server_fault = self._score(
            weights, meta, data, slot=slot,
            trace_id=_package_trace_id(
                client.endpoints[name].deployments[slot].package_dir
            ),
        )
        # Only SERVER faults count against the slot: a client's bad
        # payload (400) must not spike the canary's error series and
        # trigger a rollback of a healthy deployment.
        self.server.slot_metrics.record(
            slot, time.perf_counter() - t0, ok=not server_fault
        )
        if result is None:
            return
        self._reply(200, {**result, "slot": slot})
        # Mirror (shadow) traffic AFTER the live response is flushed —
        # a slow or broken shadow must never touch live latency (exactly
        # Azure's mirror semantics: the caller never sees it). Outcomes
        # ARE recorded under the shadow slot: evaluating the shadow is
        # what mirror traffic exists for.
        for shadow, pct in client.get_mirror_traffic(name).items():
            if (
                pct > 0
                and shadow != slot
                and shadow in client.list_deployments(name)
                and random.random() * 100 < pct
            ):
                ts = time.perf_counter()
                try:
                    w_s, m_s = self._load_slot(client, shadow)
                    # Shadow scoring rides the same micro-batcher (it
                    # may merge with other mirrored copies); capture
                    # stays strictly PER LOGICAL REQUEST — one paired
                    # record with this request's own probability rows,
                    # however the flush grouped them.
                    p_s = self.server.batcher.score(
                        w_s, m_s, validate_payload(m_s, data), slot=shadow
                    )
                    shadow_ok = True
                    if result is not None:
                        # Mirror capture: the paired live/shadow
                        # responses are the prediction-disagreement
                        # evidence the shadow->canary promotion gate
                        # scores (evaluation.drift). Append-only JSONL,
                        # best-effort, after the live reply flushed.
                        client.append_mirror_record({
                            "ts": round(time.time(), 6),
                            "endpoint": name,
                            "live_slot": slot,
                            "shadow_slot": shadow,
                            "live_probs": result["probabilities"],
                            "shadow_probs": p_s.tolist(),
                        })
                except Exception:  # noqa: BLE001 — shadow failures are
                    shadow_ok = False  # invisible to the caller by design
                self.server.slot_metrics.record(
                    shadow, time.perf_counter() - ts, ok=shadow_ok
                )


def make_endpoint_server(
    endpoint: str, *, state_path: str | None = None,
    host: str = "127.0.0.1", port: int = 0, serving=None,
    reuse_port: bool = False,
):
    """HTTP server over the local rollout endpoint ``endpoint`` whose
    control-plane state lives at ``state_path`` (default: the
    DCT_LOCAL_ENDPOINT_STATE env the rollout DAG uses).

    The worker pool shares deployed-package state through the server's
    single :class:`_PackageCache`, so blue/green flips, shadow mirrors
    and canary splits stay atomic under concurrency: the batch key is
    the weights object the cache resolved, and a request routed to a
    new package can never merge into a flush of the old one."""
    server = _new_score_server(
        EndpointScoreHandler, host, port, serving, reuse_port
    )
    server.endpoint_name = endpoint
    server.state_path = state_path or os.environ.get(
        "DCT_LOCAL_ENDPOINT_STATE"
    )
    server.package_cache = _PackageCache()
    return server


def serve_forever(ckpt_path: str, *, host: str = "0.0.0.0",
                  port: int = 8901) -> None:
    server = make_server(ckpt_path, host=host, port=port)
    meta = server.model_meta
    print(
        f"serving {meta.get('model', 'weather_mlp')} from {ckpt_path} on "
        f"http://{host}:{port} (POST /score, GET /healthz)",
        flush=True,
    )
    server.serve_forever()
