"""Pack-side weight quantization: f32 deploy package -> int8/bf16 twin.

The serve side lives in :mod:`dct_tpu.serving.runtime`
(:class:`~dct_tpu.serving.runtime.QuantTensor`,
:func:`~dct_tpu.serving.runtime.assemble_weights`) so the generated
``score.py`` stays self-contained; this module only PRODUCES quantized
packages and is never embedded.

Two variants:

- ``int8`` — per-output-channel symmetric scales over every 2D matmul
  kernel (``w\\d+`` MLP stacks, any 2D ``*kernel`` flax path); biases,
  layernorm affines, and stacked 3D+ trees (MoE experts, pp_stages)
  stay f32. Served through the integer-exact GEMM, which is
  row-invariant by construction AND faster than the f32 twin's per-row
  ``rows_mm`` flush path.
- ``bf16`` — every float leaf rounded to bf16 bit patterns (uint16
  storage, half the npz bytes), widened back to f32 at load; compute
  stays f32 on bf16-rounded weights, so the row-invariance machinery is
  untouched.

The quantized package is just another challenger: ship it through the
champion/challenger gates (docs/SERVING.md §quantized scorers) so an
accuracy regression is a gate ``hold`` with bootstrap evidence, never a
silent cliff. ``DCT_QUANT_PROB_BOUND`` documents the max-abs-prob
parity bound the smoke/bench rigs assert against the f32 twin.
"""

from __future__ import annotations

import json
import os
import re

import numpy as np

#: Documented serving parity bound: max |p_quant - p_f32| over the eval
#: batch must stay below this for a healthy int8 package (bf16 lands far
#: inside it). The gate pipeline remains the real safety net — this
#: bound is the loud first tripwire.
DEFAULT_PROB_BOUND = 0.05

_MLP_KERNEL_RE = re.compile(r"w\d+$")


def prob_bound() -> float:
    """The asserted max-abs-prob parity bound (env-overridable)."""
    from dct_tpu.config import _env

    return float(_env("DCT_QUANT_PROB_BOUND", DEFAULT_PROB_BOUND, float))


def is_matmul_kernel(key: str, arr: np.ndarray) -> bool:
    """True for the 2D matmul kernels the int8 variant packs: MLP
    ``w<i>`` stacks and any 2D flax ``*kernel`` leaf. 3D+ stacks
    (``pp_stages/*``, MoE expert banks) are structurally excluded by
    the ndim check."""
    return arr.ndim == 2 and (
        key.endswith("kernel") or _MLP_KERNEL_RE.fullmatch(key) is not None
    )


def quantize_array_int8(a: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """[K, M] f32 -> (int8 [K, M], f32 per-output-channel scale [M]).

    Symmetric: scale = max|w|/127 per column; an all-zero channel keeps
    scale 0 (dequantizes to exact zeros)."""
    a = np.asarray(a, np.float32)
    scale = (np.abs(a).max(axis=0) / np.float32(127.0)).astype(np.float32)
    safe = np.where(scale > 0, scale, 1).astype(np.float32)
    q = np.clip(np.rint(a / safe[None, :]), -127, 127).astype(np.int8)
    return q, scale


def quantize_weights(
    weights: dict, meta: dict, dtype: str = "int8"
) -> tuple[dict, dict]:
    """(f32 serving weights, meta) -> (flat quantized dict, meta').

    The returned flat dict uses the ``k::q8``/``k::scale``/``k::bf16``
    key grammar :func:`runtime.assemble_weights` reconstitutes; meta'
    carries a ``quant`` stanza ({dtype, prob_bound}) so every consumer
    (package loader, jax scorer, gates, bench) can see the variant
    without sniffing key suffixes."""
    from dct_tpu.serving.runtime import bf16_pack

    if dtype not in ("int8", "bf16"):
        raise ValueError(
            f"quantize dtype must be 'int8' or 'bf16', got {dtype!r}"
        )
    flat: dict = {}
    for k, v in weights.items():
        v = np.asarray(v)
        if dtype == "int8" and is_matmul_kernel(k, v):
            q, scale = quantize_array_int8(v)
            flat[f"{k}::q8"] = q
            flat[f"{k}::scale"] = scale
        elif dtype == "bf16" and np.issubdtype(v.dtype, np.floating):
            flat[f"{k}::bf16"] = bf16_pack(v)
        else:
            flat[k] = v
    meta_out = dict(meta)
    meta_out["quant"] = {"dtype": dtype, "prob_bound": prob_bound()}
    return flat, meta_out


def quantize_package(
    package_dir: str, out_dir: str, dtype: str | None = None
) -> dict:
    """An f32 deploy package -> a fully servable quantized sibling.

    Reads ``model.npz``/``model_meta.json`` from ``package_dir``,
    quantizes (``dtype`` defaults to ``DCT_QUANT_DTYPE``, int8), and
    writes a COMPLETE package (npz + meta + generated score.py +
    conda.yaml) into ``out_dir`` — a first-class challenger for the
    promotion gates. Returns the quantized meta."""
    from dct_tpu.config import _env
    from dct_tpu.serving.score_gen import _publish_text, render_score_py

    if dtype is None:
        dtype = str(_env("DCT_QUANT_DTYPE", "int8", str)).strip().lower()
    npz = np.load(os.path.join(package_dir, "model.npz"))
    weights = {k: npz[k] for k in npz.files}
    with open(os.path.join(package_dir, "model_meta.json")) as f:
        meta = json.load(f)
    if "quant" in meta:
        raise ValueError(
            f"{package_dir} is already quantized "
            f"({meta['quant'].get('dtype')}) — re-quantizing compounds "
            "rounding; start from the f32 package"
        )
    flat, meta_out = quantize_weights(weights, meta, dtype)
    os.makedirs(out_dir, exist_ok=True)
    npz_path = os.path.join(out_dir, "model.npz")
    npz_tmp = f"{npz_path}.tmp.{os.getpid()}"
    with open(npz_tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(npz_tmp, npz_path)
    _publish_text(
        os.path.join(out_dir, "model_meta.json"),
        json.dumps(meta_out, indent=2),
    )
    _publish_text(os.path.join(out_dir, "score.py"), render_score_py())
    from dct_tpu.serving.score_gen import _CONDA_YAML

    _publish_text(os.path.join(out_dir, "conda.yaml"), _CONDA_YAML)
    return meta_out


def main(argv: list | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Quantize an f32 deploy package (int8/bf16 twin)."
    )
    ap.add_argument("package_dir")
    ap.add_argument("out_dir")
    ap.add_argument("--dtype", choices=("int8", "bf16"), default=None)
    args = ap.parse_args(argv)
    meta = quantize_package(args.package_dir, args.out_dir, args.dtype)
    print(json.dumps(meta.get("quant", {})))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
