from dct_tpu.serving.runtime import mlp_forward_numpy, softmax_numpy, score_payload  # noqa: F401
from dct_tpu.serving.score_gen import generate_score_package  # noqa: F401
