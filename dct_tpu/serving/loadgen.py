"""Load generation for the serving tier: qps, tail latency, saturation.

"Serves heavy traffic" is a slogan until it is a tracked number; this
module makes it one. Two generator shapes, both stdlib + numpy only:

- **Closed loop** — ``concurrency`` clients, each sending its next
  request the moment the previous response lands (keep-alive
  connections). Throughput at a fixed in-flight population: the shape
  that finds the saturation knee.
- **Open loop** — requests dispatched at a target arrival rate
  (``qps``) regardless of completions, the arrival process a real
  traffic front end faces; queueing delay shows up in the latency tail
  instead of being absorbed by back-pressure the way a closed loop
  hides it.

``sweep_closed_loop`` walks concurrency levels and reports the knee:
the last level whose throughput still improved materially over the
previous one — beyond it, added concurrency buys queue depth, not qps.

The CLI doubles as the CI smoke (``--selftest``): a synthetic MLP
behind a micro-batched server, fixed request counts at two concurrency
levels, asserting non-zero qps and batched responses bit-identical to
sequential single-row scoring — dependency-free (no jax, no
checkpoint IO) so a broken accelerator wheel can never mask a broken
serving tier.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import numpy as np


class _Collector:
    """Thread-safe latency/error/shed sink shared by generator threads.

    Admitted and shed traffic are SEPARATE populations: a 429 from
    admission control (docs/SERVING.md §elasticity) is neither a
    success nor an error — folding its (deliberately fast) turnaround
    into the latency list would flatter p50/p99, and counting it as an
    error would page on behavior the server chose. ``latencies`` holds
    admitted (200) requests only; ``shed_latencies`` the 429
    turnarounds; ``errors`` everything actually broken."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: list[float] = []
        self.shed_latencies: list[float] = []
        self.errors = 0

    def ok(self, seconds: float) -> None:
        with self.lock:
            self.latencies.append(seconds)

    def shed(self, seconds: float) -> None:
        with self.lock:
            self.shed_latencies.append(seconds)

    def fail(self) -> None:
        with self.lock:
            self.errors += 1


class _Client:
    """One keep-alive HTTP connection; reconnects on transport errors
    (a fresh connection per request would measure TCP setup, not the
    serving tier)."""

    def __init__(self, host: str, port: int, path: str = "/score",
                 timeout: float = 30.0, headers: dict | None = None):
        self.host, self.port, self.path = host, port, path
        self.timeout = timeout
        self.headers = dict(headers or {})
        #: Retry-After (seconds) off the most recent response, or None.
        self.last_retry_after: float | None = None
        self._conn: http.client.HTTPConnection | None = None

    def _connect(self) -> http.client.HTTPConnection:
        if self._conn is None:
            import socket

            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
            self._conn.connect()
            # Nagle off, like the server side: a small POST body queued
            # behind its header otherwise waits out the peer's
            # delayed-ACK timer (~40 ms) on every keep-alive request.
            self._conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def post(self, body: bytes) -> tuple[int, bytes]:
        """(status, body); raises on transport failure after two
        reconnect attempts. Keep-alive connections drop legitimately,
        and a dying SO_REUSEPORT pool worker RSTs both its in-flight
        responses AND connections still in its accept queue — an
        IMMEDIATE reconnect can race that teardown window onto the same
        dying socket, so the second retry backs off a beat before
        dialing (by then the kernel routes to a surviving sibling)."""
        for attempt in (0, 1, 2):
            if attempt > 1:
                time.sleep(0.05)
            conn = self._connect()
            try:
                conn.request(
                    "POST", self.path, body,
                    {"Content-Type": "application/json", **self.headers},
                )
                resp = conn.getresponse()
                body_out = resp.read()
                self.last_retry_after = None
                if resp.status == 429:
                    # Prefer the precise jittered value in the JSON
                    # body (the header is RFC delta-seconds — integer,
                    # coarse); fall back to the header.
                    try:
                        self.last_retry_after = float(
                            json.loads(body_out)["retry_after_s"]
                        )
                    except (ValueError, KeyError, TypeError):
                        ra = resp.getheader("Retry-After")
                        try:
                            self.last_retry_after = (
                                float(ra) if ra is not None else None
                            )
                        except ValueError:
                            pass
                return resp.status, body_out
            except (http.client.HTTPException, OSError):
                self.close()
                if attempt >= 2:
                    raise
        raise RuntimeError("unreachable")


def _percentile_ms(latencies: list[float], q: float) -> float | None:
    if not latencies:
        return None
    lat = sorted(latencies)
    idx = min(len(lat) - 1, int(q * len(lat)))
    return round(lat[idx] * 1e3, 4)


def _result(mode: str, concurrency: int, col: _Collector,
            wall: float, **extra) -> dict:
    n = len(col.latencies)
    out = {
        "mode": mode,
        "concurrency": concurrency,
        "requests": n,
        "errors": col.errors,
        "duration_s": round(wall, 3),
        "qps": round(n / wall, 1) if wall > 0 else 0.0,
        "p50_ms": _percentile_ms(col.latencies, 0.50),
        "p99_ms": _percentile_ms(col.latencies, 0.99),
    }
    shed = len(col.shed_latencies)
    if shed:
        # Admitted-vs-shed reported separately (the percentiles above
        # cover ADMITTED traffic only); keys appear only when admission
        # control actually fired, so unshedded sweeps stay byte-stable.
        out["shed"] = shed
        out["shed_fraction"] = round(shed / max(1, shed + n), 4)
        out["shed_p50_ms"] = _percentile_ms(col.shed_latencies, 0.50)
    out.update(extra)
    return out


def run_closed_loop(
    host: str, port: int, body: bytes, *,
    concurrency: int, total_requests: int = 300,
    duration_s: float = 30.0, path: str = "/score",
    headers: dict | None = None,
) -> dict:
    """``concurrency`` keep-alive clients ping-ponging until
    ``total_requests`` ADMITTED requests land or ``duration_s`` elapses
    (whichever first — the wall budget keeps a wedged or persistently
    overloaded server from wedging the bench).

    A 429 from admission control is honored, not hammered: the client
    backs off for the server's ``Retry-After`` (plus a small client-side
    jitter so a shed herd de-synchronizes), re-credits the request
    quota, and retries — the well-behaved-client contract the shed
    shape exists for. Sheds are reported separately and never poison
    the admitted percentiles (:class:`_Collector`)."""
    import random

    col = _Collector()
    remaining = [max(1, int(total_requests))]
    quota_lock = threading.Lock()
    deadline = time.perf_counter() + duration_s

    def worker():
        client = _Client(host, port, path, headers=headers)
        try:
            while time.perf_counter() < deadline:
                with quota_lock:
                    if remaining[0] <= 0:
                        return
                    remaining[0] -= 1
                t0 = time.perf_counter()
                try:
                    status, _ = client.post(body)
                except Exception:  # noqa: BLE001 — transport tear = error
                    col.fail()
                    continue
                if status == 200:
                    col.ok(time.perf_counter() - t0)
                elif status == 429:
                    col.shed(time.perf_counter() - t0)
                    with quota_lock:
                        remaining[0] += 1  # the admitted quota is unmet
                    pause = (client.last_retry_after or 0.05) * (
                        1.0 + 0.1 * random.random()
                    )
                    time.sleep(
                        min(pause,
                            max(0.0, deadline - time.perf_counter()))
                    )
                else:
                    col.fail()
        finally:
            client.close()

    threads = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(max(1, int(concurrency)))
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 30.0)
    return _result("closed", concurrency, col, time.perf_counter() - t0)


def run_open_loop(
    host: str, port: int, body: bytes, *,
    qps: float, duration_s: float = 2.0, max_inflight: int = 64,
    path: str = "/score", headers: dict | None = None,
) -> dict:
    """Arrivals paced at ``qps`` for ``duration_s``; each request runs
    on a pooled keep-alive client. If the pool is saturated
    (``max_inflight``), the arrival counts as a drop (reported) rather
    than silently back-pressuring the clock — an open-loop generator
    that waits is a closed loop in disguise. A 429 counts as SHED
    offered load (separate from errors; open-loop arrivals do not
    retry — the next arrival is already scheduled)."""
    col = _Collector()
    dropped = [0]
    pool: list[_Client] = [
        _Client(host, port, path, headers=headers)
        for _ in range(max_inflight)
    ]
    free = list(range(max_inflight))
    free_lock = threading.Lock()
    live: list[threading.Thread] = []

    def fire(idx: int):
        t0 = time.perf_counter()
        try:
            status, _ = pool[idx].post(body)
            if status == 200:
                col.ok(time.perf_counter() - t0)
            elif status == 429:
                col.shed(time.perf_counter() - t0)
            else:
                col.fail()
        except Exception:  # noqa: BLE001
            col.fail()
        finally:
            with free_lock:
                free.append(idx)

    interval = 1.0 / max(qps, 1e-6)
    start = time.perf_counter()
    n_arrivals = max(1, int(qps * duration_s))
    for i in range(n_arrivals):
        target = start + i * interval
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        with free_lock:
            idx = free.pop() if free else None
        if idx is None:
            dropped[0] += 1
            continue
        t = threading.Thread(target=fire, args=(idx,), daemon=True)
        live.append(t)
        t.start()
    for t in live:
        t.join(30.0)
    wall = time.perf_counter() - start
    for c in pool:
        c.close()
    return _result(
        "open", max_inflight, col, wall,
        target_qps=qps, dropped=dropped[0],
    )


def saturation_knee(levels: list[dict],
                    min_gain: float = 1.2) -> dict:
    """The knee of a closed-loop sweep: the last concurrency level whose
    qps still improved by ``min_gain``x over the previous level. Beyond
    it, added concurrency buys queue depth, not throughput."""
    knee = levels[0]
    for prev, cur in zip(levels, levels[1:]):
        if prev["qps"] > 0 and cur["qps"] >= min_gain * prev["qps"]:
            knee = cur
        else:
            break
    peak = max(levels, key=lambda r: r["qps"])
    return {
        "knee_concurrency": knee["concurrency"],
        "knee_qps": knee["qps"],
        "saturated_qps": peak["qps"],
        "saturated_concurrency": peak["concurrency"],
    }


def sweep_closed_loop(
    host: str, port: int, body: bytes, *,
    levels: list[int], requests_per_level: int = 300,
    duration_s: float = 30.0,
) -> dict:
    """Closed-loop sweep over ``levels`` + knee analysis — the
    ``serving_load`` bench stanza's engine."""
    results = [
        run_closed_loop(
            host, port, body, concurrency=c,
            total_requests=requests_per_level, duration_s=duration_s,
        )
        for c in levels
    ]
    return {"levels": results, **saturation_knee(results)}


# ----------------------------------------------------------------------
# Synthetic fixture + selftest (the CI smoke; numpy + stdlib only).

def synthetic_mlp(seed: int = 0, input_dim: int = 5,
                  hidden: int = 64) -> tuple[dict, dict]:
    """A deterministic random MLP (weights, meta) pair shaped exactly
    like a deployed weather_mlp package — no training, no checkpoint."""
    rng = np.random.default_rng(seed)
    weights = {
        "w0": rng.standard_normal((input_dim, hidden)).astype(np.float32),
        "b0": rng.standard_normal(hidden).astype(np.float32) * 0.1,
        "w1": rng.standard_normal((hidden, 2)).astype(np.float32),
        "b1": rng.standard_normal(2).astype(np.float32) * 0.1,
    }
    meta = {
        "model": "weather_mlp", "input_dim": input_dim,
        "hidden_dim": hidden, "num_classes": 2,
        "feature_names": [f"f{i}_norm" for i in range(input_dim)],
    }
    return weights, meta


def _selftest(requests_per_level: int = 200,
              levels: tuple = (2, 8)) -> dict:
    """The serving-load CI smoke: a micro-batched server over a
    synthetic MLP must (1) answer a concurrency sweep with non-zero qps
    and zero errors, and (2) answer bit-identically to sequential
    single-row scoring while requests are being merged."""
    from dct_tpu.config import ServingConfig
    from dct_tpu.serving.runtime import score_payload
    from dct_tpu.serving.server import make_server_from_weights

    weights, meta = synthetic_mlp()
    serving = ServingConfig(
        max_batch=32, batch_window_ms=2.0, workers=2
    )
    server = make_server_from_weights(weights, meta, serving=serving)
    host, port = server.server_address[:2]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        # Parity leg: concurrent distinct payloads, each response
        # compared bitwise against the sequential single-row reference.
        rng = np.random.default_rng(7)
        rows = rng.standard_normal((32, meta["input_dim"])).astype(
            np.float32
        )
        expected = [
            np.asarray(
                score_payload(weights, meta, [row.tolist()])
                ["probabilities"],
                np.float32,
            )
            for row in rows
        ]
        got: list = [None] * len(rows)

        def one(i: int):
            client = _Client(host, port)
            try:
                status, body = client.post(
                    json.dumps({"data": [rows[i].tolist()]}).encode()
                )
                if status == 200:
                    got[i] = np.asarray(
                        json.loads(body)["probabilities"], np.float32
                    )
            finally:
                client.close()

        threads = [
            threading.Thread(target=one, args=(i,), daemon=True)
            for i in range(len(rows))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(30.0)
        parity = all(
            g is not None and g.shape == e.shape and (g == e).all()
            for g, e in zip(got, expected)
        )

        body = json.dumps({"data": [rows[0].tolist()]}).encode()
        sweep = sweep_closed_loop(
            host, port, body, levels=list(levels),
            requests_per_level=requests_per_level,
        )
        merged = server.batcher.flushes < (
            len(rows) + sum(r["requests"] for r in sweep["levels"])
        )
        ok = (
            parity
            and all(r["qps"] > 0 for r in sweep["levels"])
            and all(r["errors"] == 0 for r in sweep["levels"])
        )
        return {
            "ok": ok, "parity": parity, "batching_observed": merged,
            **sweep,
        }
    finally:
        server.shutdown()
        server.server_close()


def main(argv: list[str] | None = None) -> int:
    import argparse
    import urllib.parse

    from dct_tpu.config import ServingConfig

    cfg = ServingConfig.from_env()
    ap = argparse.ArgumentParser(
        description="dct_tpu serving load generator"
    )
    ap.add_argument("--url", help="server base URL (http://host:port)")
    ap.add_argument("--mode", choices=("closed", "open"), default=None,
                    help="default: open when --qps/DCT_SERVE_LOADGEN_QPS "
                         "> 0, else closed")
    ap.add_argument("--concurrency", type=int, default=None,
                    help="closed loop: comma levels come from "
                         "DCT_SERVE_LOADGEN_CONCURRENCY when unset")
    ap.add_argument("--requests", type=int, default=cfg.loadgen_requests)
    ap.add_argument("--duration", type=float,
                    default=cfg.loadgen_duration_s)
    ap.add_argument("--qps", type=float, default=cfg.loadgen_qps)
    ap.add_argument("--rows", type=int, default=1,
                    help="feature rows per request payload")
    ap.add_argument("--selftest", action="store_true",
                    help="hermetic smoke: synthetic model, in-process "
                         "server, parity + qps assertions")
    args = ap.parse_args(argv)

    if args.selftest:
        out = _selftest()
        print(json.dumps(out))
        return 0 if out["ok"] else 1

    if not args.url:
        ap.error("--url is required (or use --selftest)")
    parsed = urllib.parse.urlparse(args.url)
    host, port = parsed.hostname, parsed.port or 80
    rng = np.random.default_rng(0)
    body = json.dumps(
        {"data": rng.standard_normal((args.rows, 5)).round(4).tolist()}
    ).encode()

    mode = args.mode or ("open" if args.qps > 0 else "closed")
    if mode == "open":
        out = run_open_loop(
            host, port, body, qps=args.qps or 100.0,
            duration_s=args.duration,
        )
    elif args.concurrency:
        out = run_closed_loop(
            host, port, body, concurrency=args.concurrency,
            total_requests=args.requests, duration_s=args.duration,
        )
    else:
        out = sweep_closed_loop(
            host, port, body, levels=cfg.concurrency_levels(),
            requests_per_level=args.requests,
            duration_s=args.duration,
        )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
