"""Dependency-free inference runtime for every deployed model family.

The reference's generated ``score.py`` re-declares the torch model class and
loads a Lightning checkpoint inside the serving container
(dags/azure_manual_deploy.py:54-125), pulling torch+lightning into the
inference image and hardcoding ``input_dim=5`` (:109). Here the deploy
package carries the weights as a plain ``model.npz`` (+ JSON meta with the
true architecture/feature names from the checkpoint), and inference is pure
numpy — the serving container needs no ML framework at all, for ANY family:

- ``weather_mlp``        — sequential dense stack (w0/b0.. keys);
- ``weather_gru``        — stacked GRU over windows (flat flax-path keys);
- ``weather_transformer``— encoder over windows (flat flax-path keys).

:func:`score_payload` dispatches on ``meta["model"]`` and validates the
payload shape per family. This module is the single source of truth: the
score.py generator embeds its source verbatim so the deployed copy cannot
drift from the tested one.
"""

from __future__ import annotations

import functools
import math

import numpy as np


def softmax_numpy(logits: np.ndarray) -> np.ndarray:
    z = logits - logits.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def rows_mm(x: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Row-invariant 2D matmul: ``x[i] @ w`` computed as an independent
    ``[1, K] @ [K, M]`` product per row.

    A plain ``[N, K] @ [K, M]`` GEMM picks different BLAS kernels (and
    different FMA groupings) at different ``N``, so row ``i``'s bits can
    depend on how many OTHER rows share the call — which would make a
    micro-batched score depend on co-batched traffic. Batched matmul
    over a size-1 middle axis runs each row as its own ``[1, K]`` GEMM,
    bit-identical to scoring that row alone, at any stacking. The
    micro-batcher (serving/batching.py) threads this in via the ``mm``
    hooks below; the direct :func:`score_payload` path keeps the plain
    GEMM (``mm=np.matmul`` defaults — bits unchanged for existing
    consumers)."""
    return (x[:, None, :] @ w)[:, 0, :]


#: K-reduction block for the int8 integer GEMM: every partial product is
#: <= 127*127 and 1024 of them sum below 2**24, so each block's
#: accumulation is EXACT in float32 — no rounding for any BLAS kernel or
#: FMA grouping to disagree about.
_INT8_CHUNK = 1024


def bf16_pack(a: np.ndarray) -> np.ndarray:
    """float32 -> bf16 bit pattern (round-to-nearest-even) as uint16.

    Pure-numpy twin of ``jnp.asarray(a, jnp.bfloat16)``'s rounding:
    halves the stored bytes; :func:`bf16_unpack` widens back exactly."""
    u = np.ascontiguousarray(a, np.float32).view(np.uint32)
    return ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(np.uint16)


def bf16_unpack(u: np.ndarray) -> np.ndarray:
    """bf16 bit pattern (uint16) -> float32 (exact widening)."""
    return (
        np.ascontiguousarray(u, np.uint16).astype(np.uint32) << 16
    ).view(np.float32)


class QuantTensor:
    """An int8 weight matrix with per-output-channel symmetric scales,
    dequantized INSIDE the matmul.

    ``np.matmul(x, qt)`` (and the ``@`` operator — numpy routes both
    through ``__array_ufunc__``) quantizes the activation rows
    dynamically (symmetric int8, one scale per row), runs the GEMM as a
    float32-carried INTEGER product, and rescales by
    ``row_scale * channel_scale``. Because every intermediate value of
    the integer reduction is an integer below 2**24 (the K axis is
    chunked to ``_INT8_CHUNK`` columns), the float32 accumulation is
    exact — the result is bit-identical under any BLAS kernel, batch
    size, or row stacking. That restores the micro-batcher's
    row-invariance contract through ONE plain GEMM, where the f32 twin
    must fall back to the per-row ``rows_mm`` path: quantization here
    buys speed precisely by making the fast path exact.

    Only 2D matmul kernels are packed this way (serving/quant.py);
    biases, layernorm affines, and stacked 3D+ trees stay f32, so every
    other op in the forward pass is untouched.
    """

    __slots__ = ("q", "scale", "qf")

    #: Logical dtype: the tensor stands in for a float32 weight matrix.
    dtype = np.dtype(np.float32)

    def __init__(self, q: np.ndarray, scale: np.ndarray):
        self.q = np.ascontiguousarray(q, np.int8)
        self.scale = np.ascontiguousarray(scale, np.float32)
        # float32 carrier of the int8 entries: cast once at load — the
        # GEMM consumes it directly on every call.
        self.qf = self.q.astype(np.float32)

    @property
    def shape(self) -> tuple:
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim

    def dequantize(self) -> np.ndarray:
        """Dense f32 reconstruction (jax-engine and debugging path)."""
        return self.qf * self.scale[None, :]

    def matmul(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        lead, k = x.shape[:-1], x.shape[-1]
        x2 = x.reshape(-1, k)
        amax = np.abs(x2).max(axis=1) if x2.size else np.zeros(
            x2.shape[0], np.float32
        )
        sx = (amax / np.float32(127.0)).astype(np.float32)
        inv = np.where(sx > 0, np.float32(1.0) / np.where(sx > 0, sx, 1), 0)
        xq = x2 * inv[:, None].astype(np.float32)
        np.rint(xq, out=xq)
        np.clip(xq, -127.0, 127.0, out=xq)
        acc = None
        for c in range(0, k, _INT8_CHUNK):
            part = xq[:, c:c + _INT8_CHUNK] @ self.qf[c:c + _INT8_CHUNK]
            # Fixed-order elementwise adds between exact integer blocks:
            # still deterministic and row-independent.
            acc = part if acc is None else acc + part
        acc *= sx[:, None]
        acc *= self.scale
        return acc.reshape(*lead, self.q.shape[1])

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if (
            ufunc is np.matmul and method == "__call__"
            and len(inputs) == 2 and inputs[1] is self and not kwargs
        ):
            return self.matmul(inputs[0])
        return NotImplemented

    def __rmatmul__(self, x):
        return self.matmul(x)


def assemble_weights(flat: dict) -> dict:
    """Reconstitute serving weights from a flat npz-style mapping.

    Quantized packages (serving/quant.py) store ``k::q8`` (int8) +
    ``k::scale`` (f32 per output channel) pairs and ``k::bf16`` (uint16
    bf16 bit patterns); a plain f32 package passes through unchanged.
    The ``::`` separator cannot collide with flax ``/`` paths. Returns
    the original keys mapped to f32 arrays or :class:`QuantTensor`s —
    every forward above consumes either transparently."""
    out: dict = {}
    for k, v in flat.items():
        if k.endswith("::q8"):
            out[k[:-4]] = QuantTensor(v, flat[k[:-4] + "::scale"])
        elif k.endswith("::scale"):
            continue
        elif k.endswith("::bf16"):
            out[k[:-6]] = bf16_unpack(v)
        else:
            out[k] = v
    return out


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def _gelu_tanh(x: np.ndarray) -> np.ndarray:
    # jax.nn.gelu default (approximate=True): the tanh approximation.
    return 0.5 * x * (
        1.0 + np.tanh(np.sqrt(2.0 / np.pi) * (x + 0.044715 * x**3))
    )


def _layernorm(x: np.ndarray, scale: np.ndarray, bias: np.ndarray,
               eps: float = 1e-6) -> np.ndarray:
    mu = x.mean(axis=-1, keepdims=True)
    var = x.var(axis=-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * scale + bias


def _sincos_positions(seq_len: int, d_model: int) -> np.ndarray:
    # Mirrors dct_tpu.models.transformer.sincos_positions.
    pos = np.arange(seq_len)[:, None].astype(np.float32)
    i = np.arange(d_model // 2)[None, :].astype(np.float32)
    ang = pos / np.power(10000.0, 2.0 * i / d_model)
    out = np.zeros((seq_len, d_model), np.float32)
    out[:, 0::2] = np.sin(ang)
    out[:, 1::2] = np.cos(ang)
    return out


def mlp_forward_numpy(weights: dict, x: np.ndarray,
                      mm=np.matmul) -> np.ndarray:
    """Forward pass of a sequential dense stack (dropout is inference-off).

    weights keys: w0/b0 .. wN/bN, exported from the flax checkpoint by the
    packager; ReLU between layers, raw logits at the last. ``mm`` is the
    2D-matmul hook (:func:`rows_mm` makes the pass row-invariant for the
    micro-batcher; the default keeps the plain GEMM).
    """
    n_layers = sum(1 for k in weights if k.startswith("w"))
    h = x
    for i in range(n_layers):
        h = mm(h, weights[f"w{i}"]) + weights[f"b{i}"]
        if i < n_layers - 1:
            h = np.maximum(h, 0.0)
    return h


def gru_forward_numpy(weights: dict, meta: dict, x: np.ndarray,
                      mm=np.matmul) -> np.ndarray:
    """Stacked GRU inference; weights carry flax paths
    (``gru_<i>/x_gates/kernel`` etc., gate order r,z,n — torch semantics:
    reset gate applied to the full hidden pre-activation). ``mm`` hooks
    the 2D matmuls (recurrence + head) — the x-gate product is a 3D
    stacked matmul and is per-window-invariant already."""
    n_layers = int(meta["n_layers"])
    h_seq = x
    h = None
    for i in range(n_layers):
        xg = h_seq @ weights[f"gru_{i}/x_gates/kernel"] + weights[
            f"gru_{i}/x_gates/bias"
        ]  # [N, S, 3H]
        wh = weights[f"gru_{i}/h_kernel"]
        bh = weights[f"gru_{i}/h_bias"]
        h = np.zeros((x.shape[0], wh.shape[0]), np.float32)
        # Only the last layer's final state feeds the head; intermediate
        # layers need the full output sequence as the next layer's input.
        keep_seq = i < n_layers - 1
        outs = []
        for t in range(xg.shape[1]):
            hg = mm(h, wh) + bh
            xr, xz, xn = np.split(xg[:, t], 3, axis=-1)
            hr, hz, hn = np.split(hg, 3, axis=-1)
            r = _sigmoid(xr + hr)
            z = _sigmoid(xz + hz)
            n = np.tanh(xn + r * hn)
            h = (1.0 - z) * n + z * h
            if keep_seq:
                outs.append(h)
        if keep_seq:
            h_seq = np.stack(outs, axis=1)
    return mm(h, weights["head/kernel"]) + weights["head/bias"]


@functools.lru_cache(maxsize=8)
def _rope_tables_np(s: int, half: int) -> tuple:
    """Cached [S, Dh/2] cos/sin tables: a served L-layer transformer
    would otherwise rebuild identical trig tables 2L times per request."""
    inv = 1.0 / np.power(
        10000.0, np.arange(half, dtype=np.float32) / half
    )
    ang = np.arange(s, dtype=np.float32)[:, None] * inv[None, :]
    return np.cos(ang), np.sin(ang)


def _rope_numpy(x: np.ndarray) -> np.ndarray:
    """Rotate q/k [N, H, S, Dh] — numpy twin of
    dct_tpu.models.transformer.apply_rope (rotate-half pairing)."""
    half = x.shape[-1] // 2
    cos, sin = _rope_tables_np(x.shape[-2], half)
    x1, x2 = x[..., :half], x[..., half:]
    return np.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )


def _mha_numpy(weights: dict, prefix: str, h: np.ndarray,
               n_heads: int, causal: bool = False,
               window: int | None = None,
               n_kv_heads: int | None = None,
               rope: bool = False) -> np.ndarray:
    """Multi-head attention matching
    dct_tpu.models.transformer.MultiHeadAttention's fused-qkv layout
    (``causal`` masks positions > query, the causal family's path;
    ``window`` adds the sliding-window band; ``n_kv_heads`` selects the
    GQA group-major layout; ``rope`` rotates q/k — each must mirror
    training or the served model silently differs from the trained
    one)."""
    n, s, d_model = h.shape
    head_dim = d_model // n_heads
    g = n_kv_heads or n_heads
    hg = n_heads // g
    qkv = h @ weights[f"{prefix}/qkv_proj/kernel"] + weights[
        f"{prefix}/qkv_proj/bias"
    ]
    qkv = qkv.reshape(n, s, g, hg + 2, head_dim)
    q = np.swapaxes(
        qkv[:, :, :, :hg].reshape(n, s, n_heads, head_dim), 1, 2
    )  # [N, H, S, Dh]
    k = np.swapaxes(qkv[:, :, :, hg], 1, 2)  # [N, G, S, Dh]
    v = np.swapaxes(qkv[:, :, :, hg + 1], 1, 2)
    if rope:
        q = _rope_numpy(q)
        k = _rope_numpy(k)
    if hg > 1:
        k = np.repeat(k, hg, axis=1)
        v = np.repeat(v, hg, axis=1)
    scores = q @ np.swapaxes(k, -1, -2) / math.sqrt(head_dim)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        if window is not None:
            pos = np.arange(s)
            mask &= pos[:, None] - pos[None, :] < window
        scores = np.where(mask, scores, -1e30)
    o = softmax_numpy(scores) @ v  # [N, H, S, Dh]
    o = np.moveaxis(o, 1, 2).reshape(n, s, d_model)
    return o @ weights[f"{prefix}/o_proj/kernel"] + weights[
        f"{prefix}/o_proj/bias"
    ]


def _dense_ffn_numpy(w: dict, pre: str, f: np.ndarray) -> np.ndarray:
    f = _gelu_tanh(f @ w[f"{pre}/ffn_in/kernel"] + w[f"{pre}/ffn_in/bias"])
    return f @ w[f"{pre}/ffn_out/kernel"] + w[f"{pre}/ffn_out/bias"]


def _pre_ln_block(w: dict, pre: str, h: np.ndarray, n_heads: int, ffn,
                  causal: bool = False, window: int | None = None,
                  n_kv_heads: int | None = None,
                  rope: bool = False) -> np.ndarray:
    """One pre-LN residual block (attention + FFN) — the single source of
    the block math for the transformer, MoE, causal, AND pipeline-stage
    serving paths (train/serve parity lives or dies here)."""
    a = _layernorm(h, w[f"{pre}/ln_attn/scale"], w[f"{pre}/ln_attn/bias"])
    h = h + _mha_numpy(
        w, f"{pre}/attn", a, n_heads, causal, window, n_kv_heads, rope
    )
    f = _layernorm(h, w[f"{pre}/ln_ffn/scale"], w[f"{pre}/ln_ffn/bias"])
    return h + ffn(w, pre, f)


def _head_numpy(weights: dict, h: np.ndarray,
                per_position: bool, horizon: int = 1,
                mm=np.matmul) -> np.ndarray:
    h = _layernorm(h, weights["ln_out/scale"], weights["ln_out/bias"])
    pooled = h[:, -1, :] if per_position else h.mean(axis=1)
    out = mm(pooled, weights["head/kernel"]) + weights["head/bias"]
    if per_position and horizon > 1:
        # Multi-horizon causal head: [B, H*C] -> [B, H, C] — forecasts
        # for steps t+1..t+H from the window's last position.
        return out.reshape(out.shape[0], horizon, -1)
    return out


def _encoder_numpy(weights: dict, meta: dict, x: np.ndarray, ffn, *,
                   causal: bool = False,
                   per_position: bool = False,
                   mm=np.matmul) -> np.ndarray:
    """Shared pre-LN encoder skeleton (in_proj + positions, per-block
    attention and FFN residuals, final LN + mean-pool + head). ``ffn`` is
    ``(weights, block_prefix, h) -> h_ffn`` — the only point where the
    transformer and MoE families differ. The causal family sets both
    flags; ``per_position`` serves the LAST position's logits (the
    next-step forecast for the window)."""
    d_model = int(meta["d_model"])
    n_heads = int(meta["n_heads"])
    n_layers = int(meta["n_layers"])
    # Same config normalization as the registry: <= 0 = off for both
    # (truthiness alone would turn a -1 sentinel into an all-masked band
    # the trained model never had).
    _w = int(meta.get("attn_window", 0) or 0)
    window = _w if _w > 0 and causal else None
    _g = int(meta.get("n_kv_heads", 0) or 0)
    n_kv = _g if _g > 0 else None
    rope = str(meta.get("pos_embed", "sincos")) == "rope"
    s = x.shape[1]

    h = x @ weights["in_proj/kernel"] + weights["in_proj/bias"]
    if not rope:  # rope rotates q/k inside attention instead
        h = h + _sincos_positions(s, d_model)
    for i in range(n_layers):
        h = _pre_ln_block(
            weights, f"block_{i}", h, n_heads, ffn, causal, window, n_kv,
            rope,
        )
    return _head_numpy(
        weights, h, per_position, horizon=int(meta.get("horizon", 1)),
        mm=mm,
    )


def transformer_forward_numpy(
    weights: dict, meta: dict, x: np.ndarray, *, causal: bool = False,
    mm=np.matmul,
) -> np.ndarray:
    """Pre-LN encoder inference; weights carry flax paths
    (``block_<i>/attn/qkv_proj/kernel`` etc.). ``causal`` serves the
    decoder-style causal family (per-position head, last position out).
    Every block matmul is a 3D/4D stacked product (per-window-invariant
    by construction); ``mm`` hooks the one 2D site, the pooled head."""

    return _encoder_numpy(
        weights, meta, x, _dense_ffn_numpy, causal=causal,
        per_position=causal, mm=mm,
    )


def transformer_pp_forward_numpy(
    weights: dict, meta: dict, x: np.ndarray, mm=np.matmul
) -> np.ndarray:
    """Pipeline-parallel transformer inference: the ``pp_stages`` param is
    a stacked tree (leading dim = stage,
    dct_tpu.models.transformer.WeatherTransformerPP); serving just
    unstacks it and applies the stages sequentially — pipelining is a
    training-time throughput construct, numerically the sequential stack."""
    d_model = int(meta["d_model"])
    n_heads = int(meta["n_heads"])
    n_layers = int(meta["n_layers"])
    n_stages = int(meta["n_stages"])
    layers_per_stage = n_layers // n_stages
    s = x.shape[1]

    rope = str(meta.get("pos_embed", "sincos")) == "rope"
    h = x @ weights["in_proj/kernel"] + weights["in_proj/bias"]
    if not rope:
        h = h + _sincos_positions(s, d_model)
    stage_keys = {
        k[len("pp_stages/"):]: v
        for k, v in weights.items()
        if k.startswith("pp_stages/")
    }
    _g = int(meta.get("n_kv_heads", 0) or 0)
    n_kv = _g if _g > 0 else None
    for st in range(n_stages):
        w = {k: v[st] for k, v in stage_keys.items()}
        for i in range(layers_per_stage):
            h = _pre_ln_block(
                w, f"block_{i}", h, n_heads, _dense_ffn_numpy,
                n_kv_heads=n_kv, rope=rope,
            )
    return _head_numpy(weights, h, per_position=False, mm=mm)


def _moe_ffn_numpy(weights: dict, prefix: str, h: np.ndarray,
                   capacity_factor: float, top_k: int = 1) -> np.ndarray:
    """MoE inference matching dct_tpu.models.moe.MoEFFN: same routing
    (switch top-1 or GShard top-k with normalized gates), capacity, and
    choice-major arrival-order drop semantics as training."""
    b, s, d = h.shape
    n = b * s
    tokens = h.reshape(n, d)
    logits = tokens @ weights[f"{prefix}/router/kernel"] + weights[
        f"{prefix}/router/bias"
    ]
    probs = softmax_numpy(logits)
    e = probs.shape[-1]
    capacity = max(1, int(capacity_factor * top_k * n / e))
    if top_k == 1:
        expert_choice = np.argmax(probs, axis=-1)[None, :]
        gate_choice = np.max(probs, axis=-1)[None, :]
    else:
        topi = np.argsort(-probs, axis=-1)[:, :top_k]  # [N, k] best-first
        topv = np.take_along_axis(probs, topi, axis=-1)
        gates = topv / np.maximum(topv.sum(axis=-1, keepdims=True), 1e-9)
        expert_choice = topi.T
        gate_choice = gates.T
    flat_idx = expert_choice.reshape(top_k * n)
    flat_gate = gate_choice.reshape(top_k * n)

    out2 = np.zeros((top_k * n, d), tokens.dtype)
    w_in = weights[f"{prefix}/experts_in_kernel"]
    b_in = weights[f"{prefix}/experts_in_bias"]
    w_out = weights[f"{prefix}/experts_out_kernel"]
    b_out = weights[f"{prefix}/experts_out_bias"]
    for ex in range(e):
        ids = np.nonzero(flat_idx == ex)[0][:capacity]  # choice-major order
        if ids.size == 0:
            continue
        t = tokens[ids % n]
        a = _gelu_tanh(t @ w_in[ex] + b_in[ex])
        out2[ids] = (a @ w_out[ex] + b_out[ex]) * flat_gate[ids, None]
    return out2.reshape(top_k, n, d).sum(axis=0).reshape(b, s, d)


def moe_forward_numpy(weights: dict, meta: dict, x: np.ndarray) -> np.ndarray:
    """MoE encoder inference (same skeleton as the transformer, with the
    dense FFN replaced by the switch-routed expert mixture)."""
    capacity_factor = float(meta.get("capacity_factor", 1.25))
    top_k = int(meta.get("router_top_k", 1))

    def moe_ffn(w, pre, f):
        return _moe_ffn_numpy(w, f"{pre}/moe", f, capacity_factor, top_k)

    return _encoder_numpy(weights, meta, x, moe_ffn)


def forward_numpy(weights: dict, meta: dict, x: np.ndarray,
                  mm=np.matmul) -> np.ndarray:
    """Dispatch inference on the checkpoint's model family.

    ``mm`` is the 2D-matmul hook (:func:`rows_mm` = row-invariant bits
    for the micro-batcher). The MoE family ignores it: its routing
    capacity depends on the total token count, so batch-invariance there
    is the batcher's job (it scores MoE requests as separate segments,
    serving/batching.py)."""
    family = meta.get("model", "weather_mlp")
    if family == "weather_gru":
        return gru_forward_numpy(weights, meta, x, mm=mm)
    if family == "weather_transformer":
        return transformer_forward_numpy(weights, meta, x, mm=mm)
    if family == "weather_transformer_causal":
        return transformer_forward_numpy(weights, meta, x, causal=True, mm=mm)
    if family == "weather_transformer_pp":
        return transformer_pp_forward_numpy(weights, meta, x, mm=mm)
    if family == "weather_moe":
        return moe_forward_numpy(weights, meta, x)
    return mlp_forward_numpy(weights, x, mm=mm)


_SEQUENCE_FAMILIES = (
    "weather_gru", "weather_transformer", "weather_transformer_causal",
    "weather_transformer_pp", "weather_moe",
)


def validate_payload(meta: dict, data) -> np.ndarray:
    """Client-input validation: payload -> float32 batch array.

    Raises ValueError for anything that is the REQUEST's fault (ragged or
    non-numeric rows, wrong shape, non-finite values after float32
    conversion) — callers can map exactly this to an HTTP 400 while
    treating any later forward-pass failure as a server defect."""
    # A huge JSON number overflowing the float32 cast is the REQUEST's
    # fault, reported below as a clean 400 via the finiteness check —
    # numpy's "overflow encountered in cast" RuntimeWarning would only
    # leak noise into the server log for a condition already handled.
    with np.errstate(over="ignore", invalid="ignore"):
        x = np.asarray(data, dtype=np.float32)
    expected = int(meta["input_dim"])
    family = meta.get("model", "weather_mlp")
    if family in _SEQUENCE_FAMILIES:
        seq_len = int(meta["seq_len"])
        if x.ndim == 2:
            x = x[None, :, :]
        if x.ndim != 3 or x.shape[1] != seq_len or x.shape[2] != expected:
            raise ValueError(
                f"Expected shape [N, {seq_len}, {expected}] (windows of "
                f"features: {meta.get('feature_names', '?')}), got "
                f"{list(x.shape)}"
            )
    else:
        if x.ndim == 1:
            x = x[None, :]
        if x.ndim != 2 or x.shape[1] != expected:
            raise ValueError(
                f"Expected shape [N, {expected}] (features: "
                f"{meta.get('feature_names', '?')}), got {list(x.shape)}"
            )
    if not np.isfinite(x).all():
        # Includes float32 overflow of huge JSON numbers: softmax of an
        # inf logit is NaN, which is not valid strict JSON.
        raise ValueError(
            "features must be finite after float32 conversion"
        )
    return x


#: Exact JSON number grammar (one token, then comma-separated): the
#: fast path accepts PRECISELY what json.loads would, so it can never
#: answer 200 to a payload the contract path would 400 (no leading
#: zeros/plus signs, no bare trailing dots, no NaN/Infinity literals).
_JSON_NUM = rb"-?(?:0|[1-9][0-9]*)(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?"
_NUM_LIST_RE = None  # compiled lazily (module import stays regex-free)

#: Whitespace BETWEEN two number-grammar bytes means the global strip
#: below would splice tokens together ("[1 2]" -> "12" — invalid JSON
#: scored as the wrong number). Whitespace next to punctuation
#: (pretty-printed arrays) never matches.
_WS_SPLICE_RE = None


def parse_envelope_array(body: bytes) -> np.ndarray | None:
    """Zero-copy(-ish) fast path for the ``{"data": [...]}`` envelope:
    raw request bytes -> float32 ndarray without materializing the
    nested Python lists (and millions of boxed floats) ``json.loads``
    would build. The numeric text is parsed C-side in one pass
    (``np.fromstring`` text mode) after the bracket structure is
    verified rectangular and every token is matched against the exact
    JSON number grammar (one C-side regex pass — a malformed token like
    ``4.5.6`` must fall back, not half-parse).

    Returns ``None`` for anything that is not a strictly rectangular
    JSON-numeric envelope (ragged rows, strings, objects, nesting
    deeper than 3, extra top-level keys, non-JSON numerics) — the
    caller then falls back to the ``json.loads`` path, whose error
    reporting stays the contract. Overflow to ``inf`` is still rejected
    downstream by :func:`validate_payload`."""
    import re

    global _NUM_LIST_RE, _WS_SPLICE_RE
    if _NUM_LIST_RE is None:
        _NUM_LIST_RE = re.compile(
            _JSON_NUM + rb"(?:," + _JSON_NUM + rb")*"
        )
        _WS_SPLICE_RE = re.compile(rb"[0-9.eE+-][ \t\r\n]+[0-9.eE+-]")
    if _WS_SPLICE_RE.search(body):
        return None
    s = body.translate(None, b" \t\r\n")
    if not (s.startswith(b'{"data":[') and s.endswith(b']}')):
        return None
    arr = s[8:-1]
    depth = 0
    for c in arr:
        if c != 0x5B:  # ord('[')
            break
        depth += 1
    if not 1 <= depth <= 3 or arr.count(b"[") != arr.count(b"]"):
        return None
    flat_txt = arr.translate(None, b"[]")
    # Every token must be an exact JSON number (comma-separated): this
    # one pass rejects strings/objects/true/null AND malformed numerics
    # np.fromstring would silently half-parse ("4.5.6" -> 4.5).
    if not flat_txt or _NUM_LIST_RE.fullmatch(flat_txt) is None:
        return None

    # Rectangularity: every row at every level must agree in length —
    # the flat parse below cannot see brackets, so shape is proven here
    # (splitting on the row separators costs O(rows) small bytes
    # objects, never a Python float).
    if not (arr.startswith(b"[" * depth) and arr.endswith(b"]" * depth)):
        return None
    if depth == 1:
        if arr.count(b"[") != 1:  # e.g. [3,[1,2]] — not a flat vector
            return None
        shape: tuple = (flat_txt.count(b",") + 1,)
    elif depth == 2:
        rows = arr[2:-2].split(b"],[")
        width = rows[0].count(b",") + 1
        if any(
            b"[" in r or b"]" in r or not r or r.count(b",") + 1 != width
            for r in rows
        ):
            return None
        shape = (len(rows), width)
    else:
        outer = arr[3:-3].split(b"]],[[")
        seq = feat = None
        for win in outer:
            rows = win.split(b"],[")
            if seq is None:
                seq = len(rows)
                feat = rows[0].count(b",") + 1
            if len(rows) != seq or any(
                b"[" in r or b"]" in r or not r
                or r.count(b",") + 1 != feat
                for r in rows
            ):
                return None
        shape = (len(outer), seq, feat)

    expected = 1
    for d in shape:
        expected *= d
    parser = getattr(np, "fromstring", None)
    if parser is None:  # a future numpy without text-mode fromstring:
        return None  # the json.loads path is always correct, just slower
    try:
        with np.errstate(over="ignore", invalid="ignore"):
            flat = parser(
                flat_txt.decode("ascii"), dtype=np.float32, sep=","
            )
    except (ValueError, DeprecationWarning, UnicodeDecodeError):
        return None
    if flat.size != expected:
        # A token fromstring could not parse truncates the output — the
        # count check catches it and the json path reports it properly.
        return None
    return flat.reshape(shape)


def score_payload(weights: dict, meta: dict, data) -> dict:
    """The run()-body: validate + forward + softmax.

    Mirrors the reference's response contract
    (dags/azure_manual_deploy.py:116-124): {"probabilities": [[...], ...]}.
    Row families take {"data": [[feature vector], ...]}; sequence families
    take {"data": [[[row x seq_len] window], ...]} (one window may be passed
    un-batched).
    """
    x = validate_payload(meta, data)
    probs = softmax_numpy(forward_numpy(weights, meta, x))
    return {"probabilities": probs.tolist()}
