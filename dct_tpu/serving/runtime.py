"""Dependency-free inference runtime for the deployed model.

The reference's generated ``score.py`` re-declares the torch model class and
loads a Lightning checkpoint inside the serving container
(dags/azure_manual_deploy.py:54-125), pulling torch+lightning into the
inference image and hardcoding ``input_dim=5`` (:109). Here the deploy
package carries the weights as a plain ``model.npz`` (+ JSON meta with the
true input_dim/feature names from the checkpoint), and inference is pure
numpy — the serving container needs no ML framework at all. These functions
are the single source of truth; the score.py generator embeds this module's
source verbatim so the deployed copy cannot drift from the tested one.
"""

from __future__ import annotations

import numpy as np


def softmax_numpy(logits: np.ndarray) -> np.ndarray:
    z = logits - logits.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def mlp_forward_numpy(weights: dict, x: np.ndarray) -> np.ndarray:
    """Forward pass of a sequential dense stack (dropout is inference-off).

    weights keys: w0/b0 .. wN/bN, exported from the flax checkpoint by the
    packager; ReLU between layers, raw logits at the last.
    """
    n_layers = sum(1 for k in weights if k.startswith("w"))
    h = x
    for i in range(n_layers):
        h = h @ weights[f"w{i}"] + weights[f"b{i}"]
        if i < n_layers - 1:
            h = np.maximum(h, 0.0)
    return h


def score_payload(weights: dict, meta: dict, data) -> dict:
    """The run()-body: validate + forward + softmax.

    Mirrors the reference's response contract
    (dags/azure_manual_deploy.py:116-124): {"probabilities": [[...], ...]}.
    Input: {"data": [[feature vector], ...]}.
    """
    x = np.asarray(data, dtype=np.float32)
    if x.ndim == 1:
        x = x[None, :]
    expected = int(meta["input_dim"])
    if x.ndim != 2 or x.shape[1] != expected:
        raise ValueError(
            f"Expected shape [N, {expected}] (features: "
            f"{meta.get('feature_names', '?')}), got {list(x.shape)}"
        )
    probs = softmax_numpy(mlp_forward_numpy(weights, x))
    return {"probabilities": probs.tolist()}
