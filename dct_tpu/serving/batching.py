"""Dynamic micro-batching: decouple request arrival from compute dispatch.

The serving tier's throughput story (ROADMAP item 1): the endpoint's
HTTP front end handles each connection on its own thread, but scoring
one request at a time pays the full Python/numpy per-call overhead —
~a hundred small ops per transformer forward — once PER REQUEST. This
module batches instead: handler threads validate and enqueue; a small
pool of scoring workers drains the queue, merging compatible in-flight
requests (same weights object, same family, same row shape) into ONE
stacked forward of up to ``DCT_SERVE_MAX_BATCH`` rows, waiting up to
``DCT_SERVE_BATCH_WINDOW_MS`` from the oldest queued request for
co-arrivals before flushing. That is the Podracer decoupling applied to
the scorer: arrival concurrency fills batches, batches amortize
dispatch, and the compute path stays saturated instead of thrashing
per-request.

**Bit-identity contract** (the property tests/test_serving_batching.py
pins): a request's probabilities NEVER depend on what other traffic it
was batched with. Two mechanisms:

- Row/window-independent families (MLP, GRU, the transformer variants)
  are scored through :func:`dct_tpu.serving.runtime.forward_numpy` with
  the ``rows_mm`` matmul hook — every 2D GEMM runs each row as its own
  ``[1, K]`` product, so row ``i`` of a merged batch is bit-identical
  to scoring that row alone (plain GEMMs pick different BLAS kernels at
  different batch sizes; see ``rows_mm``'s docstring).
- The MoE family's routing capacity is a function of the TOTAL token
  count, so cross-request merging would change which tokens get
  dropped. MoE requests are therefore scored as per-request segments
  inside the flush (bit-identical to the request scored alone); the
  batch still amortizes queueing and dispatch overhead.

An optional jitted scorer (``DCT_SERVE_ENGINE=jax``) replaces the numpy
flush with a registry-model ``jax.jit`` forward — the throughput choice
for the transformer/MoE families on accelerator rigs. It matches the
numpy twin to ~2e-6 (the evaluation harness's proven engine-parity
band) but trades the bitwise guarantee; the default engine keeps it.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from dct_tpu.serving.runtime import forward_numpy, rows_mm, softmax_numpy


class ScoringError(RuntimeError):
    """A server-fault scoring failure (maps to HTTP 500 — the request
    already passed validation, so whatever broke is ours)."""


def score_rows_invariant(weights: dict, meta: dict,
                         arrays: list) -> list:
    """Score several validated requests as one flush; returns one
    ``[N_i, ...]`` probability array per request, bit-identical to each
    request scored alone (module docstring). ``arrays`` must share one
    trailing shape (the batch key guarantees it)."""
    family = meta.get("model", "weather_mlp")
    if family == "weather_moe":
        # Token-count-dependent routing capacity: merging requests would
        # change drop semantics. Segment per request — same bits as the
        # request scored alone through score_payload.
        return [
            softmax_numpy(forward_numpy(weights, meta, a)) for a in arrays
        ]
    stacked = (
        np.concatenate(arrays, axis=0) if len(arrays) > 1 else arrays[0]
    )
    probs = softmax_numpy(forward_numpy(weights, meta, stacked, mm=rows_mm))
    out = []
    start = 0
    for a in arrays:
        out.append(probs[start:start + len(a)])
        start += len(a)
    return out


def _digest_view(weights: dict) -> dict:
    """Flat ndarray view of a serving weights dict for content hashing:
    QuantTensor leaves expand to their q8/scale planes (the npz key
    grammar), everything else passes through."""
    from dct_tpu.serving.runtime import QuantTensor

    out: dict = {}
    for k, v in weights.items():
        if isinstance(v, QuantTensor):
            out[f"{k}::q8"] = v.q
            out[f"{k}::scale"] = v.scale
        else:
            out[k] = v
    return out


def _build_jax_scorer(weights: dict, meta: dict, force_store: bool = False):
    """Jitted batched scorer: registry model rebuilt from the package's
    self-describing meta (the evaluation harness's jax-engine idiom),
    returning the SERVING contract's probability shape (multi-horizon
    causal heads keep ``[N, H, C]``). Batches are padded to the next
    power of two so jit recompiles O(log max_batch) times, not per
    distinct arrival pattern.

    When the package loader stamped an ``_aot_dir`` into ``meta`` and
    the compile cache is armed (``DCT_COMPILE_CACHE``; or
    ``force_store=True`` — the packaging-time warm-up), the forward
    fronts an AOT executable store over ``<package>/aot/``: a deployed
    package carries its pre-compiled scorer, so a fresh endpoint
    worker's first score deserializes instead of compiling. Identity =
    (family, hash of the package meta, local layout); a skewed artifact
    is a loud miss back onto the jit path."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dct_tpu.config import ModelConfig
    from dct_tpu.evaluation.harness import _unflatten_weights
    from dct_tpu.models.registry import get_model, is_causal_model

    from dct_tpu.serving.runtime import QuantTensor

    family = meta.get("model", "weather_mlp")
    fields = {f.name for f in dataclasses.fields(ModelConfig)}
    cfg = ModelConfig(name=family, **{
        k: v for k, v in meta.items() if k in fields and k != "name"
    })
    qdtype = (meta.get("quant") or {}).get("dtype")
    model = get_model(
        cfg, input_dim=int(meta["input_dim"]),
        compute_dtype=jnp.bfloat16 if qdtype == "bf16" else jnp.float32,
    )
    # Low-precision residency (docs/SERVING.md §quantized scorers): the
    # int8 variant keeps q8 + per-channel scales resident (a quarter of
    # the f32 weight bytes) and dequantizes INSIDE the jitted forward;
    # the bf16 variant keeps params resident as bf16 (the package's
    # widened-f32 values are bf16-exact, so this cast is lossless) and
    # runs the model at bf16 compute. A plain f32 package takes neither
    # branch — bits unchanged.
    flat_plain: dict = {}
    flat_q: dict = {}
    for k, v in weights.items():
        if isinstance(v, QuantTensor):
            flat_q[k] = (jnp.asarray(v.q), jnp.asarray(v.scale))
        elif qdtype == "bf16" and np.issubdtype(
            np.asarray(v).dtype, np.floating
        ):
            flat_plain[k] = jnp.asarray(v, jnp.bfloat16)
        else:
            flat_plain[k] = jnp.asarray(v)

    def _materialize_params():
        flat = dict(flat_plain)
        for k, (q, s) in flat_q.items():
            flat[k] = q.astype(jnp.float32) * s
        return _unflatten_weights(flat, family)

    causal = is_causal_model(family)
    horizon = int(meta.get("horizon", 1))
    moe = family == "weather_moe"

    @jax.jit
    def forward(xb):
        logits = model.apply({"params": _materialize_params()}, xb,
                             train=False)
        logits = logits.astype(jnp.float32)
        if causal:
            # Per-position head: [B, S, C] (horizon 1) or [B, S, H, C];
            # serving answers for the window's LAST position, keeping
            # the multi-horizon axis ([B, H, C]) like the numpy twin.
            logits = logits[:, -1]
            if horizon > 1 and logits.ndim == 2:
                logits = logits.reshape(logits.shape[0], horizon, -1)
        return jax.nn.softmax(logits, axis=-1)

    from dct_tpu import compilecache as _cc
    from dct_tpu.compilecache.aot import weights_digest as _weights_digest
    from dct_tpu.observability.goodput import config_hash as _config_hash

    def _emit_compile_event(component, event, **fields):
        # Late-bound process-default sink (the same idiom as the
        # batcher's serve.* events): a skewed/corrupt package artifact
        # must be a LOUD miss on the event log, not a silent recompile.
        from dct_tpu.observability import events as _events

        _events.get_default().emit(component, event, **fields)

    aot_root = meta.get("_aot_dir")
    armed = bool(aot_root) and (_cc.aot_enabled() or force_store)
    store = _cc.store_from_env(
        aot_root,
        family=family,
        config_hash=_config_hash(
            {k: v for k, v in meta.items() if not k.startswith("_")}
        ),
        mesh="serve_local",
        # The scorer closes over the weights — they are constants baked
        # into the executable, so they MUST be part of the artifact
        # identity (a meta-identical package with different weights
        # would otherwise load a stale model's executable). Hashed only
        # when the store can actually engage (one build-time pass).
        # QuantTensor leaves hash as their npz representation (q8 +
        # scale planes), so an int8/bf16 variant of the same checkpoint
        # keys a DISTINCT artifact from its f32 twin by content alone.
        extra=(
            {"weights": _weights_digest(_digest_view(weights))}
            if armed else None
        ),
        emit=_emit_compile_event,
    )
    if force_store and aot_root:
        store.enabled = True
    forward_prog = store.wrap(forward, program="serve_scorer")

    def score(x: np.ndarray) -> np.ndarray:
        if moe:
            # MoE capacity is a function of the TOTAL token count:
            # padding rows would change which tokens get dropped, so the
            # request is scored at its true shape (jit recompiles per
            # distinct request size — the opt-in engine's cost here;
            # the AOT store still serves repeat sizes across restarts).
            return np.asarray(jax.device_get(forward_prog(x)))
        n = len(x)
        padded = 1
        while padded < n:
            padded *= 2
        if padded != n:
            x = np.concatenate([x, np.repeat(x[-1:], padded - n, axis=0)])
        return np.asarray(jax.device_get(forward_prog(x)))[:n]

    return score


class _Request:
    """One logical request in flight through the batcher."""

    __slots__ = ("x", "slot", "t", "done", "probs", "error")

    def __init__(self, x: np.ndarray, slot: str):
        self.x = x
        self.slot = slot
        self.t = time.monotonic()
        self.done = threading.Event()
        self.probs: np.ndarray | None = None
        self.error: str | None = None


class _Group:
    """Pending requests sharing one batch key (weights/meta/row shape)."""

    __slots__ = ("weights", "meta", "items", "rows")

    def __init__(self, weights: dict, meta: dict):
        self.weights = weights
        self.meta = meta
        self.items: list[_Request] = []
        self.rows = 0

    @property
    def t_oldest(self) -> float:
        return self.items[0].t if self.items else float("inf")


class MicroBatcher:
    """The dynamic micro-batcher behind both HTTP server modes.

    - ``max_batch`` caps a flush in ROWS (a multi-row request always
      flushes whole; a single request larger than the cap flushes
      alone).
    - ``window_ms`` is the co-arrival deadline: a flush waits at most
      this long past the OLDEST queued request before dispatching. 0
      (the default) is purely opportunistic — whatever is queued when a
      worker frees up merges, and an idle server adds zero latency.
    - ``workers`` scoring threads drain the queue (numpy releases the
      GIL inside the stacked GEMMs, so workers overlap on real cores).
      ``workers=0`` scores inline on the caller's thread through the
      same code path — the hermetic mode tests and the loadgen
      selftest use.

    Thread-safe; shared by every handler thread of a server. Slot flips
    stay atomic under concurrency because the batch key includes the
    identity of the weights dict the package cache resolved — a request
    routed to the new package can never merge into a flush of the old
    one.
    """

    def __init__(
        self,
        *,
        max_batch: int = 64,
        window_ms: float = 0.0,
        workers: int = 2,
        engine: str = "numpy",
        metrics=None,
        emit_events: bool | None = None,
    ):
        self.max_batch = max(1, int(max_batch))
        self.window_s = max(0.0, float(window_ms)) / 1e3
        self.engine = str(engine or "numpy").strip().lower()
        self.metrics = metrics
        if emit_events is None:
            from dct_tpu.config import _env

            # Same opt-in as serving spans: per-flush disk appends have
            # no place on an un-traced heavy-traffic hot path.
            emit_events = _env("DCT_SERVE_TRACE", False, bool)
        self.emit_events = bool(emit_events)
        self._cond = threading.Condition()
        self._groups: dict = {}
        self._order: deque = deque()
        self._closed = False
        self._jax_scorers: dict = {}
        self.flushes = 0  # lifetime flush count (tests/diagnostics)
        self.scored_requests = 0  # lifetime logical requests scored
        self._shrink = 0  # workers asked to exit (set_workers)
        self._spawned = 0  # lifetime worker-thread ordinal (names)
        # (t_done, rows) of recent flush completions — the service-rate
        # window the admission controller's queue-wait estimate reads.
        self._done: deque = deque(maxlen=256)
        self._threads: list[threading.Thread] = []
        for _ in range(max(0, int(workers))):
            self._spawn_worker()

    # -- request side ---------------------------------------------------

    def score(
        self, weights: dict, meta: dict, x: np.ndarray,
        *, slot: str = "default", timeout: float = 30.0,
    ) -> np.ndarray:
        """Blocking scoring of one validated request; returns this
        request's probability array. Raises :class:`ScoringError` for
        any server-fault (broken weights, non-finite output, timeout)."""
        if not self._threads:
            return self._score_one(weights, meta, x)
        req = _Request(np.ascontiguousarray(x, np.float32), slot)
        key = (id(weights), meta.get("model", "weather_mlp"), x.shape[1:])
        with self._cond:
            if self._closed:
                raise ScoringError("micro-batcher is closed")
            g = self._groups.get(key)
            if g is None:
                g = self._groups[key] = _Group(weights, meta)
                self._order.append(key)
            g.items.append(req)
            g.rows += len(req.x)
            self._cond.notify()
        if not req.done.wait(timeout):
            raise ScoringError(f"scoring timed out after {timeout:.0f}s")
        if req.error is not None:
            raise ScoringError(req.error)
        return req.probs

    def _score_one(self, weights: dict, meta: dict,
                   x: np.ndarray) -> np.ndarray:
        with self._cond:
            self.scored_requests += 1
            seq = self.scored_requests
        self._fire_score_faults(seq)
        probs = self._dispatch(weights, meta, [x])[0]
        if not np.isfinite(probs).all():
            raise ScoringError("non-finite probabilities")
        return probs

    # -- saturation introspection (admission control / autoscaling) -----

    def queued_rows(self) -> int:
        """Rows currently queued behind in-flight flushes — the
        admission controller's primary overload signal."""
        with self._cond:
            return sum(g.rows for g in self._groups.values())

    #: Flush completions older than this stop informing the rate.
    _RATE_WINDOW_S = 10.0

    def service_rate(self) -> float | None:
        """Recent rows/second over all workers (None until at least two
        flush completions land inside the window — no evidence must not
        read as zero capacity)."""
        now = time.monotonic()
        with self._cond:
            while self._done and now - self._done[0][0] > self._RATE_WINDOW_S:
                self._done.popleft()
            if len(self._done) < 2:
                return None
            rows = sum(r for _, r in self._done)
            span = now - self._done[0][0]
        if span <= 0:
            return None
        return rows / span

    def estimated_wait_s(self) -> float | None:
        """Queue-wait estimate: queued rows over the recent service
        rate. None when there is no rate evidence yet."""
        return self.saturation()[1]

    def saturation(self) -> tuple:
        """(queued_rows, est_wait_s|None) in ONE lock pass — the
        admission gate's per-request read. A self-consistent snapshot
        (depth and the rate window observed together), and one
        acquisition of the contended condition instead of three on the
        exact path that runs hottest during overload."""
        now = time.monotonic()
        with self._cond:
            queued = sum(g.rows for g in self._groups.values())
            while self._done and now - self._done[0][0] > self._RATE_WINDOW_S:
                self._done.popleft()
            if len(self._done) < 2:
                return queued, None
            rows = sum(r for _, r in self._done)
            span = now - self._done[0][0]
        if span <= 0 or rows <= 0:
            return queued, None
        return queued, queued / (rows / span)

    def _fire_score_faults(self, seq: int) -> None:
        """The serving-side ``DCT_FAULT_SPEC`` hook point (``score``):
        ``crash_worker`` kills this process mid-traffic (the ServerPool
        respawn drill), ``slow_score`` sleeps per flush (deterministic
        overload). Consulted only while a plan is armed — the unarmed
        check is one attribute read."""
        from dct_tpu.resilience import faults as _faults

        plan = _faults.get_default()
        if plan.enabled:
            plan.maybe_fire("score", req=seq)

    # -- worker side ----------------------------------------------------

    #: Jitted scorers kept per batcher, at most this many: each entry
    #: pins device-resident params, so the cache must not accumulate one
    #: per package ever served (the same reason _PackageCache evicts).
    _JAX_SCORER_CAP = 8

    def _jax_scorer_for(self, weights: dict, meta: dict):
        """Scorer cache entries hold a STRONG reference to the weights
        dict next to the compiled fn: the key is ``id(weights)``, and an
        id is only unique while the object lives — without the ref, a
        retired package's freed dict could hand its id to a new
        package's weights and silently serve the old model. Oldest
        entries evict past the cap."""
        key = id(weights)
        entry = self._jax_scorers.get(key)
        if entry is None or entry[0] is not weights:
            entry = (weights, _build_jax_scorer(weights, meta))
            self._jax_scorers.pop(key, None)
            self._jax_scorers[key] = entry
            while len(self._jax_scorers) > self._JAX_SCORER_CAP:
                self._jax_scorers.pop(next(iter(self._jax_scorers)))
        return entry[1]

    def _dispatch(self, weights: dict, meta: dict, arrays: list) -> list:
        if self.engine == "jax":
            fn = self._jax_scorer_for(weights, meta)
            if meta.get("model", "weather_mlp") == "weather_moe":
                # Same segmentation as the numpy path: MoE routing
                # capacity depends on the total token count, so merging
                # (or padding) would make a request's drops depend on
                # co-batched traffic.
                return [fn(a) for a in arrays]
            stacked = (
                np.concatenate(arrays, axis=0)
                if len(arrays) > 1 else arrays[0]
            )
            probs = fn(stacked)
            out, start = [], 0
            for a in arrays:
                out.append(probs[start:start + len(a)])
                start += len(a)
            return out
        return score_rows_invariant(weights, meta, arrays)

    def _claim(self, key, g: _Group) -> tuple:
        """Pop up to ``max_batch`` rows of ``g`` (≥ 1 request always);
        caller holds the lock."""
        take: list[_Request] = []
        rows = 0
        while g.items and (
            not take or rows + len(g.items[0].x) <= self.max_batch
        ):
            req = g.items.pop(0)
            take.append(req)
            rows += len(req.x)
        if not g.items:
            self._groups.pop(key, None)
            try:
                self._order.remove(key)
            except ValueError:
                pass
        return g.weights, g.meta, take

    def _spawn_worker(self) -> None:
        t = threading.Thread(
            target=self._worker,
            name=f"dct-serve-worker-{self._spawned}", daemon=True,
        )
        self._spawned += 1
        self._threads.append(t)
        t.start()

    @property
    def workers(self) -> int:
        """Target worker count (live threads minus pending shrinks)."""
        with self._cond:
            return max(0, len(self._threads) - self._shrink)

    def set_workers(self, n: int) -> None:
        """Scale the scoring pool to ``n`` threads — the autoscaler's
        in-process capacity axis. Scale-down is cooperative: surplus
        workers exit at their next loop visit (never mid-flush), so
        in-flight requests finish normally."""
        n = max(0, int(n))
        with self._cond:
            if self._closed:
                return
            current = len(self._threads) - self._shrink
            if n < current:
                self._shrink += current - n
                self._cond.notify_all()
                return
            spawn = n - current
        for _ in range(max(0, spawn)):
            with self._cond:
                if self._shrink > 0:  # an unserved shrink cancels out
                    self._shrink -= 1
                    continue
            self._spawn_worker()

    def _worker(self) -> None:
        while True:
            batch = None
            with self._cond:
                while batch is None:
                    if self._shrink > 0:
                        # A scale-down claimed this worker: leave the
                        # pool between flushes.
                        self._shrink -= 1
                        try:
                            self._threads.remove(threading.current_thread())
                        except ValueError:
                            pass
                        return
                    if self._closed and not self._groups:
                        return
                    now = time.monotonic()
                    next_deadline = None
                    for key in list(self._order):
                        g = self._groups.get(key)
                        if g is None or not g.items:
                            self._groups.pop(key, None)
                            try:
                                self._order.remove(key)
                            except ValueError:
                                pass
                            continue
                        deadline = g.t_oldest + self.window_s
                        if (
                            self._closed
                            or g.rows >= self.max_batch
                            or now >= deadline
                        ):
                            batch = self._claim(key, g)
                            break
                        if next_deadline is None or deadline < next_deadline:
                            next_deadline = deadline
                    if batch is None:
                        if self._closed and not self._groups:
                            return
                        if next_deadline is None:
                            self._cond.wait()
                        else:
                            self._cond.wait(max(0.0, next_deadline - now))
                queue_depth = sum(
                    grp.rows for grp in self._groups.values()
                )
                self.flushes += 1
            self._flush(batch, queue_depth)

    def _flush(self, batch: tuple, queue_depth: int) -> None:
        weights, meta, items = batch
        rows = sum(len(req.x) for req in items)
        waited_ms = round(
            (time.monotonic() - min(req.t for req in items)) * 1e3, 3
        )
        with self._cond:
            self.scored_requests += len(items)
            seq = self.scored_requests
        self._fire_score_faults(seq)
        try:
            results = self._dispatch(weights, meta, [r.x for r in items])
            for req, probs in zip(items, results):
                if np.isfinite(probs).all():
                    req.probs = probs
                else:
                    # A finite validated input producing NaN is a broken
                    # checkpoint — attributed per request so the 500
                    # lands on exactly the requests it poisoned.
                    req.error = "non-finite probabilities"
        except Exception as e:  # noqa: BLE001 — anything past validation
            # is a server fault; every co-batched request shares it.
            msg = f"{type(e).__name__}: {e}"
            for req in items:
                req.error = msg
            if self.emit_events:
                from dct_tpu.observability import events as _events

                _events.get_default().emit(
                    "serve", "serve.batch_error",
                    rows=rows, requests=len(items), error=msg[:300],
                )
        finally:
            for req in items:
                req.done.set()
            with self._cond:
                # Completion record AFTER any injected slow_score sleep,
                # so the service-rate window prices the real (possibly
                # degraded) capacity the queue-wait estimate divides by.
                self._done.append((time.monotonic(), rows))
        if self.metrics is not None:
            try:
                self.metrics.observe_batch(rows, len(items), queue_depth)
            except Exception:  # noqa: BLE001 — telemetry never fails a flush
                pass
        if self.emit_events:
            from dct_tpu.observability import events as _events

            _events.get_default().emit(
                "serve", "serve.batch_flush",
                rows=rows, requests=len(items), queue_depth=queue_depth,
                waited_ms=waited_ms,
            )

    # -- lifecycle -------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting requests, drain pending flushes, join workers."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout)


def batcher_from_env(metrics=None) -> MicroBatcher:
    """A :class:`MicroBatcher` configured from the ``DCT_SERVE_*`` knobs
    (``ServingConfig.from_env`` is the registry of record)."""
    from dct_tpu.config import ServingConfig

    cfg = ServingConfig.from_env()
    return MicroBatcher(
        max_batch=cfg.max_batch,
        window_ms=cfg.batch_window_ms,
        workers=cfg.workers,
        engine=cfg.engine,
        metrics=metrics,
    )
