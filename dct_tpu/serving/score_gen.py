"""Deploy-package generation: checkpoint -> {model.npz, model_meta.json,
score.py, conda.yaml}.

The analog of the reference's ``prepare_package`` code-generation block
(dags/azure_manual_deploy.py:46-134), with its two bugs fixed:

- ``input_dim`` is read from the checkpoint's self-describing meta instead
  of being hardcoded to 5 (:109);
- the serving stack is numpy-only (conda.yaml without torch/lightning,
  :127-134) because weights ship as ``model.npz``.

The generated score.py keeps the reference's operational contract:
``init()`` locates the model under AZUREML_MODEL_DIR with the same
expected-path -> nested -> os.walk fallback chain (:79-114), ``run()``
accepts ``{"data": [[...]]}`` and returns ``{"probabilities": [[...]]}``
(:116-124). The numerical core is embedded verbatim from
:mod:`dct_tpu.serving.runtime` so the deployed code equals the tested code.
"""

from __future__ import annotations

import inspect
import json
import os

import numpy as np


def _flatten_params(tree: dict, prefix: str = "") -> dict:
    """Flax param tree -> flat {'a/b/c': ndarray} dict (npz-friendly)."""
    out: dict = {}
    for name, val in tree.items():
        key = f"{prefix}/{name}" if prefix else str(name)
        if isinstance(val, dict):
            out.update(_flatten_params(val, key))
        else:
            out[key] = np.asarray(val, np.float32)
    return out


def _serving_weights(p: dict, family: str) -> dict:
    """A dense host param tree -> the serving weights dict for
    ``family`` (shared by the checkpoint and live-state exporters)."""
    from dct_tpu.serving.runtime import _SEQUENCE_FAMILIES

    # Single source of truth with runtime's dispatch (a family in one
    # list but not the other would export through the wrong branch).
    if family in _SEQUENCE_FAMILIES:
        return _flatten_params(p)

    def layer_index(name: str) -> int:
        tail = name.rsplit("_", 1)[-1]
        return int(tail) if tail.isdigit() else -1

    layers = sorted(p, key=layer_index)
    if not all(
        isinstance(p[n], dict) and {"kernel", "bias"} <= set(p[n])
        for n in layers
    ):
        raise ValueError(
            f"Serving export for model={family!r} expects a sequential "
            f"dense stack; checkpoint has param tree {sorted(p)} — "
            "register a dedicated exporter for this family"
        )
    weights = {}
    for i, name in enumerate(layers):
        weights[f"w{i}"] = np.asarray(p[name]["kernel"], np.float32)
        weights[f"b{i}"] = np.asarray(p[name]["bias"], np.float32)
    return weights


def weights_from_checkpoint(ckpt_path: str) -> tuple[dict, dict]:
    """model.ckpt (flax msgpack) -> (serving weights dict, meta).

    The MLP family converts to an anonymous sequential dense stack
    (``w0/b0..`` keys — what :func:`runtime.mlp_forward_numpy` consumes
    and what existing deployments already serve). Sequence families
    convert to the flax param tree flattened to ``/``-joined keys;
    :func:`runtime.forward_numpy` dispatches on ``meta["model"]``.
    """
    from dct_tpu.checkpoint.manager import load_checkpoint

    params, meta = load_checkpoint(ckpt_path)
    family = meta.get("model", "weather_mlp")
    return _serving_weights(params["params"], family), meta


def weights_from_state(state, meta: dict) -> tuple[dict, dict]:
    """A LIVE TrainState -> (serving weights dict, meta): the direct
    publish path for rigs that package without a checkpoint file
    round-trip (benches, eval harnesses over in-memory states).

    Gather-on-publish contract (docs/PARALLELISM.md): the params go
    through the partition rules' gather fns, so a state sharded over
    any mesh layout exports DENSE host arrays — a sharded jax.Array
    must never leak into a package. Enforced tree-wide by the dct-lint
    ``gather-on-publish`` rule.
    """
    from dct_tpu.parallel.sharding_rules import gather_tree

    dense = gather_tree(state.params)
    family = dict(meta).get("model", "weather_mlp")
    p = dense["params"] if "params" in dense else dense
    return _serving_weights(p, family), dict(meta)


def _publish_text(path: str, text: str) -> None:
    """Atomic text-file publish: tmp sibling + ``os.replace`` (the
    platform-wide torn-write convention — a serving worker or rollout
    stage reading the package mid-regeneration must never see a
    half-written file)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def export_npz_weights(ckpt_path: str, deploy_dir: str) -> dict:
    """model.ckpt -> model.npz + model_meta.json in ``deploy_dir``."""
    weights, meta = weights_from_checkpoint(ckpt_path)
    os.makedirs(deploy_dir, exist_ok=True)
    npz_path = os.path.join(deploy_dir, "model.npz")
    npz_tmp = f"{npz_path}.tmp.{os.getpid()}"
    with open(npz_tmp, "wb") as f:
        np.savez(f, **weights)
    os.replace(npz_tmp, npz_path)
    _publish_text(
        os.path.join(deploy_dir, "model_meta.json"),
        json.dumps(meta, indent=2),
    )
    return meta


_SCORE_TEMPLATE = '''"""Generated inference server entry (numpy-only).

Serves the dct_tpu rain classifier: init() loads model.npz/model_meta.json
from AZUREML_MODEL_DIR (with nested-path fallbacks), run() scores JSON
payloads {{"data": [[...feature vector...], ...]}}.
"""

import json
import os

import numpy as np

# ---- embedded from dct_tpu.serving.runtime (tested source of truth) ----
{runtime_source}
# ------------------------------------------------------------------------

_WEIGHTS = None
_META = None


def _locate(name):
    base = os.environ.get("AZUREML_MODEL_DIR", ".")
    expected = os.path.join(base, name)
    if os.path.exists(expected):
        return expected
    nested = os.path.join(base, "deploy_package", name)
    if os.path.exists(nested):
        return nested
    for root, _dirs, files in os.walk(base):
        if name in files:
            return os.path.join(root, name)
    raise FileNotFoundError(f"{{name}} not found under {{base}}")


def init():
    global _WEIGHTS, _META
    npz = np.load(_locate("model.npz"))
    # assemble_weights reconstitutes quantized packages (::q8/::scale/
    # ::bf16 key pairs -> QuantTensor / widened f32); a plain f32
    # package passes through unchanged.
    _WEIGHTS = assemble_weights({{k: npz[k] for k in npz.files}})
    with open(_locate("model_meta.json")) as f:
        _META = json.load(f)
    print(f"Model loaded: input_dim={{_META['input_dim']}}")


def run(raw_data):
    try:
        payload = json.loads(raw_data) if isinstance(raw_data, str) else raw_data
        return score_payload(_WEIGHTS, _META, payload["data"])
    except Exception as e:
        return {{"error": str(e)}}
'''

_CONDA_YAML = """name: dct-tpu-inference
channels:
  - conda-forge
dependencies:
  - python=3.10
  - numpy
  - pip
  - pip:
      - azureml-inference-server-http
"""


def render_score_py() -> str:
    """The generated score.py text (shared by the f32 packager and the
    quantized-package writer, serving/quant.py — both must embed the
    SAME tested runtime)."""
    from dct_tpu.serving import runtime

    # Embed the WHOLE runtime module (every family's forward + dispatch);
    # drop the __future__ import, which must stay file-leading and is
    # unneeded at serving time.
    runtime_source = inspect.getsource(runtime).replace(
        "from __future__ import annotations\n", ""
    )
    # str.format substitutes values verbatim (braces inside runtime_source
    # are untouched); only the template's own {{ }} literals are unescaped.
    return _SCORE_TEMPLATE.format(runtime_source=runtime_source)


def generate_score_package(ckpt_path: str, deploy_dir: str) -> dict:
    """Write the full deploy package; returns the model meta."""
    meta = export_npz_weights(ckpt_path, deploy_dir)

    _publish_text(os.path.join(deploy_dir, "score.py"), render_score_py())
    _publish_text(os.path.join(deploy_dir, "conda.yaml"), _CONDA_YAML)

    # Packaging-time scorer warm-up (compilecache): with the compile
    # cache armed AND DCT_COMPILE_CACHE_WARM_SIZES set, pre-compile the
    # jitted batched scorer at those (power-of-two-padded) batch sizes
    # into <deploy_dir>/aot/ — the deployed package then carries its
    # executables and an endpoint worker's first score deserializes
    # instead of compiling. Best-effort: a rig without a working jax
    # backend still produces a valid (un-warmed) package.
    from dct_tpu import compilecache as _compilecache

    if _compilecache.enabled() and _compilecache.warm_sizes():
        _compilecache.warm_package_scorer(deploy_dir)
    return meta
