from dct_tpu.checkpoint.manager import (  # noqa: F401
    BestLastCheckpointer,
    save_checkpoint,
    load_checkpoint,
    TrainStateCheckpointer,
)
