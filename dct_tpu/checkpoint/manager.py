"""Checkpointing: best/last policy + full-state resume.

Two tiers, mirroring and extending the reference:

1. **Deploy tier** (`*.ckpt` single files) — the analog of Lightning's
   ``ModelCheckpoint(dirpath=data/models, filename="weather-best-{epoch:02d}-
   {val_loss:.2f}", save_top_k=1, monitor=val_loss, mode=min, save_last=True)``
   (jobs/train_lightning_ddp.py:103-110). Same directory layout, same
   filename convention, same ``last.ckpt`` fallback — so the training DAG's
   ``ls *.ckpt`` verification gate (dags/2_pytorch_training.py:81-91) and the
   deploy DAG's "first .ckpt in best_checkpoints" pick
   (dags/azure_manual_deploy.py:46-50) work unchanged. Format: flax msgpack
   of ``{"meta": {...}, "params": ...}`` — self-describing (input_dim,
   feature names, architecture) so serving never hardcodes ``input_dim=5``
   like the reference's score.py does (dags/azure_manual_deploy.py:109).

2. **Resume tier** (per-process ``state.npz`` with crash-safe directory
   rotation) — full TrainState (params + Adam moments + step + rng), which
   the reference cannot do at all (``fit()`` never gets a ckpt_path;
   jobs/train_lightning_ddp.py:143). Continuous training can therefore
   actually continue rather than restart from scratch. Cross-process-
   sharded leaves (TP/SP spanning hosts) save as local shards and
   reassemble on restore — no allgather, no cross-process coordination.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
from flax import serialization

from dct_tpu.observability import events as _events
from dct_tpu.observability import spans as _spans
from dct_tpu.resilience import faults as _faults


def needs_cross_process_gather(tree) -> bool:
    """True when any leaf is sharded across processes (not addressable
    from this host alone)."""
    return any(
        isinstance(a, jax.Array) and not a.is_fully_addressable
        for a in jax.tree.leaves(tree)
    )


def to_host(tree):
    """Device tree -> host numpy tree.

    Arrays sharded across processes (tensor/sequence parallelism spanning
    hosts) are not fully addressable and cannot be ``device_get``; they are
    assembled with a cross-process allgather instead. NB: the allgather is
    a COLLECTIVE — when any leaf is non-addressable
    (:func:`needs_cross_process_gather`), every process must call this
    function (the Trainer does: it gathers on all ranks, then gates the
    file write on the coordinator).
    """

    def one(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(a, tiled=True))
        return np.asarray(jax.device_get(a))

    return jax.tree.map(one, tree)


def save_checkpoint(path: str, params: Any, meta: dict) -> str:  # dct: noqa[rank0-io] — caller-gated: the trainer invokes the deploy tier under its coordinator gate; the write itself must stay rank-agnostic for tests and single-process tools
    """Serialize {meta, params} to a single msgpack file.

    Write-to-temp + ``os.replace``: a crash anywhere in the window (now
    injectable — ``slow_save`` widens it, ``crash_save`` dies inside it)
    can never publish a torn best/last file; at worst ``*.tmp`` debris
    remains and the previous publish stays intact. The temp name is
    pid-suffixed so concurrent writers (another rank, a stale zombie)
    cannot tear each other's in-flight temp.
    """
    payload = {"meta": dict(meta), "params": to_host(params)}
    data = serialization.msgpack_serialize(payload)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    # Fault hook INSIDE the vulnerable window: tmp written, final not
    # yet renamed — the exact instant a preemption would tear a
    # non-atomic write.
    _faults.get_default().maybe_fire("save", save_kind="deploy", path=path)
    os.replace(tmp, path)  # atomic: no torn ckpt if a rank dies mid-write
    return path


def load_checkpoint(path: str) -> tuple[Any, dict]:
    """Returns (params, meta)."""
    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return payload["params"], dict(payload["meta"])


class BestLastCheckpointer:
    """save_top_k=1 on min val_loss, plus always-updated last.ckpt."""

    def __init__(
        self,
        dirpath: str,
        *,
        filename_template: str = "weather-best-{epoch:02d}-{val_loss:.2f}",
        monitor: str = "val_loss",
        mode: str = "min",
    ):
        self.dirpath = dirpath
        self.filename_template = filename_template
        self.monitor = monitor
        self.sign = 1.0 if mode == "min" else -1.0
        self.best_value: float | None = None
        self.best_model_path: str = ""
        os.makedirs(dirpath, exist_ok=True)

    @property
    def last_path(self) -> str:
        return os.path.join(self.dirpath, "last.ckpt")

    def update(self, *, epoch: int, metrics: dict, params: Any, meta: dict) -> bool:  # dct: noqa[rank0-io] — caller-gated: Trainer.fit calls update() under `if self.coordinator:`; the checkpointer has no rank identity of its own
        """Write last.ckpt; if monitor improved, replace the best file.
        Returns True when a new best was saved."""
        meta = {**meta, "epoch": int(epoch), **{k: float(v) for k, v in metrics.items()}}
        with _spans.get_default().span(
            "checkpoint.deploy_write", component="checkpoint",
            epoch=int(epoch),
        ) as sp:
            save_checkpoint(self.last_path, params, meta)

            value = float(metrics[self.monitor])
            improved = self.best_value is None or self.sign * value < self.sign * self.best_value
            if improved:
                name = self.filename_template.format(epoch=epoch, **metrics) + ".ckpt"
                new_path = os.path.join(self.dirpath, name)
                save_checkpoint(new_path, params, meta)
                if self.best_model_path and os.path.exists(self.best_model_path):
                    if os.path.abspath(self.best_model_path) != os.path.abspath(new_path):
                        os.remove(self.best_model_path)
                self.best_value = value
                self.best_model_path = new_path
            sp.set(improved=improved)
        _events.get_default().emit(
            "checkpoint", "best_saved" if improved else "last_saved",
            epoch=int(epoch),
            path=self.best_model_path if improved else self.last_path,
            **{self.monitor: value},
        )
        return improved


class TrainStateCheckpointer:  # dct: noqa[rank0-io] — per-process BY DESIGN: every rank owns its private p<rank>/ rotation dir (shard-local saves, no cross-rank file is ever shared), so rank-0 gating would lose all nonzero ranks' resume state
    """Full train-state save/restore for true resume (per-process npz
    with crash-safe rotation; shard-local for cross-process arrays)."""

    def __init__(self, dirpath: str):
        self.dirpath = os.path.abspath(dirpath)
        os.makedirs(self.dirpath, exist_ok=True)

    # Crash-safe directory rotation: a new checkpoint is fully written to
    # ``state.next`` before the live ``state`` is touched, so at every
    # instant at least one *complete* checkpoint exists (restore prefers
    # state > state.next > state.old). A plain force=True overwrite of the
    # single live dir would destroy the only resume point if the process
    # died mid-save — the exact preemption scenario resume exists for.
    _LIVE, _NEXT, _OLD = "state", "state.next", "state.old"

    def _dir(self, name: str) -> str:
        return os.path.join(self.dirpath, name)

    def _rotation_dirs(self) -> tuple[str, ...]:
        return (
            self._dir(self._LIVE), self._dir(self._NEXT), self._dir(self._OLD)
        )

    def _restore_candidates(self) -> list[str]:
        return [
            d
            for d in self._rotation_dirs()
            if os.path.exists(os.path.join(d, "state.npz"))
        ]

    @staticmethod
    def _dir_is_torn(d: str) -> bool:
        """A rotation dir left by a save preempted before its atomic
        rename: empty, or containing only *.tmp debris."""
        try:
            names = os.listdir(d)
        except OSError:
            # Unreadable is NOT torn: route into restore()'s loud error
            # rather than silently restarting over existing progress.
            return False
        return all(n.endswith(".tmp") for n in names)

    @staticmethod
    def _tree(state) -> dict:
        return {
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "rng": state.rng,
        }

    @staticmethod
    def _index_key(index) -> tuple:
        """Deterministic key for a shard's global position (start offsets).
        Replicated copies on different local devices share a key — saved
        once, fanned back out on restore."""
        return tuple(sl.start or 0 for sl in index)

    def save(self, state, meta: dict | None = None) -> str:
        """Persist this process's ADDRESSABLE view of the train state.

        ``meta``: small JSON-able run facts (epochs_completed,
        target_epochs, ...) stored beside the arrays and returned by
        :meth:`load_meta` — the continuous-training re-run semantics
        (Trainer.fit) are decided from these, not from step arithmetic
        that breaks when the dataset size changes between daily runs.

        Fully-addressable leaves (replicated params, single-host runs) are
        saved whole; leaves sharded across processes (TP/SP spanning
        hosts) are saved as this process's local shards only — RAM and
        disk stay proportional to the local share, with no allgather, at
        exactly the scale cross-host sharding exists for. Each leaf i is
        stored as key ``"i"`` (whole) or keys ``"i_s<off0>x<off1>..."``
        (shards, named by their GLOBAL start offsets so restore matches by
        position, not ordinal — a changed process->device mapping is then
        a detected error instead of a silent global permutation).

        Storage is a plain ``state.npz`` per process — deliberately NOT an
        orbax pytree directory: orbax's save finalization (structure
        metadata, ocdbt manifest merge) is gated on the primary host even
        with ``primary_host=None``, so nonzero ranks' private directories
        end up unreadable. This tier is host-local numpy by construction
        and needs zero cross-process coordination.
        """
        self.wait()
        return self._publish(self._entries(state), meta)

    def _entries(self, state) -> dict:
        """Device state -> host {key: ndarray} dict (the npz payload).

        Flattened to an index-keyed dict: optax opt_states contain
        namedtuples that do not round-trip through generic tree
        serialization; the target treedef at restore time supplies the
        structure instead."""
        leaves = jax.tree.leaves(self._tree(state))
        entries: dict[str, np.ndarray] = {}
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                # One copy per distinct global position: replicated copies
                # on several local devices dedupe to a single entry.
                by_key = {}
                for s in leaf.addressable_shards:
                    by_key.setdefault(self._index_key(s.index), s)
                for k, s in by_key.items():
                    off = "x".join(map(str, k))
                    entries[f"{i}_s{off}"] = np.asarray(s.data)
            else:
                entries[str(i)] = np.asarray(jax.device_get(leaf))
        return entries

    def _publish(self, entries: dict, meta: dict | None = None) -> str:
        """Write ``entries`` (+ meta) into state.next, then rotate."""
        # Span from whichever thread publishes (save_async's worker
        # included): the resume-save I/O window on the trace timeline.
        # try/finally so a FAILED write (ENOSPC — exactly the window an
        # operator opens the trace to diagnose) is still recorded.
        span = _spans.get_default().start(
            "checkpoint.resume_save", component="checkpoint",
            epochs_completed=(meta or {}).get("epochs_completed"),
        )
        try:
            return self._publish_inner(entries, meta)
        except BaseException as e:
            span.attrs["error"] = type(e).__name__
            raise
        finally:
            span.end()

    def _publish_inner(self, entries: dict, meta: dict | None = None) -> str:
        import shutil

        next_dir = self._dir(self._NEXT)
        if os.path.isdir(next_dir):
            shutil.rmtree(next_dir)
        os.makedirs(next_dir)
        # Atomic publish: a save preempted mid-write must never leave a
        # torn state.npz that _restore_candidates would accept.
        final = os.path.join(next_dir, "state.npz")
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **entries)
        # Fault hook between the shard write and its atomic rename: a
        # ``crash_save`` here leaves state.next holding only *.tmp
        # debris — the torn dir _restore_candidates must skip so the
        # previous publish restores (``slow_save`` widens the window for
        # kill-based tests instead).
        _faults.get_default().maybe_fire(
            "save", save_kind="resume_state", dir=next_dir
        )
        os.replace(tmp, final)
        if meta is not None:
            import json

            mfinal = os.path.join(next_dir, "meta.json")
            mtmp = mfinal + ".tmp"
            with open(mtmp, "w") as f:
                json.dump(meta, f)
            os.replace(mtmp, mfinal)

        live, old = self._dir(self._LIVE), self._dir(self._OLD)
        if os.path.isdir(old):
            shutil.rmtree(old)
        if os.path.isdir(live):
            os.rename(live, old)
        os.rename(next_dir, live)
        if os.path.isdir(old):
            shutil.rmtree(old)
        # Emitted from whichever thread published (EventLog is locked);
        # the resume tier is per-process, so every rank's event appears,
        # rank-stamped, in the shared log.
        _events.get_default().emit(
            "checkpoint", "resume_state_saved", dir=live,
            epochs_completed=(meta or {}).get("epochs_completed"),
        )
        return live

    def save_async(self, state, meta: dict | None = None) -> None:
        """Overlap the checkpoint write with the next epoch's compute: the
        device->host snapshot happens NOW (the worker must not touch
        device arrays a donated train step may alias next epoch), and the
        npz write + rotation run on a worker thread. At most one write is
        in flight — a second call joins the first, so the rotation
        protocol's invariants hold unchanged. Call :meth:`wait` (or any
        ``save``/``restore``) before reading the checkpoint back."""
        import threading

        self.wait()
        entries = self._entries(state)

        def work():
            try:
                self._publish(entries, meta)
            except BaseException as e:  # surfaced by the next wait()
                self._error = e

        self._pending = threading.Thread(target=work, daemon=True)
        self._pending.start()

    def wait(self) -> None:
        """Join any in-flight async write; re-raise its failure — a lost
        background write must be as loud as a failed synchronous save
        (ENOSPC on the final epoch would otherwise report success while
        the resume state silently stays one epoch stale)."""
        t = getattr(self, "_pending", None)
        if t is not None:
            t.join()
            self._pending = None
        err = getattr(self, "_error", None)
        if err is not None:
            self._error = None
            raise RuntimeError(
                f"async train-state checkpoint write failed: {err!r}"
            ) from err

    def load_meta(self) -> dict:
        """Run facts saved beside the newest restorable checkpoint
        (empty dict when the checkpoint predates meta support)."""
        import json

        self.wait()
        candidates = self._restore_candidates()
        if not candidates:
            return {}
        # candidates[0] to stay paired with restore(), which reads the
        # same directory's arrays.
        path = os.path.join(candidates[0], "meta.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return dict(json.load(f))

    def exists(self) -> bool:
        self.wait()
        # A readable checkpoint, or a dir in an unreadable (legacy) format
        # — the latter must route resume into restore()'s loud error, not
        # a silent from-scratch restart that overwrites the old progress.
        # Torn-save debris (only *.tmp content) does NOT count: the save
        # protocol itself creates those and a fresh start is correct.
        if self._restore_candidates():
            return True
        return any(
            os.path.isdir(d) and not self._dir_is_torn(d)
            for d in self._rotation_dirs()
        )

    def _reassemble(self, template, part_by_key: dict):
        """Offset-keyed local shards -> global jax.Array with the
        template's sharding. Shards are matched by their stored global
        offsets, so a topology whose local shard positions differ from the
        saving run fails loudly instead of permuting data."""
        sharding = template.sharding
        gshape = template.shape
        dev_idx = sharding.addressable_devices_indices_map(gshape)
        want = {self._index_key(ix) for ix in dev_idx.values()}
        if want != set(part_by_key):
            raise ValueError(
                f"Shard-saved leaf holds offsets {sorted(part_by_key)} but "
                f"the current topology needs {sorted(want)}; resume "
                "requires the same mesh/process topology that saved the "
                "state. (If the topology is unchanged, this checkpoint "
                "may predate declared-layout saves — written while the "
                "step's output layout had drifted, e.g. ZeRO-1 sharded "
                "output params; clear the train_state dir to restart "
                "from the deploy checkpoint.)"
            )
        arrays = [
            jax.device_put(part_by_key[self._index_key(ix)], d)
            for d, ix in dev_idx.items()
        ]
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, arrays
        )

    def restore(self, state):
        """Restore into the structure (and shardings) of ``state``
        (apply_fn/tx kept). Whole-saved leaves come back as host numpy;
        shard-saved leaves are reassembled onto this process's devices
        under the template leaf's sharding."""
        self.wait()
        with _spans.get_default().span(
            "checkpoint.restore", component="checkpoint",
        ):
            return self._restore(state)

    def _restore(self, state):
        candidates = self._restore_candidates()
        if not candidates:
            legacy = [
                d
                for d in self._rotation_dirs()
                if os.path.isdir(d) and not self._dir_is_torn(d)
            ]
            if legacy:
                raise RuntimeError(
                    f"Checkpoint dir(s) {legacy} exist but contain no "
                    "state.npz — an unreadable (pre-npz/orbax) format. "
                    "Delete them to restart from scratch, or restore with "
                    "the version that wrote them."
                )
            raise FileNotFoundError(f"No train-state checkpoint under {self.dirpath}")
        npz = np.load(os.path.join(candidates[0], "state.npz"))
        restored = {k: npz[k] for k in npz.files}
        template = self._tree(state)
        treedef = jax.tree.structure(template)
        tleaves = jax.tree.leaves(template)

        def _mismatch(detail: str) -> KeyError:
            # The most common cause is a CONFIG change between runs — a
            # different DCT_OPTIMIZER restructures opt_state, so the
            # saved flat leaves no longer line up with this run's
            # template. Name that instead of a bare index; a silent
            # misaligned restore would train from garbage weights.
            return KeyError(
                f"Checkpoint {candidates[0]} does not match this run's "
                f"TrainState: {detail}. Typically DCT_OPTIMIZER (or "
                "another state-shaping knob) changed since the "
                "checkpoint was written. Restore the original setting, "
                f"or clear {self.dirpath} to restart the trajectory."
            )

        # Count check BOTH directions: a template with FEWER leaves than
        # the checkpoint would otherwise restore silently with every flat
        # index shifted onto the wrong (often identically-shaped) array.
        saved_groups = {
            k.split("_s")[0] for k in restored if k and k[0].isdigit()
        }
        if len(saved_groups) != len(tleaves):
            raise _mismatch(
                f"{len(saved_groups)} leaf groups saved, "
                f"{len(tleaves)} expected"
            )
        leaves = []
        for i, t in enumerate(tleaves):
            if str(i) in restored:
                whole = restored[str(i)]
                if tuple(whole.shape) != tuple(getattr(t, "shape", ())):
                    raise _mismatch(
                        f"leaf {i} has shape {tuple(whole.shape)} on disk "
                        f"but {tuple(getattr(t, 'shape', ()))} in the "
                        "template"
                    )
                leaves.append(whole)
                continue
            prefix = f"{i}_s"
            part_by_key = {
                # 0-d leaves have an empty offset suffix -> key ().
                tuple(
                    int(o) for o in k[len(prefix):].split("x")
                ) if k[len(prefix):] else (): v
                for k, v in restored.items()
                if k.startswith(prefix)
            }
            if not part_by_key:
                raise _mismatch(f"no data for template leaf {i}")
            leaves.append(self._reassemble(t, part_by_key))
        tree = jax.tree.unflatten(treedef, leaves)
        return state.replace(
            step=jax.numpy.asarray(tree["step"]),
            params=tree["params"],
            opt_state=tree["opt_state"],
            rng=jax.numpy.asarray(tree["rng"]),
        )
