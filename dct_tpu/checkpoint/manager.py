"""Checkpointing: best/last policy + full-state resume.

Two tiers, mirroring and extending the reference:

1. **Deploy tier** (`*.ckpt` single files) — the analog of Lightning's
   ``ModelCheckpoint(dirpath=data/models, filename="weather-best-{epoch:02d}-
   {val_loss:.2f}", save_top_k=1, monitor=val_loss, mode=min, save_last=True)``
   (jobs/train_lightning_ddp.py:103-110). Same directory layout, same
   filename convention, same ``last.ckpt`` fallback — so the training DAG's
   ``ls *.ckpt`` verification gate (dags/2_pytorch_training.py:81-91) and the
   deploy DAG's "first .ckpt in best_checkpoints" pick
   (dags/azure_manual_deploy.py:46-50) work unchanged. Format: flax msgpack
   of ``{"meta": {...}, "params": ...}`` — self-describing (input_dim,
   feature names, architecture) so serving never hardcodes ``input_dim=5``
   like the reference's score.py does (dags/azure_manual_deploy.py:109).

2. **Resume tier** (per-process ``state.npz`` with crash-safe directory
   rotation) — full TrainState (params + Adam moments + step + rng), which
   the reference cannot do at all (``fit()`` never gets a ckpt_path;
   jobs/train_lightning_ddp.py:143). Continuous training can therefore
   actually continue rather than restart from scratch. Cross-process-
   sharded leaves (TP/SP spanning hosts) save as local shards and
   reassemble on restore — no allgather, no cross-process coordination.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any

import jax
import numpy as np
from flax import serialization

from dct_tpu.observability import events as _events
from dct_tpu.observability import lineage as _lineage
from dct_tpu.observability import spans as _spans
from dct_tpu.resilience import faults as _faults


def needs_cross_process_gather(tree) -> bool:
    """True when any leaf is sharded across processes (not addressable
    from this host alone)."""
    return any(
        isinstance(a, jax.Array) and not a.is_fully_addressable
        for a in jax.tree.leaves(tree)
    )


def to_host(tree):
    """Device tree -> dense host numpy tree, through the partition
    rules' gather fns (:func:`dct_tpu.parallel.sharding_rules
    .gather_tree`): arrays sharded across processes (tensor/sequence
    parallelism spanning hosts) are assembled with a cross-process
    allgather, everything else is a device_get. NB: the allgather is a
    COLLECTIVE — when any leaf is non-addressable
    (:func:`needs_cross_process_gather`), every process must call this
    function (the Trainer does: it gathers on all ranks, then gates the
    file write on the coordinator).
    """
    from dct_tpu.parallel.sharding_rules import gather_tree

    return gather_tree(tree)


def save_checkpoint(path: str, params: Any, meta: dict) -> str:  # dct: noqa[rank0-io] — caller-gated: the trainer invokes the deploy tier under its coordinator gate; the write itself must stay rank-agnostic for tests and single-process tools
    """Serialize {meta, params} to a single msgpack file.

    Write-to-temp + ``os.replace``: a crash anywhere in the window (now
    injectable — ``slow_save`` widens it, ``crash_save`` dies inside it)
    can never publish a torn best/last file; at worst ``*.tmp`` debris
    remains and the previous publish stays intact. The temp name is
    pid-suffixed so concurrent writers (another rank, a stale zombie)
    cannot tear each other's in-flight temp.
    """
    payload = {"meta": dict(meta), "params": to_host(params)}
    data = serialization.msgpack_serialize(payload)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    # Fault hook INSIDE the vulnerable window: tmp written, final not
    # yet renamed — the exact instant a preemption would tear a
    # non-atomic write.
    _faults.get_default().maybe_fire("save", save_kind="deploy", path=path)
    os.replace(tmp, path)  # atomic: no torn ckpt if a rank dies mid-write
    lin = _lineage.get_default()
    if lin.enabled:
        # Content address from the serialized bytes already in hand (no
        # file re-read); edges to whatever training inputs the trainer
        # declared (dataset snapshot, a restored trajectory) make every
        # published checkpoint a walkable graph hop.
        nid = lin.node(
            "checkpoint", path=path,
            sha256=hashlib.sha256(data).hexdigest(),
            attrs={"epoch": dict(meta).get("epoch")},
        )
        for src in _lineage.run_inputs():
            lin.edge("consumed", nid, src)
    return path


def load_checkpoint(path: str) -> tuple[Any, dict]:
    """Returns (params, meta)."""
    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return payload["params"], dict(payload["meta"])


class BestLastCheckpointer:
    """save_top_k=1 on min val_loss, plus always-updated last.ckpt."""

    def __init__(
        self,
        dirpath: str,
        *,
        filename_template: str = "weather-best-{epoch:02d}-{val_loss:.2f}",
        monitor: str = "val_loss",
        mode: str = "min",
    ):
        self.dirpath = dirpath
        self.filename_template = filename_template
        self.monitor = monitor
        self.sign = 1.0 if mode == "min" else -1.0
        self.best_value: float | None = None
        self.best_model_path: str = ""
        os.makedirs(dirpath, exist_ok=True)

    @property
    def last_path(self) -> str:
        return os.path.join(self.dirpath, "last.ckpt")

    def update(self, *, epoch: int, metrics: dict, params: Any, meta: dict) -> bool:  # dct: noqa[rank0-io] — caller-gated: Trainer.fit calls update() under `if self.coordinator:`; the checkpointer has no rank identity of its own
        """Write last.ckpt; if monitor improved, replace the best file.
        Returns True when a new best was saved."""
        meta = {**meta, "epoch": int(epoch), **{k: float(v) for k, v in metrics.items()}}
        with _spans.get_default().span(
            "checkpoint.deploy_write", component="checkpoint",
            epoch=int(epoch),
        ) as sp:
            save_checkpoint(self.last_path, params, meta)

            value = float(metrics[self.monitor])
            improved = self.best_value is None or self.sign * value < self.sign * self.best_value
            if improved:
                name = self.filename_template.format(epoch=epoch, **metrics) + ".ckpt"
                new_path = os.path.join(self.dirpath, name)
                save_checkpoint(new_path, params, meta)
                if self.best_model_path and os.path.exists(self.best_model_path):
                    if os.path.abspath(self.best_model_path) != os.path.abspath(new_path):
                        os.remove(self.best_model_path)
                        # Retention tombstone: the pruned best is gone on
                        # purpose; without this the integrity audit would
                        # flag it MISSING.
                        _lineage.get_default().retire(
                            self.best_model_path, reason="superseded_best",
                        )
                self.best_value = value
                self.best_model_path = new_path
            sp.set(improved=improved)
        _events.get_default().emit(
            "checkpoint", "best_saved" if improved else "last_saved",
            epoch=int(epoch),
            path=self.best_model_path if improved else self.last_path,
            **{self.monitor: value},
        )
        return improved


class TrainStateCheckpointer:  # dct: noqa[rank0-io] — per-process BY DESIGN: every rank owns its private p<rank>/ rotation dir (shard-local saves, no cross-rank file is ever shared), so rank-0 gating would lose all nonzero ranks' resume state
    """Full train-state save/restore for true resume (per-process npz
    with crash-safe rotation; shard-local for cross-process arrays)."""

    def __init__(self, dirpath: str):
        self.dirpath = os.path.abspath(dirpath)
        os.makedirs(self.dirpath, exist_ok=True)

    # Crash-safe directory rotation: a new checkpoint is fully written to
    # ``state.next`` before the live ``state`` is touched, so at every
    # instant at least one *complete* checkpoint exists (restore prefers
    # state > state.next > state.old). A plain force=True overwrite of the
    # single live dir would destroy the only resume point if the process
    # died mid-save — the exact preemption scenario resume exists for.
    _LIVE, _NEXT, _OLD = "state", "state.next", "state.old"

    def _dir(self, name: str) -> str:
        return os.path.join(self.dirpath, name)

    def _rotation_dirs(self) -> tuple[str, ...]:
        return (
            self._dir(self._LIVE), self._dir(self._NEXT), self._dir(self._OLD)
        )

    def _restore_candidates(self) -> list[str]:
        return [
            d
            for d in self._rotation_dirs()
            if os.path.exists(os.path.join(d, "state.npz"))
        ]

    @staticmethod
    def _dir_is_torn(d: str) -> bool:
        """A rotation dir left by a save preempted before its atomic
        rename: empty, or containing only *.tmp debris."""
        try:
            names = os.listdir(d)
        except OSError:
            # Unreadable is NOT torn: route into restore()'s loud error
            # rather than silently restarting over existing progress.
            return False
        return all(n.endswith(".tmp") for n in names)

    @staticmethod
    def _tree(state) -> dict:
        return {
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "rng": state.rng,
        }

    @staticmethod
    def _index_key(index) -> tuple:
        """Deterministic key for a shard's global position (start offsets).
        Replicated copies on different local devices share a key — saved
        once, fanned back out on restore."""
        return tuple(sl.start or 0 for sl in index)

    def _layout(self, state) -> dict:
        """The LAYOUT MANIFEST saved beside the arrays (``layout.json``):
        per-leaf global shape + declared PartitionSpec + whether the
        leaf was saved whole or as local shards, plus the saving run's
        mesh shape and process topology. Restore uses it to (a) name a
        topology change precisely and (b) re-map saved shards onto a
        DIFFERENT mesh (``shard.topology_remap``) instead of refusing —
        docs/PARALLELISM.md §layout manifest."""
        from dct_tpu.parallel.sharding_rules import leaf_spec, spec_to_json

        leaves = jax.tree.leaves(self._tree(state))
        mesh_shape = None
        entries = []
        for i, leaf in enumerate(leaves):
            sharding = getattr(leaf, "sharding", None)
            if mesh_shape is None and hasattr(sharding, "mesh"):
                try:
                    mesh_shape = {
                        str(k): int(v)
                        for k, v in dict(sharding.mesh.shape).items()
                    }
                except (TypeError, ValueError):
                    mesh_shape = None
            spec = leaf_spec(leaf)
            entries.append({
                "leaf": i,
                "shape": [int(s) for s in getattr(leaf, "shape", ())],
                "spec": spec_to_json(spec) if spec is not None else None,
                "saved": (
                    "shards"
                    if isinstance(leaf, jax.Array)
                    and not leaf.is_fully_addressable
                    else "whole"
                ),
            })
        from dct_tpu.parallel.sharding_rules import dtype_rules_digest

        return {
            "version": 1,
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "mesh": mesh_shape,
            # Precision provenance (docs/PARALLELISM.md §dtype rules):
            # the SAVED arrays are always the dense f32 masters — the
            # dtype rules only shape the traced compute — but a
            # checkpoint written under active rules records which, so
            # a trajectory's precision history is auditable from its
            # manifests alone. "off" = the bitwise status quo.
            "dtype_rules": dtype_rules_digest(),
            "leaves": entries,
        }

    def save(self, state, meta: dict | None = None) -> str:
        """Persist this process's ADDRESSABLE view of the train state.

        ``meta``: small JSON-able run facts (epochs_completed,
        target_epochs, ...) stored beside the arrays and returned by
        :meth:`load_meta` — the continuous-training re-run semantics
        (Trainer.fit) are decided from these, not from step arithmetic
        that breaks when the dataset size changes between daily runs.

        Fully-addressable leaves (replicated params, single-host runs) are
        saved whole; leaves sharded across processes (TP/SP spanning
        hosts) are saved as this process's local shards only — RAM and
        disk stay proportional to the local share, with no allgather, at
        exactly the scale cross-host sharding exists for. Each leaf i is
        stored as key ``"i"`` (whole) or keys ``"i_s<off0>x<off1>..."``
        (shards, named by their GLOBAL start offsets so restore matches by
        position, not ordinal — a changed process->device mapping is then
        a detected error instead of a silent global permutation).

        Storage is a plain ``state.npz`` per process — deliberately NOT an
        orbax pytree directory: orbax's save finalization (structure
        metadata, ocdbt manifest merge) is gated on the primary host even
        with ``primary_host=None``, so nonzero ranks' private directories
        end up unreadable. This tier is host-local numpy by construction
        and needs zero cross-process coordination.
        """
        self.wait()
        return self._publish(self._entries(state), meta, self._layout(state))

    def _entries(self, state) -> dict:
        """Device state -> host {key: ndarray} dict (the npz payload).

        Flattened to an index-keyed dict: optax opt_states contain
        namedtuples that do not round-trip through generic tree
        serialization; the target treedef at restore time supplies the
        structure instead."""
        leaves = jax.tree.leaves(self._tree(state))
        entries: dict[str, np.ndarray] = {}
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                # One copy per distinct global position: replicated copies
                # on several local devices dedupe to a single entry.
                by_key = {}
                for s in leaf.addressable_shards:
                    by_key.setdefault(self._index_key(s.index), s)
                for k, s in by_key.items():
                    off = "x".join(map(str, k))
                    entries[f"{i}_s{off}"] = np.asarray(s.data)
            else:
                entries[str(i)] = np.asarray(jax.device_get(leaf))
        return entries

    def _publish(
        self, entries: dict, meta: dict | None = None,
        layout: dict | None = None,
    ) -> str:
        """Write ``entries`` (+ meta + layout) into state.next, then
        rotate."""
        # Span from whichever thread publishes (save_async's worker
        # included): the resume-save I/O window on the trace timeline.
        # try/finally so a FAILED write (ENOSPC — exactly the window an
        # operator opens the trace to diagnose) is still recorded.
        span = _spans.get_default().start(
            "checkpoint.resume_save", component="checkpoint",
            epochs_completed=(meta or {}).get("epochs_completed"),
        )
        try:
            return self._publish_inner(entries, meta, layout)
        except BaseException as e:
            span.attrs["error"] = type(e).__name__
            raise
        finally:
            span.end()

    def _publish_inner(
        self, entries: dict, meta: dict | None = None,
        layout: dict | None = None,
    ) -> str:
        import shutil

        next_dir = self._dir(self._NEXT)
        if os.path.isdir(next_dir):
            shutil.rmtree(next_dir)
        os.makedirs(next_dir)
        # Atomic publish: a save preempted mid-write must never leave a
        # torn state.npz that _restore_candidates would accept.
        final = os.path.join(next_dir, "state.npz")
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **entries)
        # Fault hook between the shard write and its atomic rename: a
        # ``crash_save`` here leaves state.next holding only *.tmp
        # debris — the torn dir _restore_candidates must skip so the
        # previous publish restores (``slow_save`` widens the window for
        # kill-based tests instead).
        _faults.get_default().maybe_fire(
            "save", save_kind="resume_state", dir=next_dir
        )
        os.replace(tmp, final)
        if meta is not None:
            import json

            mfinal = os.path.join(next_dir, "meta.json")
            mtmp = mfinal + ".tmp"
            with open(mtmp, "w") as f:
                json.dump(meta, f)
            os.replace(mtmp, mfinal)
        if layout is not None:
            import json

            lfinal = os.path.join(next_dir, "layout.json")
            ltmp = lfinal + ".tmp"
            with open(ltmp, "w") as f:
                json.dump(layout, f)
            os.replace(ltmp, lfinal)

        live, old = self._dir(self._LIVE), self._dir(self._OLD)
        if os.path.isdir(old):
            shutil.rmtree(old)
        if os.path.isdir(live):
            os.rename(live, old)
        os.rename(next_dir, live)
        if os.path.isdir(old):
            shutil.rmtree(old)
        # Emitted from whichever thread published (EventLog is locked);
        # the resume tier is per-process, so every rank's event appears,
        # rank-stamped, in the shared log.
        _events.get_default().emit(
            "checkpoint", "resume_state_saved", dir=live,
            epochs_completed=(meta or {}).get("epochs_completed"),
        )
        lin = _lineage.get_default()
        if lin.enabled:
            nid = lin.node(
                "checkpoint", path=os.path.join(live, "state.npz"),
                attrs={
                    "tier": "resume",
                    "epochs_completed": (meta or {}).get("epochs_completed"),
                },
            )
            for src in _lineage.run_inputs():
                lin.edge("consumed", nid, src)
        return live

    def save_async(self, state, meta: dict | None = None) -> None:
        """Overlap the checkpoint write with the next epoch's compute: the
        device->host snapshot happens NOW (the worker must not touch
        device arrays a donated train step may alias next epoch), and the
        npz write + rotation run on a worker thread. At most one write is
        in flight — a second call joins the first, so the rotation
        protocol's invariants hold unchanged. Call :meth:`wait` (or any
        ``save``/``restore``) before reading the checkpoint back."""
        import threading

        self.wait()
        entries = self._entries(state)
        layout = self._layout(state)

        def work():
            try:
                self._publish(entries, meta, layout)
            except BaseException as e:  # surfaced by the next wait()
                self._error = e

        self._pending = threading.Thread(target=work, daemon=True)
        self._pending.start()

    def wait(self) -> None:
        """Join any in-flight async write; re-raise its failure — a lost
        background write must be as loud as a failed synchronous save
        (ENOSPC on the final epoch would otherwise report success while
        the resume state silently stays one epoch stale)."""
        t = getattr(self, "_pending", None)
        if t is not None:
            t.join()
            self._pending = None
        err = getattr(self, "_error", None)
        if err is not None:
            self._error = None
            raise RuntimeError(
                f"async train-state checkpoint write failed: {err!r}"
            ) from err

    def _sibling_candidate_dirs(self) -> list[str]:
        """Sibling ranks' newest restorable rotation dirs (``p<rank>/``
        siblings under the shared ``train_state`` parent). A topology-
        change restore reads shards the SAVING topology placed in other
        processes' files — possible exactly when the resume tier sits
        on a shared filesystem (the test rig and pod-slice NFS case);
        private-disk pods keep the loud same-topology contract."""
        parent = os.path.dirname(self.dirpath)
        out: list[str] = []
        try:
            names = os.listdir(parent)
        except OSError:
            return out
        for n in sorted(names):
            d = os.path.join(parent, n)
            if os.path.abspath(d) == self.dirpath:
                continue
            if not (n.startswith("p") and n[1:].isdigit()):
                continue
            for rot in (self._LIVE, self._NEXT, self._OLD):
                cand = os.path.join(d, rot)
                if os.path.exists(os.path.join(cand, "state.npz")):
                    out.append(cand)
                    break
        return out

    def load_layout(self) -> dict:
        """The layout manifest saved beside the newest restorable
        checkpoint (own dir first, siblings as fallback; empty dict for
        pre-manifest checkpoints)."""
        import json

        self.wait()
        for d in self._restore_candidates() + self._sibling_candidate_dirs():
            path = os.path.join(d, "layout.json")
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        return dict(json.load(f))
                except (OSError, ValueError):
                    return {}
        return {}

    def load_meta(self) -> dict:
        """Run facts saved beside the newest restorable checkpoint
        (empty dict when the checkpoint predates meta support). Falls
        back to a SIBLING rank's meta when this process has no
        checkpoint of its own — the topology-growth restore (e.g. 2
        saving processes resumed as 4) must agree on epochs_completed
        with the ranks that do."""
        import json

        self.wait()
        candidates = self._restore_candidates()
        if not candidates:
            candidates = self._sibling_candidate_dirs()[:1]
        if not candidates:
            return {}
        # candidates[0] to stay paired with restore(), which reads the
        # same directory's arrays.
        path = os.path.join(candidates[0], "meta.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return dict(json.load(f))

    def exists(self) -> bool:
        self.wait()
        # A readable checkpoint, or a dir in an unreadable (legacy) format
        # — the latter must route resume into restore()'s loud error, not
        # a silent from-scratch restart that overwrites the old progress.
        # Torn-save debris (only *.tmp content) does NOT count: the save
        # protocol itself creates those and a fresh start is correct.
        if self._restore_candidates():
            return True
        if any(
            os.path.isdir(d) and not self._dir_is_torn(d)
            for d in self._rotation_dirs()
        ):
            return True
        # Topology growth: a rank with no checkpoint of its own can
        # still restore from sibling ranks' files (shared fs) — resume
        # must say yes or the new rank would restart epoch 0 while the
        # old ranks resume, and the start-epoch allgather check in
        # Trainer.fit would abort the whole world.
        return bool(self._sibling_candidate_dirs())

    @staticmethod
    def _assemble_dense(gshape: tuple, part_by_key: dict):
        """Offset-keyed shards -> one dense host array, or None when
        the shards do not cleanly tile the global shape (out-of-bounds
        placement, gaps, overlaps). Replicated copies saved under the
        same offsets by different processes have already deduped to one
        entry per distinct offset key."""
        first = next(iter(part_by_key.values()))
        dense = np.zeros(gshape, dtype=first.dtype)
        covered = 0
        for off, arr in part_by_key.items():
            off = tuple(off) + (0,) * (len(gshape) - len(off))
            if arr.ndim != len(gshape) or any(
                o + s > g for o, s, g in zip(off, arr.shape, gshape)
            ):
                return None
            dense[tuple(
                slice(o, o + s) for o, s in zip(off, arr.shape)
            )] = arr
            covered += arr.size
        if covered != dense.size:
            return None
        return dense

    def _reassemble(self, template, part_by_key: dict, extra_shards=None):
        """Offset-keyed local shards -> global jax.Array with the
        template's sharding.

        Fast path: the stored global offsets match the current
        topology's shard positions exactly — each shard device_puts
        straight onto its device (no dense copy). Otherwise the shards
        are RE-MAPPED: the dense global array is assembled from every
        available shard (this process's file plus, via
        ``extra_shards``, sibling ranks' files on a shared filesystem)
        and re-placed under the template's sharding — a checkpoint
        saved on data=2/model=2 resumes on data=4/model=1 with the
        values bit-identical. Shards that cannot tile the full global
        shape (private-disk pod, missing sibling files) fail loudly
        instead of permuting data."""
        sharding = template.sharding
        gshape = tuple(template.shape)
        dev_idx = sharding.addressable_devices_indices_map(gshape)
        want = {self._index_key(ix) for ix in dev_idx.values()}

        def _extent(ix) -> tuple:
            return tuple(
                len(range(*sl.indices(g))) for sl, g in zip(ix, gshape)
            )

        # Same-topology fast path needs offsets AND extents to match: a
        # saving topology's shard can share offset (0, 0) with the new
        # topology's (every layout has a shard there) while holding a
        # different slice of the array.
        if want == set(part_by_key) and all(
            tuple(part_by_key[self._index_key(ix)].shape) == _extent(ix)
            for ix in dev_idx.values()
        ):
            arrays = [
                jax.device_put(part_by_key[self._index_key(ix)], d)
                for d, ix in dev_idx.items()
            ]
            return jax.make_array_from_single_device_arrays(
                gshape, sharding, arrays
            ), False
        merged = dict(part_by_key)
        for key, arr in (extra_shards() if extra_shards else {}).items():
            merged.setdefault(key, arr)
        dense = self._assemble_dense(gshape, merged)
        if dense is None:
            raise ValueError(
                f"Shard-saved leaf holds offsets {sorted(part_by_key)} but "
                f"the current topology needs {sorted(want)}, and the "
                "available shards (this process's file + any sibling "
                "p<rank>/ files) do not tile the full global shape "
                f"{gshape} — a topology re-map needs every saving rank's "
                "state file on a shared filesystem. Restore with the "
                "saving mesh/process topology, or clear the train_state "
                "dir to restart from the deploy checkpoint."
            )
        return jax.make_array_from_callback(
            gshape, sharding, lambda idx: dense[idx]
        ), True

    def restore(self, state):
        """Restore into the structure (and shardings) of ``state``
        (apply_fn/tx kept). Whole-saved leaves come back as host numpy;
        shard-saved leaves are reassembled onto this process's devices
        under the template leaf's sharding."""
        self.wait()
        with _spans.get_default().span(
            "checkpoint.restore", component="checkpoint",
        ):
            return self._restore(state)

    @staticmethod
    def _dir_meta(d: str) -> dict:
        import json

        try:
            with open(os.path.join(d, "meta.json")) as f:
                return dict(json.load(f))
        except (OSError, ValueError):
            return {}

    def _sibling_entries(self) -> dict:
        """Every CONSISTENT sibling rank's npz entries, merged (first
        sibling wins per key) — the shard pool a topology re-map draws
        from. Loaded lazily, once per restore.

        Consistency gate: a sibling is admitted only when its saved
        ``epochs_completed`` matches the reference meta (this process's
        own checkpoint when it has one, else the first sibling's). A
        rank that died before publishing its last rotation leaves a
        one-save-older file behind — tiling ITS shards next to the
        others' would silently assemble a parameter array mixed across
        two optimizer steps, exactly the torn state the loud offset
        refusal used to prevent. A stale sibling here means the re-map
        falls back to "cannot tile" and raises instead."""
        cached = getattr(self, "_sibling_cache", None)
        if cached is not None:
            return cached
        own = self._restore_candidates()
        ref_epochs = self._dir_meta(own[0]).get("epochs_completed") if own else None
        merged: dict[str, np.ndarray] = {}
        for d in self._sibling_candidate_dirs():
            sib_epochs = self._dir_meta(d).get("epochs_completed")
            if ref_epochs is None:
                # Growth restore (no own checkpoint): the first
                # readable sibling sets the reference generation.
                ref_epochs = sib_epochs
            if sib_epochs != ref_epochs:
                continue
            try:
                npz = np.load(os.path.join(d, "state.npz"))
            except (OSError, ValueError):
                continue
            for k in npz.files:
                merged.setdefault(k, npz[k])
        self._sibling_cache = merged
        return merged

    def _restore(self, state):
        self._sibling_cache = None
        candidates = self._restore_candidates()
        if not candidates:
            legacy = [
                d
                for d in self._rotation_dirs()
                if os.path.isdir(d) and not self._dir_is_torn(d)
            ]
            if legacy:
                raise RuntimeError(
                    f"Checkpoint dir(s) {legacy} exist but contain no "
                    "state.npz — an unreadable (pre-npz/orbax) format. "
                    "Delete them to restart from scratch, or restore with "
                    "the version that wrote them."
                )
            # Topology growth: this rank saved nothing, but sibling
            # ranks' files on the shared filesystem can rebuild the
            # full state (whole leaves from any sibling, shard-saved
            # leaves re-mapped below).
            if self._sibling_entries():
                restored = dict(self._sibling_entries())
                return self._restore_from(state, restored, source="siblings")
            raise FileNotFoundError(f"No train-state checkpoint under {self.dirpath}")
        npz = np.load(os.path.join(candidates[0], "state.npz"))
        restored = {k: npz[k] for k in npz.files}
        return self._restore_from(state, restored, source=candidates[0])

    def _restore_from(self, state, restored: dict, *, source: str):
        template = self._tree(state)
        treedef = jax.tree.structure(template)
        tleaves = jax.tree.leaves(template)

        def _mismatch(detail: str) -> KeyError:
            # The most common cause is a CONFIG change between runs — a
            # different DCT_OPTIMIZER restructures opt_state, so the
            # saved flat leaves no longer line up with this run's
            # template. Name that instead of a bare index; a silent
            # misaligned restore would train from garbage weights.
            return KeyError(
                f"Checkpoint {source} does not match this run's "
                f"TrainState: {detail}. Typically DCT_OPTIMIZER (or "
                "another state-shaping knob) changed since the "
                "checkpoint was written. Restore the original setting, "
                f"or clear {self.dirpath} to restart the trajectory."
            )

        # Count check BOTH directions: a template with FEWER leaves than
        # the checkpoint would otherwise restore silently with every flat
        # index shifted onto the wrong (often identically-shaped) array.
        saved_groups = {
            k.split("_s")[0] for k in restored if k and k[0].isdigit()
        }
        if len(saved_groups) != len(tleaves):
            raise _mismatch(
                f"{len(saved_groups)} leaf groups saved, "
                f"{len(tleaves)} expected"
            )
        def _parts_for(entries: dict, i: int) -> dict:
            prefix = f"{i}_s"
            return {
                # 0-d leaves have an empty offset suffix -> key ().
                tuple(
                    int(o) for o in k[len(prefix):].split("x")
                ) if k[len(prefix):] else (): v
                for k, v in entries.items()
                if k.startswith(prefix)
            }

        leaves = []
        remapped: list[int] = []
        for i, t in enumerate(tleaves):
            if str(i) in restored:
                whole = restored[str(i)]
                if tuple(whole.shape) != tuple(getattr(t, "shape", ())):
                    raise _mismatch(
                        f"leaf {i} has shape {tuple(whole.shape)} on disk "
                        f"but {tuple(getattr(t, 'shape', ()))} in the "
                        "template"
                    )
                leaves.append(whole)
                continue
            part_by_key = _parts_for(restored, i)
            if not part_by_key:
                raise _mismatch(f"no data for template leaf {i}")
            arr, was_remapped = self._reassemble(
                t, part_by_key,
                extra_shards=lambda i=i: _parts_for(
                    self._sibling_entries(), i
                ),
            )
            if was_remapped:
                remapped.append(i)
            leaves.append(arr)
        if remapped:
            # A different mesh topology adopted this trajectory: on the
            # record (docs/PARALLELISM.md §topology re-map), values
            # bit-identical by construction (pure data movement).
            saved_layout = self.load_layout()
            to_mesh = None
            for t in tleaves:
                sh = getattr(t, "sharding", None)
                if hasattr(sh, "mesh"):
                    to_mesh = {
                        str(k): int(v)
                        for k, v in dict(sh.mesh.shape).items()
                    }
                    break
            self.last_remap = {
                "leaves": len(remapped),
                "from_mesh": saved_layout.get("mesh"),
                "from_processes": saved_layout.get("process_count"),
                "to_mesh": to_mesh,
            }
            _events.get_default().emit(
                "shard", "shard.topology_remap",
                dir=source, **self.last_remap,
            )
        tree = jax.tree.unflatten(treedef, leaves)
        # Drop the sibling shard pool: it holds full copies of every
        # sibling's arrays and is only valid for THIS restore.
        self._sibling_cache = None
        lin = _lineage.get_default()
        if lin.enabled and source != "siblings":
            # The adopted trajectory becomes a training input: the next
            # checkpoint this run publishes gets a ``consumed`` edge to
            # the state it resumed from — lineage across preemptions.
            _lineage.add_run_input(lin.node(
                "checkpoint", path=os.path.join(source, "state.npz"),
                attrs={"tier": "resume", "restored": True},
            ))
        return state.replace(
            step=jax.numpy.asarray(tree["step"]),
            params=tree["params"],
            opt_state=tree["opt_state"],
            rng=jax.numpy.asarray(tree["rng"]),
        )
