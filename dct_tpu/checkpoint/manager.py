"""Checkpointing: best/last policy + full-state resume.

Two tiers, mirroring and extending the reference:

1. **Deploy tier** (`*.ckpt` single files) — the analog of Lightning's
   ``ModelCheckpoint(dirpath=data/models, filename="weather-best-{epoch:02d}-
   {val_loss:.2f}", save_top_k=1, monitor=val_loss, mode=min, save_last=True)``
   (jobs/train_lightning_ddp.py:103-110). Same directory layout, same
   filename convention, same ``last.ckpt`` fallback — so the training DAG's
   ``ls *.ckpt`` verification gate (dags/2_pytorch_training.py:81-91) and the
   deploy DAG's "first .ckpt in best_checkpoints" pick
   (dags/azure_manual_deploy.py:46-50) work unchanged. Format: flax msgpack
   of ``{"meta": {...}, "params": ...}`` — self-describing (input_dim,
   feature names, architecture) so serving never hardcodes ``input_dim=5``
   like the reference's score.py does (dags/azure_manual_deploy.py:109).

2. **Resume tier** (Orbax) — full TrainState (params + Adam moments + step +
   rng), which the reference cannot do at all (``fit()`` never gets a
   ckpt_path; jobs/train_lightning_ddp.py:143). Continuous training can
   therefore actually continue rather than restart from scratch.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
from flax import serialization


def _to_host(tree):
    return jax.tree.map(lambda a: np.asarray(jax.device_get(a)), tree)


def save_checkpoint(path: str, params: Any, meta: dict) -> str:
    """Serialize {meta, params} to a single msgpack file."""
    payload = {"meta": dict(meta), "params": _to_host(params)}
    data = serialization.msgpack_serialize(payload)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: no torn ckpt if a rank dies mid-write
    return path


def load_checkpoint(path: str) -> tuple[Any, dict]:
    """Returns (params, meta)."""
    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return payload["params"], dict(payload["meta"])


class BestLastCheckpointer:
    """save_top_k=1 on min val_loss, plus always-updated last.ckpt."""

    def __init__(
        self,
        dirpath: str,
        *,
        filename_template: str = "weather-best-{epoch:02d}-{val_loss:.2f}",
        monitor: str = "val_loss",
        mode: str = "min",
    ):
        self.dirpath = dirpath
        self.filename_template = filename_template
        self.monitor = monitor
        self.sign = 1.0 if mode == "min" else -1.0
        self.best_value: float | None = None
        self.best_model_path: str = ""
        os.makedirs(dirpath, exist_ok=True)

    @property
    def last_path(self) -> str:
        return os.path.join(self.dirpath, "last.ckpt")

    def update(self, *, epoch: int, metrics: dict, params: Any, meta: dict) -> bool:
        """Write last.ckpt; if monitor improved, replace the best file.
        Returns True when a new best was saved."""
        meta = {**meta, "epoch": int(epoch), **{k: float(v) for k, v in metrics.items()}}
        save_checkpoint(self.last_path, params, meta)

        value = float(metrics[self.monitor])
        improved = self.best_value is None or self.sign * value < self.sign * self.best_value
        if improved:
            name = self.filename_template.format(epoch=epoch, **metrics) + ".ckpt"
            new_path = os.path.join(self.dirpath, name)
            save_checkpoint(new_path, params, meta)
            if self.best_model_path and os.path.exists(self.best_model_path):
                if os.path.abspath(self.best_model_path) != os.path.abspath(new_path):
                    os.remove(self.best_model_path)
            self.best_value = value
            self.best_model_path = new_path
        return improved


class TrainStateCheckpointer:
    """Orbax-backed full train-state save/restore for true resume."""

    def __init__(self, dirpath: str):
        self.dirpath = os.path.abspath(dirpath)
        os.makedirs(self.dirpath, exist_ok=True)

    # Crash-safe directory rotation: a new checkpoint is fully written to
    # ``state.next`` before the live ``state`` is touched, so at every
    # instant at least one *complete* checkpoint exists (restore prefers
    # state > state.next > state.old). A plain force=True overwrite of the
    # single live dir would destroy the only resume point if the process
    # died mid-save — the exact preemption scenario resume exists for.
    _LIVE, _NEXT, _OLD = "state", "state.next", "state.old"

    def _dir(self, name: str) -> str:
        return os.path.join(self.dirpath, name)

    def _restore_candidates(self) -> list[str]:
        return [
            d
            for d in (self._dir(self._LIVE), self._dir(self._NEXT), self._dir(self._OLD))
            if os.path.isdir(d)
        ]

    @staticmethod
    def _tree(state) -> dict:
        return {
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "rng": state.rng,
        }

    def save(self, state) -> str:
        import orbax.checkpoint as ocp

        # Flatten to an index-keyed dict: optax opt_states contain
        # namedtuples that do not round-trip through generic tree
        # serialization; the target treedef at restore time supplies the
        # structure instead.
        leaves = jax.tree.leaves(_to_host(self._tree(state)))
        # primary_host=None -> every process writes its own (host-local)
        # checkpoint; the default primary-host-0 mode assumes a shared
        # filesystem and silently writes nothing on other ranks.
        ckptr = ocp.PyTreeCheckpointer(primary_host=None)
        import shutil

        next_dir = self._dir(self._NEXT)
        if os.path.isdir(next_dir):
            shutil.rmtree(next_dir)
        ckptr.save(next_dir, {str(i): leaf for i, leaf in enumerate(leaves)})

        live, old = self._dir(self._LIVE), self._dir(self._OLD)
        if os.path.isdir(old):
            shutil.rmtree(old)
        if os.path.isdir(live):
            os.rename(live, old)
        os.rename(next_dir, live)
        if os.path.isdir(old):
            shutil.rmtree(old)
        return live

    def exists(self) -> bool:
        return bool(self._restore_candidates())

    def restore(self, state):
        """Restore into the structure of ``state`` (apply_fn/tx kept)."""
        import orbax.checkpoint as ocp

        candidates = self._restore_candidates()
        if not candidates:
            raise FileNotFoundError(f"No train-state checkpoint under {self.dirpath}")
        ckptr = ocp.PyTreeCheckpointer(primary_host=None)
        restored = ckptr.restore(candidates[0])
        template = self._tree(state)
        treedef = jax.tree.structure(template)
        leaves = [restored[str(i)] for i in range(treedef.num_leaves)]
        tree = jax.tree.unflatten(treedef, leaves)
        return state.replace(
            step=jax.numpy.asarray(tree["step"]),
            params=tree["params"],
            opt_state=tree["opt_state"],
            rng=jax.numpy.asarray(tree["rng"]),
        )
