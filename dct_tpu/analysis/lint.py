"""dct-lint CLI: ``python -m dct_tpu.analysis.lint [paths...]``.

Exit codes (CI contract):

- ``0`` — no findings (baselined debt and stale-baseline notes do not
  fail the build; stale entries are printed so they get pruned).
- ``1`` — at least one finding (including baseline-hygiene: an entry
  with no written justification).
- ``2`` — usage or internal error (unknown rule id, unreadable
  baseline, ...).

Examples::

    python -m dct_tpu.analysis.lint dct_tpu/
    python -m dct_tpu.analysis.lint dct_tpu jobs dags scripts bench.py
    python -m dct_tpu.analysis.lint --format json dct_tpu/ | jq .
    python -m dct_tpu.analysis.lint --select env-registry,event-names
    python -m dct_tpu.analysis.lint --write-baseline   # grandfather, then justify
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dct_tpu.analysis import core


def _parse_ids(raw: str | None, known: set[str]) -> set[str] | None:
    if raw is None:
        return None
    ids = {s.strip() for s in raw.split(",") if s.strip()}
    unknown = ids - known
    if unknown:
        raise SystemExit(
            f"dct-lint: unknown rule id(s): {', '.join(sorted(unknown))}; "
            f"known: {', '.join(sorted(known))}"
        )
    return ids


def _render_text(report: core.Report, *, baseline_path: str | None) -> str:
    lines: list[str] = []
    for f in report.findings:
        loc = f"{f.path}:{f.line}" if f.line else f.path
        lines.append(f"{loc}: [{f.rule}] {f.message}")
        if f.snippet:
            lines.append(f"    {f.snippet}")
    if report.baselined:
        lines.append(
            f"-- {len(report.baselined)} finding(s) suppressed by the "
            f"baseline ({baseline_path})"
        )
    for e in report.stale_baseline:
        lines.append(
            f"-- stale baseline entry {e.fingerprint} ({e.rule} @ {e.path}):"
            " no longer matches any finding — prune it"
        )
    n = len(report.findings)
    lines.append(
        f"dct-lint: {report.checked_files} file(s), "
        f"{len(report.active_rules)} rule(s), "
        + ("clean" if n == 0 else f"{n} finding(s)")
    )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dct_tpu.analysis.lint",
        description=(
            "Project-native static analysis: SPMD and continuous-"
            "training invariants (docs/ANALYSIS.md)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: the dct_tpu package)",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="repo root for cross-file rules (default: auto-detected "
        "as the directory containing the dct_tpu package)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: <root>/.dct-lint-baseline.json "
        "when it exists)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline file (show the full finding set)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write all current findings into the baseline file with "
        "TODO justifications (each MUST then be justified by hand — "
        "an unjustified entry is itself a finding), and exit 0",
    )
    parser.add_argument(
        "--select", default=None, help="comma-separated rule ids to run"
    )
    parser.add_argument(
        "--ignore", default=None, help="comma-separated rule ids to skip"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    rules = core.all_rules()
    if args.list_rules:
        for rid, rule in sorted(rules.items()):
            print(f"{rid}: {rule.name}")
            print(f"    {rule.doc}")
        return 0

    root = os.path.abspath(args.root or core.default_root())
    paths = args.paths or [os.path.join(root, "dct_tpu")]
    try:
        select = _parse_ids(args.select, set(rules))
        ignore = _parse_ids(args.ignore, set(rules))
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(
        root, ".dct-lint-baseline.json"
    )
    baseline: core.Baseline | None = None
    if not args.no_baseline and not args.write_baseline and os.path.exists(
        baseline_path
    ):
        try:
            baseline = core.Baseline.load(baseline_path)
        except (OSError, ValueError) as e:
            print(f"dct-lint: unreadable baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    try:
        report = core.analyze(
            paths, root=root, select=select, ignore=ignore, baseline=baseline
        )
    except OSError as e:
        print(f"dct-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        previous = None
        if os.path.exists(baseline_path):
            try:
                previous = core.Baseline.load(baseline_path)
            except (OSError, ValueError):
                previous = None  # unreadable: regenerate from scratch
        core.Baseline.from_findings(
            report.findings, previous=previous
        ).save(baseline_path)
        print(
            f"dct-lint: wrote {len(report.findings)} entr"
            f"{'y' if len(report.findings) == 1 else 'ies'} to "
            f"{baseline_path} — now REPLACE every TODO justification "
            "with the real reason (an unjustified entry fails the lint)"
        )
        return 0

    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(_render_text(report, baseline_path=baseline_path))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
