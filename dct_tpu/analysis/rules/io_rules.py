"""I/O discipline rules: rank-0-only writes, atomic publishes, and
gathered publishes.

``rank0-io`` — the platform's core SPMD contract (inherited from the
reference's DDP design): in code that runs on every rank, shared
filesystem/tracking artifacts are written by the coordinator only. N
ranks racing one ``best.ckpt`` is a torn checkpoint at pod scale and a
passing test at world_size=1, which is exactly why a machine checks it.

``atomic-publish`` — anything published into a checkpoint / deploy
package / tracking registry path must be written to a tmp-suffixed
sibling and ``os.replace``d into place (the PR 3 crash-safety
convention): a reader (or a preemption) must never observe a
half-written file where a complete one is expected.

``gather-on-publish`` — modules under deploy/ and serving/ that read a
TrainState's ``.params`` must route them through the partition rules'
gather fns (``gather_tree``/``gather_leaf``/``to_host``): under a
sharded mesh layout a raw ``np.asarray``/``device_get`` of a
cross-process leaf fails — or worse, one shard's bytes ship as the
model. Sharded arrays must never leak into a package.
"""

from __future__ import annotations

import ast
import re

from dct_tpu.analysis.core import Finding, Project, Rule, register
from dct_tpu.analysis.rules._helpers import (
    enclosing_function,
    func_repr,
    iter_calls,
    open_mode,
    open_target,
    unparse,
    with_open_bindings,
)

#: A module participates in SPMD (every rank executes it) when it
#: touches the process topology. Modules that never ask "which rank am
#: I" are assumed single-process (orchestrator/DAG side).
_MULTI_RANK_RE = re.compile(
    r"jax\.process_index|jax\.process_count|multihost_utils|is_coordinator"
)

#: An ``if`` test that gates on the coordinator/rank-0 identity.
_GUARD_RE = re.compile(
    r"coordinator|process_index\(\)\s*==\s*0|process_id\s*==\s*0"
    r"|rank\s*==\s*0"
)

#: The inverted spelling: ``if rank != 0: ... else: <write>``.
_INV_GUARD_RE = re.compile(
    r"process_index\(\)\s*!=\s*0|process_id\s*!=\s*0|rank\s*!=\s*0"
)

#: Callees that create/replace filesystem state.
_WRITE_FUNCS = {
    "os.replace",
    "os.rename",
    "shutil.copy",
    "shutil.copy2",
    "shutil.copyfile",
    "shutil.copytree",
    "shutil.move",
    "np.savez",
    "numpy.savez",
    "np.save",
    "numpy.save",
}

#: Project publish APIs whose *call* is the artifact write.
_PUBLISH_CALLS = {"save_checkpoint", "write_train_metrics_prom"}
_PUBLISH_ATTRS = {"log_artifact"}


def _is_write_sink(call: ast.Call) -> str | None:
    """A human-readable sink label, or None when the call writes nothing."""
    mode = open_mode(call)
    if mode is not None and any(c in mode for c in "wax+"):
        return f"open(..., {mode!r})"
    name = func_repr(call)
    if name in _WRITE_FUNCS:
        return name
    tail = name.rsplit(".", 1)[-1]
    if tail in _PUBLISH_CALLS or tail in _PUBLISH_ATTRS:
        return name
    return None


@register
class Rank0IoRule(Rule):
    id = "rank0-io"
    name = "rank-0-only artifact writes in multi-rank modules"
    doc = (
        "In modules that execute on every SPMD rank, filesystem and "
        "tracking writes must sit under a coordinator gate "
        "(`if self.coordinator:` / `is_coordinator()` / "
        "`jax.process_index() == 0`). Per-process-by-design writers "
        "(e.g. the resume checkpoint tier) mark the whole def/class "
        "with `# dct: noqa[rank0-io] — <why>`."
    )

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for ctx in project.contexts:
            if ctx.tree is None or not _MULTI_RANK_RE.search(ctx.source):
                continue
            for call in iter_calls(ctx.tree):
                sink = _is_write_sink(call)
                if sink is None:
                    continue
                if self._guarded(ctx, call):
                    continue
                out.append(
                    ctx.finding(
                        self.id,
                        call,
                        f"unguarded {sink} in a multi-rank module: every "
                        "rank executes this — gate it on the coordinator "
                        "(`if self.coordinator:` / `jax.process_index() "
                        "== 0`), or mark the enclosing def/class "
                        "`# dct: noqa[rank0-io] — <why per-process "
                        "writes are safe here>`",
                    )
                )
        return out

    @classmethod
    def _guarded(cls, ctx, call: ast.Call) -> bool:
        """Branch-aware: the write must sit in the branch the guard
        actually selects for the coordinator — a write in the `else` of
        `if coordinator:`, or in the body of `if not coordinator:`, is
        exactly the bug this rule exists to catch."""
        parents = ctx.parents()
        child: ast.AST = call
        anc = parents.get(call)
        while anc is not None:
            if isinstance(anc, ast.If):
                branch = cls._guard_branch(anc.test)
                if branch == "body" and cls._in(child, anc.body):
                    return True
                if branch == "orelse" and cls._in(child, anc.orelse):
                    return True
            elif isinstance(anc, ast.IfExp):
                branch = cls._guard_branch(anc.test)
                if branch == "body" and child is anc.body:
                    return True
                if branch == "orelse" and child is anc.orelse:
                    return True
            child, anc = anc, parents.get(anc)
        return False

    @staticmethod
    def _in(node: ast.AST, stmts: list) -> bool:
        return any(node is s for s in stmts)

    @classmethod
    def _guard_branch(cls, test: ast.AST) -> str | None:
        """Which branch of ``if test:`` is coordinator-only: 'body',
        'orelse', or None. Negation flips the branch; a guard term
        buried under a non-trivial `not` (`a and not coordinator`) is
        conservatively no guard at all."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = cls._guard_branch(test.operand)
            if inner == "body":
                return "orelse"
            if inner == "orelse":
                return "body"
            return None
        src = unparse(test)
        if _INV_GUARD_RE.search(src):
            return "orelse"
        if _GUARD_RE.search(src) and "not " not in src:
            return "body"
        return None


#: Layers whose files publish into shared checkpoint / deploy-package /
#: tracking-registry paths; every file creation there must be atomic.
_PUBLISH_LAYERS = (
    "dct_tpu/checkpoint/",
    "dct_tpu/deploy/",
    "dct_tpu/serving/",
    "dct_tpu/tracking/",
    "dct_tpu/evaluation/",
    "dct_tpu/observability/",
    "dct_tpu/stream/",
)

#: Destination-bearing copy/move callees: (callee -> dest arg index).
_COPY_FUNCS = {
    "shutil.copy": 1,
    "shutil.copy2": 1,
    "shutil.copyfile": 1,
    "shutil.copytree": 1,
    "shutil.move": 1,
}
_SAVE_FUNCS = {"np.savez": 0, "numpy.savez": 0, "np.save": 0, "numpy.save": 0}


def _tmp_flavored(expr_src: str) -> bool:
    low = expr_src.lower()
    return "tmp" in low or "temp" in low


@register
class AtomicPublishRule(Rule):
    id = "atomic-publish"
    name = "tmp-then-os.replace publishes in the publish layers"
    doc = (
        "In the checkpoint/deploy/serving/tracking/evaluation/"
        "observability layers, creating a file in place "
        "(`open(final, 'w')`, `shutil.copy*(…, final)`, `np.savez(final)`)"
        " can be torn by a crash mid-write; write a tmp-suffixed sibling "
        "and `os.replace` it into place instead. Append-mode logs are "
        "exempt (appends are incremental by contract)."
    )

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for ctx in project.contexts:
            if ctx.tree is None or not ctx.relpath.startswith(_PUBLISH_LAYERS):
                continue
            for call in iter_calls(ctx.tree):
                target, sink = self._non_tmp_target(ctx, call)
                if target is None:
                    continue
                out.append(
                    ctx.finding(
                        self.id,
                        call,
                        f"non-atomic publish: {sink} creates "
                        f"`{target}` in place — write `{target}.tmp.<pid>`"
                        " and `os.replace` it into the final path so a "
                        "crash mid-write can never publish a torn file",
                    )
                )
        return out

    @staticmethod
    def _non_tmp_target(ctx, call: ast.Call) -> tuple[str | None, str]:
        """(offending target source, sink label); (None, '') when fine."""
        mode = open_mode(call)
        if mode is not None:
            # Pure append is incremental by contract; any 'w'/'x' create
            # must go through a tmp sibling.
            if not any(c in mode for c in "wx"):
                return None, ""
            node = open_target(call)
            if node is None:
                return None, ""
            src = unparse(node)
            return (None, "") if _tmp_flavored(src) else (src, f"open(..., {mode!r})")
        name = func_repr(call)
        if name in _COPY_FUNCS:
            idx = _COPY_FUNCS[name]
            if len(call.args) > idx:
                src = unparse(call.args[idx])
                return (None, "") if _tmp_flavored(src) else (src, name)
            return None, ""
        if name in _SAVE_FUNCS:
            if not call.args:
                return None, ""
            node = call.args[0]
            # See through a handle bound by `with open(tmp) as f`.
            if isinstance(node, ast.Name):
                fn = enclosing_function(ctx, call)
                if fn is not None:
                    bound = with_open_bindings(fn).get(node.id)
                    if bound is not None:
                        node = bound
            src = unparse(node)
            return (None, "") if _tmp_flavored(src) else (src, name)
        return None, ""


#: Layers whose modules build/ship serving artifacts from model state:
#: a TrainState read there is a publish in the making.
_GATHER_LAYERS = ("dct_tpu/deploy/", "dct_tpu/serving/")

#: The partition rules' gather surface (sharding_rules +
#: checkpoint.manager.to_host): a ``.params`` read flowing through any
#: of these produces dense host arrays whatever the mesh layout.
_GATHER_FNS = {
    "gather_tree",
    "gather_leaf",
    "to_host",
    "make_shard_and_gather_fns",
}


@register
class GatherOnPublishRule(Rule):
    id = "gather-on-publish"
    name = "TrainState params gathered before packaging/serving"
    doc = (
        "In modules under deploy/ and serving/, reading a TrainState's "
        "`.params` must go through the partition rules' gather fns "
        "(`gather_tree(state.params)` / `to_host(...)`): under a "
        "sharded mesh layout a raw read ships one shard's bytes as the "
        "model (or fails on a cross-process leaf). Mark deliberate "
        "exceptions with `# dct: noqa[gather-on-publish] — <why the "
        "leaves are host-dense here>`."
    )

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for ctx in project.contexts:
            if ctx.tree is None or not ctx.relpath.startswith(_GATHER_LAYERS):
                continue
            parents = ctx.parents()
            for node in ast.walk(ctx.tree):
                if not (
                    isinstance(node, ast.Attribute)
                    and node.attr == "params"
                    and isinstance(node.ctx, ast.Load)
                ):
                    continue
                if self._gathered(node, parents):
                    continue
                out.append(
                    ctx.finding(
                        self.id,
                        node,
                        f"`{unparse(node)}` reads TrainState params in a "
                        "publish layer without the gather fns — a sharded "
                        "layout would leak shard-local (or unreadable "
                        "cross-process) arrays into the package; wrap it "
                        "in `gather_tree(...)` / `to_host(...)`, or mark "
                        "`# dct: noqa[gather-on-publish] — <why dense>`",
                    )
                )
        return out

    @staticmethod
    def _gathered(node: ast.AST, parents: dict) -> bool:
        """True when the read sits inside a call to a gather fn (any
        ancestor call whose callee tail is in :data:`_GATHER_FNS`)."""
        anc = parents.get(node)
        while anc is not None:
            if isinstance(anc, ast.Call):
                tail = func_repr(anc).rsplit(".", 1)[-1]
                if tail in _GATHER_FNS:
                    return True
            anc = parents.get(anc)
        return False
