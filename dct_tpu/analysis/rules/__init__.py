"""Built-in dct-lint rules. Importing this package registers them.

One module per concern:

- :mod:`io_rules` — ``rank0-io`` (coordinator-gated writes in SPMD
  modules), ``atomic-publish`` (tmp-then-``os.replace`` into
  checkpoint/package/registry paths), and ``gather-on-publish``
  (TrainState params gathered dense before packaging/serving).
- :mod:`purity_rules` — ``span-sync`` (no blocking host sync inside the
  trainer's marked pipelined-dispatch region) and ``trace-purity`` (no
  impure calls inside ``jit``/``shard_map``/``pallas_call`` bodies).
- :mod:`registry_rules` — ``env-registry`` (``DCT_*`` declared in
  ``config.py`` ⇄ documented in ``.env.example`` ⇄ actually read) and
  ``event-names`` (``EventLog.emit`` sites vs the
  ``docs/OBSERVABILITY.md`` event table).
- :mod:`lineage_rules` — ``lineage-publish`` (``os.replace``
  artifact-publish sites in the data/ETL, checkpoint and deploy
  layers record provenance in the lineage ledger).
- :mod:`metric_rules` — ``metric-docs`` (``dct_*`` metric families
  rendered in ``dct_tpu/`` vs the ``docs/OBSERVABILITY.md`` metric
  table).

To add a rule: subclass :class:`dct_tpu.analysis.core.Rule`, decorate
with :func:`dct_tpu.analysis.core.register`, import the module here,
and pair it with good/bad fixtures in ``tests/test_analysis.py``
(docs/ANALYSIS.md walks through it).
"""

from dct_tpu.analysis.rules import (  # noqa: F401 — imported to register
    io_rules,
    lineage_rules,
    metric_rules,
    purity_rules,
    registry_rules,
)
