"""Execution-semantics rules: pipelined-loop sync and trace purity.

``span-sync`` — the PR 5 dispatch-gap work made ``Trainer.fit``'s scan
path a one-span-in-flight pipeline: everything between dispatching span
*e+1* and consuming span *e* must not join device results, or the
overlap the mode buys silently collapses back to serial. The no-sync
window is delimited in source with ``# dct: begin-no-host-sync`` /
``# dct: end-no-host-sync`` markers; inside it the rule flags every
construct that blocks on the device (``jax.device_get``,
``.block_until_ready()``, ``float()``/``int()``/``.item()`` on arrays,
``np.asarray``-style host materialization).

``trace-purity`` — bodies traced by ``jax.jit`` / ``shard_map`` /
``pallas_call`` execute once at trace time, then replay as compiled
XLA: host side effects inside them (wall-clock reads, ``np.random``,
``print``, env reads, file I/O) either bake a stale value into the
program or silently vanish from steady-state steps. Tracedness is
computed transitively over same-module calls (a helper called from a
jitted function is traced too).
"""

from __future__ import annotations

import ast
import re

from dct_tpu.analysis.core import Finding, Project, Rule, register
from dct_tpu.analysis.rules._helpers import func_repr, iter_calls, unparse

_SYNC_FUNCS = {
    "jax.device_get",
    "jax.block_until_ready",
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
}
_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}


@register
class SpanSyncRule(Rule):
    id = "span-sync"
    name = "no blocking host sync in the pipelined dispatch region"
    doc = (
        "Between `# dct: begin-no-host-sync` and `# dct: "
        "end-no-host-sync` (the trainer's dispatch-to-swap window), "
        "nothing may join device results: no `jax.device_get`, "
        "`.block_until_ready()`, `.item()`, `float()`/`int()` on device "
        "values, or `np.asarray`/`np.array` materialization. The join "
        "belongs one span later, in `_consume_span`."
    )

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for ctx in project.contexts:
            if ctx.tree is None:
                continue
            regions = ctx.regions()
            if not regions:
                continue

            def in_region(lineno: int) -> bool:
                return any(lo <= lineno <= hi for lo, hi in regions)

            for call in iter_calls(ctx.tree):
                if not in_region(call.lineno):
                    continue
                label = self._sync_label(call)
                if label is None:
                    continue
                out.append(
                    ctx.finding(
                        self.id,
                        call,
                        f"blocking host sync `{label}` inside the "
                        "no-host-sync region: this joins the in-flight "
                        "span and serializes the pipelined loop — move "
                        "it into the consume path (after the region), "
                        "or use copy_to_host_async for a non-blocking "
                        "D2H start",
                    )
                )
        return out

    @staticmethod
    def _sync_label(call: ast.Call) -> str | None:
        name = func_repr(call)
        if name in _SYNC_FUNCS:
            return name
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _SYNC_ATTRS
        ):
            return f".{call.func.attr}()"
        if name in ("float", "int") and call.args and not all(
            isinstance(a, ast.Constant) for a in call.args
        ):
            return f"{name}(...)"
        return None


#: Decorators / higher-order callees whose function argument is traced.
_TRACE_CALL_RE = re.compile(
    r"(?:^|\.)(?:jit|pjit|shard_map|pallas_call|checkpoint|remat)$"
)

#: Impure callee prefixes (host state readers / side effects).
_IMPURE_PREFIXES = (
    "time.",
    "np.random.",
    "numpy.random.",
    "random.",
    "datetime.",
    "uuid.",
    "os.environ.",
)
_IMPURE_EXACT = {"os.getenv", "print", "open", "input"}


@register
class TracePurityRule(Rule):
    id = "trace-purity"
    name = "no impure calls inside jit/shard_map-traced bodies"
    doc = (
        "Functions traced by `jax.jit` / `shard_map` / `pallas_call` "
        "(directly, via a factory's `return jax.jit(inner)`, or "
        "transitively through same-module calls) must be pure: "
        "`time.time`, `np.random`, `print`, env reads, `open` etc. "
        "run once at trace time — the compiled program replays a stale "
        "value (or nothing). Use `jax.random` for randomness and "
        "`jax.debug.print`/`io_callback` for effects."
    )

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for ctx in project.contexts:
            if ctx.tree is None:
                continue
            traced = self._traced_functions(ctx)
            for fn in traced:
                out.extend(self._scan_body(ctx, fn, traced))
        return out

    # -- tracedness ------------------------------------------------------
    @staticmethod
    def _traced_functions(ctx) -> list[ast.AST]:
        defs_by_name: dict[str, list[ast.AST]] = {}
        all_defs: list[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
                all_defs.append(node)

        traced: set[ast.AST] = set()
        # Seed 1: decorated defs.
        for fn in all_defs:
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _TRACE_CALL_RE.search(unparse(target)):
                    traced.add(fn)
        # Seed 2: functions passed (by name) to a tracing callee.
        for call in iter_calls(ctx.tree):
            if not _TRACE_CALL_RE.search(func_repr(call)):
                continue
            for arg in call.args[:1]:
                if isinstance(arg, ast.Name):
                    traced.update(defs_by_name.get(arg.id, ()))

        # Closure: nested defs of traced functions, and same-module
        # functions a traced body calls, are traced too.
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                for node in ast.walk(fn):
                    if (
                        isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and node is not fn
                        and node not in traced
                    ):
                        traced.add(node)
                        changed = True
                    if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name
                    ):
                        for callee in defs_by_name.get(node.func.id, ()):
                            if callee not in traced:
                                traced.add(callee)
                                changed = True
        return sorted(traced, key=lambda f: f.lineno)

    def _scan_body(self, ctx, fn, traced) -> list[Finding]:
        out: list[Finding] = []
        nested = {
            n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }

        def owned(node: ast.AST) -> bool:
            # Attribute findings to the innermost traced def, so one
            # violation reports once.
            for anc in ctx.ancestors(node):
                if anc is fn:
                    return True
                if anc in nested:
                    return False
            return False

        for node in ast.walk(fn):
            if not owned(node) and node is not fn:
                continue
            label = None
            if isinstance(node, ast.Call):
                name = func_repr(node)
                if name in _IMPURE_EXACT or name.startswith(_IMPURE_PREFIXES):
                    label = name
            elif isinstance(node, ast.Subscript) and unparse(node.value) == (
                "os.environ"
            ):
                label = "os.environ[...]"
            if label is not None:
                out.append(
                    ctx.finding(
                        self.id,
                        node,
                        f"impure call `{label}` inside traced function "
                        f"`{fn.name}`: it executes at trace time and its "
                        "value/effect is baked into (or dropped from) "
                        "the compiled program — hoist it to the host "
                        "loop, or use jax.random / jax.debug.print / "
                        "io_callback",
                    )
                )
        return out
