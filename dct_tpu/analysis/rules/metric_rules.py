"""``metric-docs`` — every ``dct_*`` metric family is documented.

The metric plane is an operator API exactly like the event log: a
``dct_*`` family rendered on ``/metrics`` (or into the textfile dump)
that ``docs/OBSERVABILITY.md``'s metric table does not name is a
series no dashboard, alert or sentinel will ever chart. Mirror of the
``event-names`` rule, for the other telemetry schema.

What counts as "rendering a family" (statically provable sites only):

- registry definition calls — ``*.counter("dct_x", ...)`` /
  ``*.gauge(...)`` / ``*.histogram(...)`` (plus the serving tier's
  local ``hist(...)`` binding of the same method);
- direct :class:`MetricFamily` construction (the merge/SLO layers);
- hand-rendered exposition text — any ``# TYPE <family> ...`` literal
  (the lineage plane renders its families this way).

Dynamic names are invisible by design — the rule checks what it can
prove; the docs table remains the review checklist for the rest.
"""

from __future__ import annotations

import ast
import re

from dct_tpu.analysis.core import Finding, Project, Rule, register
from dct_tpu.analysis.rules._helpers import func_repr

_DOCS_RELPATH = "docs/OBSERVABILITY.md"
_METRIC_NAME_RE = re.compile(r"^dct_[a-z0-9_]+$")
_TYPE_LINE_RE = re.compile(r"#\s*TYPE\s+(dct_[a-z0-9_]+)\s")
_METRIC_TABLE_HEADER_RE = re.compile(r"^\|\s*metric\s*\|", re.I)
_BACKTICK_RE = re.compile(r"`([^`]+)`")

#: Callee tails that define a metric family with their first argument.
_DEF_TAILS = ("counter", "gauge", "histogram", "hist", "MetricFamily")


def parse_metric_table(markdown: str) -> set[str] | None:
    """The ``| metric | ... |`` table -> documented family names (every
    backticked ``dct_*`` token in the first cell). None when absent."""
    lines = markdown.splitlines()
    for i, line in enumerate(lines):
        if not _METRIC_TABLE_HEADER_RE.match(line.strip()):
            continue
        names: set[str] = set()
        for row in lines[i + 1 :]:
            row = row.strip()
            if not row.startswith("|"):
                break
            cells = [c.strip() for c in row.strip("|").split("|")]
            if not cells or set(cells[0]) <= {"-", " ", ":"}:
                continue
            for token in _BACKTICK_RE.findall(cells[0]):
                if _METRIC_NAME_RE.match(token):
                    names.add(token)
        return names
    return None


def collect_metric_defs(ctx) -> dict[str, int]:
    """``dct_*`` families this file provably renders -> first line."""
    out: dict[str, int] = {}
    if ctx.tree is None:
        return out
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            tail = func_repr(node).rsplit(".", 1)[-1]
            if tail in _DEF_TAILS and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    if _METRIC_NAME_RE.match(a.value):
                        out.setdefault(a.value, node.lineno)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in _TYPE_LINE_RE.finditer(node.value):
                out.setdefault(m.group(1), node.lineno)
    return out


@register
class MetricDocsRule(Rule):
    id = "metric-docs"
    name = "dct_* metric families are documented"
    doc = (
        "Every `dct_*` metric family rendered anywhere in `dct_tpu/` "
        "(registry counter/gauge/histogram definitions, MetricFamily "
        "constructions, hand-rendered `# TYPE` exposition lines) must "
        "appear in docs/OBSERVABILITY.md's metric table. An "
        "undocumented family is a series no operator query will find — "
        "document it (one table row) in the same change that adds it."
    )

    def check(self, project: Project) -> list[Finding]:
        markdown = project.read(_DOCS_RELPATH)
        table = parse_metric_table(markdown) if markdown else None
        if table is None:
            table = set()
        out: list[Finding] = []
        for ctx in project.contexts:
            if not ctx.relpath.startswith("dct_tpu/"):
                continue
            for name, lineno in sorted(collect_metric_defs(ctx).items()):
                if name not in table:
                    out.append(
                        Finding(
                            rule=self.id,
                            path=ctx.relpath,
                            line=lineno,
                            message=(
                                f"metric family `{name}` is not in "
                                f"{_DOCS_RELPATH}'s metric table — add "
                                "a row documenting it (the metric "
                                "plane is an operator API)"
                            ),
                            snippet=ctx.line(lineno).strip(),
                        )
                    )
        return out
