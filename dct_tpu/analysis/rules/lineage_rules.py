"""Provenance discipline: artifact-publish sites record lineage.

``lineage-publish`` — the lineage plane (docs/OBSERVABILITY.md §8) is
only as complete as its emit hooks: a tmp+``os.replace`` publish in the
data/ETL, checkpoint or deploy layers that never touches the lineage
ledger is an artifact the ``trace``/``audit`` CLIs cannot see — a hole
in the provenance graph that looks exactly like tampering. Any module
in those layers that publishes via ``os.replace`` must reference the
lineage module (import it and record a node/edge near the publish, or
delegate to a helper in the same module that does). State files that
are deliberately NOT artifacts (e.g. endpoint traffic-state
bookkeeping whose lineage is recorded by the orchestrator that drives
it) carry a reviewed ``# dct: noqa[lineage-publish]``.
"""

from __future__ import annotations

import ast

from dct_tpu.analysis.core import Finding, Project, Rule, register
from dct_tpu.analysis.rules._helpers import func_repr, iter_calls

#: Layers whose ``os.replace`` publishes hand artifacts between stages
#: of the continuous cycle — exactly the hand-offs the ledger records.
_LINEAGE_LAYERS = (
    "dct_tpu/data/",
    "dct_tpu/etl/",
    "dct_tpu/checkpoint/",
    "dct_tpu/deploy/",
    "dct_tpu/stream/",
)


def _references_lineage(tree: ast.AST) -> bool:
    """True when the module imports or names the lineage module
    anywhere (top-level or lazy in-function import, aliased or not)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if "lineage" in (node.module or ""):
                return True
            if any("lineage" in a.name for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("lineage" in a.name for a in node.names):
                return True
        elif isinstance(node, ast.Attribute) and "lineage" in node.attr:
            return True
        elif isinstance(node, ast.Name) and "lineage" in node.id:
            return True
    return False


@register
class LineagePublishRule(Rule):
    id = "lineage-publish"
    name = "os.replace publish sites record lineage"
    doc = (
        "Modules under data/, etl/, checkpoint/ and deploy/ that "
        "publish artifacts via tmp+`os.replace` must record them in "
        "the lineage ledger (`dct_tpu.observability.lineage`): an "
        "unrecorded publish is invisible to `lineage trace` and reads "
        "as an integrity hole in `lineage audit`. Record a node/edge "
        "at (or on the orchestrating path of) the publish, or mark a "
        "deliberate non-artifact state file with "
        "`# dct: noqa[lineage-publish]`."
    )

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for ctx in project.contexts:
            if ctx.tree is None or not ctx.relpath.startswith(
                _LINEAGE_LAYERS
            ):
                continue
            if _references_lineage(ctx.tree):
                continue
            for call in iter_calls(ctx.tree):
                if func_repr(call) != "os.replace":
                    continue
                out.append(
                    ctx.finding(
                        self.id,
                        call,
                        "artifact published via os.replace but the "
                        "module never records lineage — import "
                        "dct_tpu.observability.lineage and record a "
                        "node/edge for the published artifact (or "
                        "noqa a deliberate non-artifact state file)",
                    )
                )
        return out
