"""Registry-consistency rules: the DCT_* env contract and event names.

``env-registry`` — the platform's ~160-knob ``DCT_*`` environment
surface drifts three ways: code reads a key nobody documented, the
documented ``.env.example`` names a key nobody reads, or the declared
registry carries a dead entry. The single source of truth is
``ENV_REGISTRY`` in ``dct_tpu/config.py``; this rule holds all three
surfaces equal. The scan is repo-wide (``dct_tpu``/``jobs``/``dags``/
``scripts``/``bench.py``, tests excluded) regardless of which paths the
CLI was pointed at, so a partial lint cannot mistake a bench-only knob
for a dead one.

``event-names`` — ``EventLog.emit(component, event, ...)`` sites must
use (component, event) pairs documented in ``docs/OBSERVABILITY.md``'s
event table: the event log is an operator API, and an undocumented
name is a record no dashboard/inspector query will ever find.
Statically-unknowable names (f-strings, variables) are skipped — the
rule checks what it can prove, and the docs table remains the review
checklist for the rest.
"""

from __future__ import annotations

import ast
import re

from dct_tpu.analysis.core import Finding, Project, Rule, register
from dct_tpu.analysis.rules._helpers import (
    func_repr,
    iter_calls,
    string_candidates,
    unparse,
)

_ENV_TOKEN_RE = re.compile(r"DCT_[A-Z0-9_]+")


def _env_mentions(text: str) -> dict[str, int]:
    """DCT_* names mentioned in free text -> first line number.
    Wildcard mentions (``DCT_BENCH_*``, trailing underscore) are not
    names and are skipped."""
    out: dict[str, int] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        for m in _ENV_TOKEN_RE.finditer(line):
            token = m.group(0)
            follow = line[m.end() : m.end() + 1]
            if token.endswith("_") or follow == "*":
                continue
            out.setdefault(token, i)
    return out


def _is_env_receiver(recv_src: str) -> bool:
    return (
        "environ" in recv_src
        or recv_src in ("env", "os")
        or recv_src.endswith(".env")
        or recv_src.endswith("_env")
    )


def collect_env_uses(ctx) -> dict[str, int]:
    """DCT_* keys this file provably touches -> first line number.

    Catches: ``_env("DCT_X", ...)``-style helper calls (any callee whose
    name mentions ``env``), ``os.environ``/``env`` ``.get/.pop/
    .setdefault``/``os.getenv`` with a literal key, subscript reads and
    writes on env-like receivers, ``NAME = "DCT_X"`` named-key
    constants, and ``DCT_X=...`` keyword arguments (the launchers build
    child envs that way). Dynamic keys are invisible — by design: the
    registry governs the *named* contract.
    """
    uses: dict[str, int] = {}
    if ctx.tree is None:
        return uses

    def note(value, lineno: int) -> None:
        if isinstance(value, str) and _ENV_TOKEN_RE.fullmatch(value):
            uses.setdefault(value, lineno)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            fname = func_repr(node)
            tail = fname.rsplit(".", 1)[-1]
            if ("env" in tail.lower() or tail == "getenv") and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant):
                    note(a.value, node.lineno)
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "get",
                "pop",
                "setdefault",
            ):
                if _is_env_receiver(unparse(node.func.value)):
                    for a in node.args[:1]:
                        if isinstance(a, ast.Constant):
                            note(a.value, node.lineno)
            for kw in node.keywords:
                if kw.arg and _ENV_TOKEN_RE.fullmatch(kw.arg):
                    uses.setdefault(kw.arg, node.lineno)
        elif isinstance(node, ast.Subscript):
            if _is_env_receiver(unparse(node.value)) and isinstance(
                node.slice, ast.Constant
            ):
                note(node.slice.value, node.lineno)
        elif isinstance(node, ast.Assign):
            if (
                len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
            ):
                note(node.value.value, node.lineno)
    return uses


def parse_env_registry(ctx) -> dict[str, int] | None:
    """``ENV_REGISTRY`` keys -> declaration line from config.py's AST
    (statically — the analyzer never imports the code it checks).
    None when the dict is absent."""
    if ctx is None or ctx.tree is None:
        return None
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "ENV_REGISTRY"
            and isinstance(node.value, ast.Dict)
        ) or (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == "ENV_REGISTRY"
            and isinstance(node.value, ast.Dict)
        ):
            value = node.value
            out: dict[str, int] = {}
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.setdefault(k.value, k.lineno)
            return out
    return None


_CONFIG_RELPATH = "dct_tpu/config.py"
_ENV_EXAMPLE_RELPATH = ".env.example"


@register
class EnvRegistryRule(Rule):
    id = "env-registry"
    name = "DCT_* env keys: declared ⇄ documented ⇄ used"
    doc = (
        "Every DCT_* key read anywhere in first-party code must be "
        "declared in dct_tpu/config.py's ENV_REGISTRY and mentioned in "
        ".env.example; every declared key must be mentioned there and "
        "actually used; every key .env.example names must be declared. "
        "One registry, zero drift."
    )

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        cfg_ctx = project.parse_aux(_CONFIG_RELPATH)
        declared = parse_env_registry(cfg_ctx)
        if declared is None:
            anchor = cfg_ctx if cfg_ctx is not None else None
            out.append(
                Finding(
                    rule=self.id,
                    path=_CONFIG_RELPATH,
                    line=1,
                    message=(
                        "ENV_REGISTRY dict not found in dct_tpu/config.py "
                        "— the DCT_* env contract has no registry of "
                        "record to check against"
                    ),
                    snippet=anchor.line(1).strip() if anchor else "",
                )
            )
            return out

        env_example = project.read(_ENV_EXAMPLE_RELPATH)
        documented = _env_mentions(env_example) if env_example else {}

        uses: dict[str, tuple[str, int]] = {}
        for rel in project.repo_python_files():
            ctx = project.parse_aux(rel)
            if ctx is None:
                continue
            for key, lineno in collect_env_uses(ctx).items():
                uses.setdefault(key, (rel, lineno))

        for key, (rel, lineno) in sorted(uses.items()):
            if key not in declared:
                ctx = project.parse_aux(rel)
                out.append(
                    Finding(
                        rule=self.id,
                        path=rel,
                        line=lineno,
                        message=(
                            f"env var {key} is used here but not declared "
                            "in dct_tpu/config.py ENV_REGISTRY — add it "
                            "(with a one-line description) and to "
                            ".env.example"
                        ),
                        snippet=ctx.line(lineno).strip() if ctx else "",
                    )
                )
        cfg_line = (
            cfg_ctx.line if cfg_ctx is not None else (lambda _i: "")
        )
        for key, lineno in sorted(declared.items()):
            if key not in documented:
                out.append(
                    Finding(
                        rule=self.id,
                        path=_CONFIG_RELPATH,
                        line=lineno,
                        message=(
                            f"registry entry {key} is not mentioned in "
                            ".env.example — document the knob (a "
                            "commented `# {key}=` line suffices)"
                        ),
                        snippet=cfg_line(lineno).strip(),
                    )
                )
            if key not in uses:
                out.append(
                    Finding(
                        rule=self.id,
                        path=_CONFIG_RELPATH,
                        line=lineno,
                        message=(
                            f"registry entry {key} is never read or set "
                            "by any first-party code — dead entry; "
                            "delete it (and its .env.example mention) or "
                            "wire it up"
                        ),
                        snippet=cfg_line(lineno).strip(),
                    )
                )
        if env_example:
            for key, lineno in sorted(documented.items()):
                if key not in declared:
                    out.append(
                        Finding(
                            rule=self.id,
                            path=_ENV_EXAMPLE_RELPATH,
                            line=lineno,
                            message=(
                                f".env.example mentions {key}, which is "
                                "not declared in dct_tpu/config.py "
                                "ENV_REGISTRY — stale doc or missing "
                                "declaration"
                            ),
                            snippet=env_example.splitlines()[
                                lineno - 1
                            ].strip(),
                        )
                    )
        return out


# ----------------------------------------------------------------------
# event-names


_DOCS_RELPATH = "docs/OBSERVABILITY.md"
_TABLE_HEADER_RE = re.compile(
    r"^\|\s*component\s*\|\s*events\s*\|\s*$", re.I
)
_BACKTICK_RE = re.compile(r"`([^`]+)`")


def parse_event_table(markdown: str) -> dict[str, set[str]] | None:
    """The ``| component | events |`` table -> component -> allowed
    event names (every backticked token in the events cell; prose
    tokens only ever widen the allowlist). None when the table is
    absent."""
    lines = markdown.splitlines()
    for i, line in enumerate(lines):
        if not _TABLE_HEADER_RE.match(line.strip()):
            continue
        table: dict[str, set[str]] = {}
        for row in lines[i + 1 :]:
            row = row.strip()
            if not row.startswith("|"):
                break
            cells = [c.strip() for c in row.strip("|").split("|")]
            if len(cells) < 2 or set(cells[0]) <= {"-", " ", ":"}:
                continue
            comp_tokens = _BACKTICK_RE.findall(cells[0])
            if not comp_tokens:
                continue
            events = set()
            for cell in cells[1:]:
                events.update(_BACKTICK_RE.findall(cell))
            table[comp_tokens[0]] = events
        return table
    return None


@register
class EventNamesRule(Rule):
    id = "event-names"
    name = "EventLog emit sites use documented event names"
    doc = (
        "Every statically-resolvable `*.emit(component, event, ...)` "
        "site must use a (component, event) pair present in "
        "docs/OBSERVABILITY.md's event table. Emitting an undocumented "
        "name ships telemetry no operator query will find — document "
        "the event (one table row) in the same change that emits it."
    )

    def check(self, project: Project) -> list[Finding]:
        markdown = project.read(_DOCS_RELPATH)
        table = parse_event_table(markdown) if markdown else None
        if table is None:
            # No docs, nothing to hold emit sites against: only flag
            # when there are emit sites that would need it.
            table = {}
        out: list[Finding] = []
        for ctx in project.contexts:
            if ctx.tree is None:
                continue
            for call in iter_calls(ctx.tree):
                if (
                    not isinstance(call.func, ast.Attribute)
                    or call.func.attr != "emit"
                    or len(call.args) < 2
                ):
                    continue
                comps = string_candidates(call.args[0])
                events = string_candidates(call.args[1])
                if comps is None or events is None:
                    continue  # dynamic: not statically checkable
                for comp in comps:
                    allowed = table.get(comp)
                    if allowed is None:
                        out.append(
                            ctx.finding(
                                self.id,
                                call,
                                f"event component `{comp}` is not in "
                                f"{_DOCS_RELPATH}'s event table — add a "
                                "row documenting this component's events",
                            )
                        )
                        continue
                    for evt in events:
                        if evt not in allowed:
                            out.append(
                                ctx.finding(
                                    self.id,
                                    call,
                                    f"event `{comp}`/`{evt}` is not "
                                    f"documented in {_DOCS_RELPATH}'s "
                                    "event table — add it to the "
                                    f"`{comp}` row (telemetry schema is "
                                    "an operator API)",
                                )
                            )
        return out
