"""AST helpers shared by the built-in rules."""

from __future__ import annotations

import ast


def func_repr(call: ast.Call) -> str:
    """Source-ish spelling of a call's callee (``os.replace``,
    ``shutil.copy2``, ``open`` ...); empty string when unrenderable."""
    try:
        return ast.unparse(call.func)
    except Exception:  # pragma: no cover — unparse is total on 3.9+
        return ""


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def iter_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def open_mode(call: ast.Call) -> str | None:
    """The literal mode of an ``open()``/``io.open()`` call; ``'r'`` when
    defaulted; None when the callee is not open or the mode is dynamic."""
    name = func_repr(call)
    if name not in ("open", "io.open"):
        return None
    mode_node: ast.AST | None = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
    if mode_node is None:
        return "r"
    if isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str):
        return mode_node.value
    return None  # dynamic mode: not statically checkable


def open_target(call: ast.Call) -> ast.AST | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "file":
            return kw.value
    return None


def string_candidates(node: ast.AST) -> list[str] | None:
    """Statically-known string values of an expression: a constant, or
    both arms of a constant conditional. None = dynamic (unknowable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        body = string_candidates(node.body)
        orelse = string_candidates(node.orelse)
        if body is not None and orelse is not None:
            return body + orelse
    return None


def with_open_bindings(fn: ast.AST) -> dict[str, ast.AST]:
    """``with open(X) as name`` bindings in a function body: name -> X.
    Lets path-shape checks see through file handles (``np.savez(f)``)."""
    out: dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            call = item.context_expr
            if (
                isinstance(call, ast.Call)
                and func_repr(call) in ("open", "io.open")
                and isinstance(item.optional_vars, ast.Name)
            ):
                target = open_target(call)
                if target is not None:
                    out[item.optional_vars.id] = target
    return out


def enclosing_function(ctx, node: ast.AST) -> ast.AST | None:
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None
