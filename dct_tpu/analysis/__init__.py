"""dct-lint: project-native static analysis for the platform's invariants.

The platform's correctness rests on conventions nothing in a generic
linter checks: rank-0-only artifact writes in SPMD code, tmp-then-
``os.replace`` atomic publishes into checkpoint/package/registry
directories, no blocking host sync inside the trainer's pipelined
dispatch region, pure bodies under ``jax.jit``/``shard_map`` traces, a
reconciled ``DCT_*`` env registry, and event names that match the
documented observability schema. ``dct_tpu.analysis`` enforces them
mechanically:

- :mod:`core` — the framework: rule registry, findings, ``# dct:
  noqa[rule-id]`` suppressions, the reviewed baseline file.
- :mod:`rules` — the project-specific rules (one module per concern).
- :mod:`lint` — the CLI: ``python -m dct_tpu.analysis.lint [paths]``
  (text or ``--format json``, exit 0 clean / 1 findings / 2 error —
  suitable for CI).

The package is deliberately stdlib-only (``ast``/``re``/``json``): the
CI job that runs it needs no jax, so a broken accelerator install can
never mask a broken invariant. Rule catalog, suppression policy, and
the how-to-extend guide live in ``docs/ANALYSIS.md``.
"""

from dct_tpu.analysis.core import (  # noqa: F401
    Finding,
    Project,
    Rule,
    all_rules,
    analyze,
    register,
)
