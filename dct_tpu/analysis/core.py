"""The dct-lint framework: findings, rules, suppressions, baseline.

Design constraints that shaped this module:

- **stdlib-only.** The analyzer must run in a bare CI container (no
  jax), and must keep working when the code under analysis cannot even
  import — a syntax error becomes a ``parse`` finding, never a crash.
- **Line-drift-stable baselines.** A baseline entry fingerprints the
  *content* of the flagged line (rule + file + stripped source +
  occurrence ordinal), not its line number, so unrelated edits above a
  grandfathered finding do not invalidate the baseline.
- **Reviewable suppressions.** ``# dct: noqa[rule-id]`` on the flagged
  line suppresses named rules there; the same comment on a ``def`` /
  ``class`` line suppresses them for that whole body (the idiom for
  "this function is per-process by design"). A bare ``# dct: noqa``
  suppresses every rule on its line. Suppressions are expected to carry
  a justification in the trailing comment text; the baseline *requires*
  one (:class:`Baseline` treats empty/TODO justifications as findings).
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

#: ``# dct: noqa`` / ``# dct: noqa[rule-a,rule-b] — why`` (trailing
#: prose after the bracket is the human justification, not parsed).
NOQA_RE = re.compile(r"#\s*dct:\s*noqa(?:\[([a-z0-9_\-, ]+)\])?", re.I)

#: Region markers consumed by the span-sync rule (and available to any
#: future region-scoped rule): ``# dct: begin-no-host-sync`` ...
#: ``# dct: end-no-host-sync``.
REGION_BEGIN_RE = re.compile(r"#\s*dct:\s*begin-no-host-sync")
REGION_END_RE = re.compile(r"#\s*dct:\s*end-no-host-sync")

_DEF_LINE_RE = re.compile(r"^\s*(?:async\s+def|def|class)\b")


@dataclass
class Finding:
    """One rule violation, anchored to a source line."""

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    snippet: str = ""
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }


class Rule:
    """Base class: subclasses set ``id``/``name``/``doc`` and implement
    :meth:`check`. Register with the :func:`register` decorator."""

    id: str = ""
    name: str = ""
    doc: str = ""

    def check(self, project: "Project") -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    """id -> rule instance, loading the built-in rule modules on first
    use (imports under a function so ``core`` alone stays cycle-free)."""
    import dct_tpu.analysis.rules  # noqa: F401 — registers on import

    return dict(_REGISTRY)


# ----------------------------------------------------------------------
# Source files


class FileContext:
    """One parsed source file plus the lazy indexes rules share."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree: ast.AST | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(source)
        except (SyntaxError, ValueError) as e:
            self.parse_error = f"{type(e).__name__}: {e}"
        self._parents: dict[ast.AST, ast.AST] | None = None
        self._suppress: dict[int, set[str]] | None = None
        self._comments: dict[int, str] | None = None

    # -- navigation ----------------------------------------------------
    def parents(self) -> dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        self._parents[child] = node
        return self._parents

    def ancestors(self, node: ast.AST):
        parents = self.parents()
        cur = parents.get(node)
        while cur is not None:
            yield cur
            cur = parents.get(cur)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    # -- comments --------------------------------------------------------
    def comments(self) -> dict[int, str]:
        """line -> actual comment text on that line. Tokenizer-accurate
        for Python (a ``# dct:`` marker quoted inside a string literal
        or docstring is NOT a comment and must not arm a region or a
        suppression); plain ``#``-to-EOL scan for non-Python files
        (.env.example) where string literals don't exist."""
        if self._comments is not None:
            return self._comments
        out: dict[int, str] = {}
        if self.tree is not None:
            try:
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline
                ):
                    if tok.type == tokenize.COMMENT:
                        out.setdefault(tok.start[0], tok.string)
            except (tokenize.TokenError, IndentationError, SyntaxError):
                out = self._comments_by_scan()
        else:
            out = self._comments_by_scan()
        self._comments = out
        return out

    def _comments_by_scan(self) -> dict[int, str]:
        out: dict[int, str] = {}
        for i, text in enumerate(self.lines, start=1):
            pos = text.find("#")
            if pos >= 0:
                out[i] = text[pos:]
        return out

    # -- suppressions ----------------------------------------------------
    def _def_keyword_line(self, node) -> int:
        """The line holding the ``def``/``class`` keyword (decorated
        nodes report the first decorator as ``lineno``)."""
        end = node.body[0].lineno if node.body else (node.end_lineno or node.lineno)
        for ln in range(node.lineno, end + 1):
            if _DEF_LINE_RE.match(self.line(ln)):
                return ln
        return node.lineno

    def suppressions(self) -> dict[int, set[str]]:
        """line -> suppressed rule ids ('*' = all). Block suppressions
        (noqa on a def/class line) are expanded to every body line."""
        if self._suppress is not None:
            return self._suppress
        out: dict[int, set[str]] = {}
        for i, text in sorted(self.comments().items()):
            m = NOQA_RE.search(text)
            if not m:
                continue
            ids = (
                {s.strip() for s in m.group(1).split(",") if s.strip()}
                if m.group(1)
                else {"*"}
            )
            out.setdefault(i, set()).update(ids)
        if self.tree is not None and out:
            for node in ast.walk(self.tree):
                if not isinstance(
                    node,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    continue
                def_line = self._def_keyword_line(node)
                ids = out.get(def_line)
                if not ids:
                    continue
                for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                    out.setdefault(ln, set()).update(ids)
        self._suppress = out
        return out

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        ids = self.suppressions().get(lineno)
        return bool(ids) and ("*" in ids or rule_id in ids)

    # -- regions ---------------------------------------------------------
    def regions(self) -> list[tuple[int, int]]:
        """``begin-no-host-sync`` .. ``end-no-host-sync`` line ranges
        (exclusive of the marker lines). Fail-safe in both directions:
        an unclosed begin extends to EOF, and a duplicate begin before
        the end is ignored (the earlier, wider window wins) — better to
        over-check than silently shrink the protected region."""
        out: list[tuple[int, int]] = []
        start: int | None = None
        for i, text in sorted(self.comments().items()):
            if REGION_BEGIN_RE.search(text):
                if start is None:
                    start = i
            elif REGION_END_RE.search(text) and start is not None:
                out.append((start + 1, i - 1))
                start = None
        if start is not None:
            out.append((start + 1, len(self.lines)))
        return out

    def finding(self, rule_id: str, node_or_line, message: str) -> Finding:
        lineno = (
            node_or_line
            if isinstance(node_or_line, int)
            else getattr(node_or_line, "lineno", 1)
        )
        return Finding(
            rule=rule_id,
            path=self.relpath,
            line=lineno,
            message=message,
            snippet=self.line(lineno).strip(),
        )


# ----------------------------------------------------------------------
# Project


def default_root() -> str:
    """The repo root: the directory holding the ``dct_tpu`` package."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


#: Where first-party Python lives relative to the repo root — the scan
#: surface for repo-wide rules (env registry), independent of which
#: paths the CLI was pointed at. Tests are deliberately absent: a test
#: monkeypatching ``DCT_FOO`` does not make ``DCT_FOO`` part of the
#: platform's env contract.
REPO_CODE_DIRS = ("dct_tpu", "jobs", "dags", "scripts")
REPO_CODE_FILES = ("bench.py",)


class Project:
    """The analysis unit: target files plus root-relative access to the
    registry/docs files cross-file rules consult."""

    def __init__(self, root: str, contexts: list[FileContext]):
        self.root = os.path.abspath(root)
        self.contexts = contexts
        self._aux: dict[str, FileContext | None] = {}

    def read(self, relpath: str) -> str | None:
        """Raw text of a root-relative file, None if absent/unreadable."""
        try:
            with open(os.path.join(self.root, relpath), encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def parse_aux(self, relpath: str) -> FileContext | None:
        """Parse a root-relative file on demand (cached); reuses a
        target context when the file is already in the lint batch."""
        relpath = relpath.replace(os.sep, "/")
        if relpath in self._aux:
            return self._aux[relpath]
        ctx = next(
            (c for c in self.contexts if c.relpath == relpath), None
        )
        if ctx is None:
            src = self.read(relpath)
            if src is not None:
                ctx = FileContext(
                    os.path.join(self.root, relpath), relpath, src
                )
        self._aux[relpath] = ctx
        return ctx

    def repo_python_files(self) -> list[str]:
        """Root-relative paths of all first-party Python (the repo-wide
        scan surface — see :data:`REPO_CODE_DIRS`)."""
        out: list[str] = []
        for d in REPO_CODE_DIRS:
            base = os.path.join(self.root, d)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [
                    n for n in dirnames
                    if n != "__pycache__" and not n.startswith(".")
                ]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, name), self.root
                        )
                        out.append(rel.replace(os.sep, "/"))
        for f in REPO_CODE_FILES:
            if os.path.exists(os.path.join(self.root, f)):
                out.append(f)
        return sorted(out)


def collect_files(paths: list[str], root: str) -> list[FileContext]:
    """Expand CLI path arguments into parsed :class:`FileContext`\\ s."""
    seen: set[str] = set()
    contexts: list[FileContext] = []

    def add(path: str) -> None:
        apath = os.path.abspath(path)
        if apath in seen:
            return
        seen.add(apath)
        rel = os.path.relpath(apath, root).replace(os.sep, "/")
        try:
            with open(apath, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            ctx = FileContext(apath, rel, "")
            ctx.parse_error = f"unreadable: {e}"
            contexts.append(ctx)
            return
        contexts.append(FileContext(apath, rel, src))

    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    n for n in dirnames
                    if n != "__pycache__" and not n.startswith(".")
                ]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        add(os.path.join(dirpath, name))
        else:
            add(p)
    contexts.sort(key=lambda c: c.relpath)
    return contexts


# ----------------------------------------------------------------------
# Baseline


def _fingerprint(rule: str, path: str, snippet: str, ordinal: int) -> str:
    h = hashlib.sha1(
        f"{rule}::{path}::{snippet}::{ordinal}".encode()
    )
    return h.hexdigest()[:16]


def assign_fingerprints(findings: list[Finding]) -> None:
    """Stamp content-based fingerprints; identical lines in one file
    disambiguate by line-ordered ordinal."""
    counters: dict[tuple[str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        key = (f.rule, f.path, f.snippet)
        n = counters.get(key, 0)
        counters[key] = n + 1
        f.fingerprint = _fingerprint(f.rule, f.path, f.snippet, n)


@dataclass
class BaselineEntry:
    fingerprint: str
    rule: str
    path: str
    snippet: str
    justification: str = ""

    def to_dict(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "rule": self.rule,
            "path": self.path,
            "snippet": self.snippet,
            "justification": self.justification,
        }


class Baseline:
    """The reviewed debt ledger: findings listed here (by content
    fingerprint) do not fail the lint, but every entry must carry a
    real justification — an empty or TODO one is itself a finding."""

    def __init__(self, entries: list[BaselineEntry], path: str | None = None):
        self.entries = entries
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        entries = [
            BaselineEntry(
                fingerprint=e.get("fingerprint", ""),
                rule=e.get("rule", ""),
                path=e.get("path", ""),
                snippet=e.get("snippet", ""),
                justification=e.get("justification", ""),
            )
            for e in raw.get("entries", [])
        ]
        return cls(entries, path=path)

    @classmethod
    def from_findings(
        cls,
        findings: list[Finding],
        path: str | None = None,
        previous: "Baseline | None" = None,
    ) -> "Baseline":
        """Build a baseline for ``findings``; entries whose fingerprint
        already exists in ``previous`` KEEP their hand-written
        justification (regenerating the baseline must never destroy the
        review record — only genuinely new findings get the TODO)."""
        keep = (
            {e.fingerprint: e.justification for e in previous.entries}
            if previous is not None
            else {}
        )
        return cls(
            [
                BaselineEntry(
                    fingerprint=f.fingerprint,
                    rule=f.rule,
                    path=f.path,
                    snippet=f.snippet,
                    justification=keep.get(
                        f.fingerprint,
                        "TODO: justify this grandfathered finding",
                    ),
                )
                for f in findings
            ],
            path=path,
        )

    def save(self, path: str) -> None:
        payload = {
            "comment": (
                "dct-lint baseline: reviewed, justified debt. Every entry "
                "MUST carry a non-TODO justification (docs/ANALYSIS.md)."
            ),
            "entries": [e.to_dict() for e in self.entries],
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=False)
            f.write("\n")
        os.replace(tmp, path)

    def hygiene_findings(self) -> list[Finding]:
        out = []
        for e in self.entries:
            just = e.justification.strip()
            if not just or just.upper().startswith("TODO"):
                out.append(
                    Finding(
                        rule="baseline-hygiene",
                        path=e.path or (self.path or ""),
                        line=0,
                        message=(
                            f"baseline entry {e.fingerprint} ({e.rule}) "
                            "has no written justification — the baseline "
                            "is a reviewed ledger, not a mute button"
                        ),
                        snippet=e.snippet,
                        fingerprint=e.fingerprint,
                    )
                )
        return out


# ----------------------------------------------------------------------
# Analysis driver


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    stale_baseline: list[BaselineEntry] = field(default_factory=list)
    checked_files: int = 0
    active_rules: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "checked_files": self.checked_files,
            "active_rules": self.active_rules,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": [e.to_dict() for e in self.stale_baseline],
        }


def analyze(
    paths: list[str],
    *,
    root: str | None = None,
    select: set[str] | None = None,
    ignore: set[str] | None = None,
    baseline: Baseline | None = None,
) -> Report:
    """Run the registered rules over ``paths``; returns a :class:`Report`
    whose ``findings`` are post-noqa, post-baseline violations."""
    root = os.path.abspath(root or default_root())
    contexts = collect_files(paths, root)
    project = Project(root, contexts)

    rules = all_rules()
    active = [
        r
        for rid, r in sorted(rules.items())
        if (select is None or rid in select)
        and (ignore is None or rid not in ignore)
    ]

    raw: list[Finding] = []
    for ctx in contexts:
        if ctx.parse_error is not None:
            raw.append(
                Finding(
                    rule="parse",
                    path=ctx.relpath,
                    line=1,
                    message=f"cannot analyze: {ctx.parse_error}",
                )
            )
    for rule in active:
        for f in rule.check(project):
            # Resolve the finding's file for suppression even when it
            # is not a lint target (repo-wide rules anchor findings in
            # bench.py/.env.example/config.py regardless of CLI paths;
            # a noqa there must bind under every invocation).
            ctx = project.parse_aux(f.path)
            if ctx is not None and ctx.suppressed(f.rule, f.line):
                continue
            raw.append(f)

    assign_fingerprints(raw)
    raw.sort(key=lambda f: (f.path, f.line, f.rule))

    report = Report(
        checked_files=len(contexts),
        active_rules=[r.id for r in active],
    )
    if baseline is None:
        report.findings = raw
        return report

    by_fp = {e.fingerprint: e for e in baseline.entries}
    matched_fps: set[str] = set()
    for f in raw:
        entry = by_fp.get(f.fingerprint)
        if entry is not None:
            matched_fps.add(entry.fingerprint)
            report.baselined.append(f)
        else:
            report.findings.append(f)
    report.stale_baseline = [
        e for e in baseline.entries if e.fingerprint not in matched_fps
    ]
    report.findings.extend(baseline.hygiene_findings())
    return report
