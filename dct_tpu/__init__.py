"""dct_tpu — a TPU-native continuous-training framework.

A brand-new JAX/XLA implementation of the capabilities of the reference
pipeline ``Distributed-Continuous-Training-with-Airflow-PyTorch-Distributed-DDP-``
(Airflow-orchestrated Spark ETL -> distributed training -> MLflow tracking ->
blue/green deployment), with the training core rebuilt idiomatically for TPUs:

- pure-functional jitted train/eval steps over a ``jax.sharding.Mesh``
  (data-parallel by default, with tensor/sequence-parallel extension axes),
- ``jax.distributed.initialize()`` multi-host rendezvous in place of the
  reference's env-var + TCP-store gloo rendezvous
  (reference: jobs/train_lightning_ddp.py:129-143, docker-compose.yml:121-124),
- XLA collectives over ICI/DCN in place of gloo/NCCL all-reduce,
- best/last checkpointing + MLflow-compatible tracking preserving the
  reference's deploy-time model-selection query
  (reference: dags/azure_auto_deploy.py:32-39).
"""

__version__ = "0.1.0"

from dct_tpu.config import (  # noqa: F401
    DataConfig,
    ModelConfig,
    TrainConfig,
    MeshConfig,
    RunConfig,
)
