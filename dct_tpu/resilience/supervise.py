"""``python -m dct_tpu.resilience.supervise [opts] -- cmd...``: the
supervised launch block as a CLI.

Wraps :meth:`LocalProcessLauncher.supervise` so a DAG's BashOperator (or
an operator's shell) gets crash/hang/preemption healing without writing
Python: the command is launched ``--world-size`` times with coordinator
env, babysat via heartbeats (stall-kill armed), and relaunched with
resume + backoff per the restart policy. Defaults come from the same
``DCT_*`` env contract as everything else, so the DAG needs no new
plumbing to tune it.

Exit code: 0 on (possibly healed) success; ``EXIT_PREEMPTED`` when the
final state is a graceful preemption (Airflow retries see "resume me");
1 otherwise.
"""

from __future__ import annotations

import argparse
import os
import sys

from dct_tpu.resilience.supervisor import EXIT_PREEMPTED


def _env_default(name: str, fallback: str) -> str:
    return os.environ.get(name) or fallback


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dct_tpu.resilience.supervise",
        description="Supervised relaunch-and-resume for SPMD training "
        "(docs/ROBUSTNESS.md).",
    )
    parser.add_argument(
        "--world-size", type=int,
        default=int(_env_default("DCT_WORLD_SIZE", "1")),
    )
    parser.add_argument(
        "--max-restarts", type=int,
        default=int(_env_default("DCT_MAX_RESTARTS", "2")),
    )
    parser.add_argument(
        "--backoff", type=float,
        default=float(_env_default("DCT_RESTART_BACKOFF_S", "5")),
    )
    parser.add_argument(
        "--backoff-factor", type=float,
        default=float(_env_default("DCT_RESTART_BACKOFF_FACTOR", "2")),
    )
    parser.add_argument(
        "--jitter", type=float,
        default=float(_env_default("DCT_RESTART_JITTER", "0.1")),
    )
    parser.add_argument(
        "--timeout", type=float,
        default=float(_env_default("DCT_LAUNCH_TIMEOUT_S", "10800")),
    )
    parser.add_argument(
        "--stall-seconds", type=float,
        default=float(_env_default("DCT_HEARTBEAT_STALL_SECONDS", "120")),
    )
    parser.add_argument(
        "--grace", type=float,
        default=float(_env_default("DCT_PREEMPT_GRACE_S", "30")),
    )
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- then the rank command")
    args = parser.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given (append: -- python3 jobs/train_tpu.py)")

    from dct_tpu.launch.launcher import LocalProcessLauncher

    launcher = LocalProcessLauncher(
        timeout=args.timeout,
        heartbeat_stall_seconds=args.stall_seconds,
        preempt_grace_s=args.grace,
        stall_kill=True,
    )
    res = launcher.supervise(
        cmd,
        world_size=args.world_size,
        max_restarts=args.max_restarts,
        backoff_s=args.backoff,
        backoff_factor=args.backoff_factor,
        jitter=args.jitter,
    )
    if res.success:
        return 0
    return EXIT_PREEMPTED if res.classification == "preempted" else 1


if __name__ == "__main__":
    sys.exit(main())
