"""Self-healing training cycles: fault injection, supervised
relaunch-and-resume, and graceful preemption.

PRs 1-2 gave the platform *senses* (heartbeats, stall/straggler flags,
NaN/spike health halts); this package adds the *reflexes*. Podracer-style
TPU fleets (PAPERS: "Podracer architectures for scalable Reinforcement
Learning") and large pjit jobs (PAPERS: "Scalable Training of Language
Models using JAX pjit and TPUv4") treat preemption and rank loss as
routine events handled by supervised relaunch from checkpoint — none of
which is testable without deterministic failures, so the package leads
with fault injection:

- :mod:`faults`     — ``DCT_FAULT_SPEC``-driven fault plan consulted at
  well-defined hook points in the trainer, the data staging path, and
  both checkpoint tiers (``crash@rank1:epoch2``, ``hang@rank0:step10``,
  ``nan@rank1:epoch1``, ``slow_save``, ``crash_save``, ``slow_epoch``);
- :mod:`supervisor` — the exit-code contract, the failure classifier
  (crash / hang / preempted / infra / health_halt), and the exponential
  restart-backoff policy the launcher's supervision loop runs on;
- :mod:`preempt`    — the rank-side SIGTERM contract: finish the
  in-flight step, make the resume checkpoint durable, exit
  ``EXIT_PREEMPTED`` so the supervisor treats the rank as
  resumable-not-failed;
- :mod:`retry`      — ``with_retries`` (backoff + jitter + transient
  classification) for the tracking client's network ops and the
  rollout's endpoint calls;
- :mod:`supervise`  — ``python -m dct_tpu.resilience.supervise`` CLI
  wrapping :meth:`LocalProcessLauncher.supervise` for DAG launch blocks.

See docs/ROBUSTNESS.md for the failure model and the fault-spec grammar.
"""

from dct_tpu.resilience.faults import (  # noqa: F401
    FAULT_CRASH_EXIT,
    FaultClause,
    FaultPlan,
)
from dct_tpu.resilience.preempt import (  # noqa: F401
    PreemptedError,
    PreemptionGuard,
)
from dct_tpu.resilience.retry import Retrier, with_retries  # noqa: F401
from dct_tpu.resilience.supervisor import (  # noqa: F401
    EXIT_HEALTH_HALT,
    EXIT_INFRA_CLEANUP,
    EXIT_INFRA_HEALTHCHECK,
    EXIT_PREEMPTED,
    RestartPolicy,
    classify_failure,
)
