"""``with_retries``: backoff + jitter + transient-vs-fatal classification.

A continuous-training cycle talks to services that flake independently
of the training itself — the tracking/registry server, the deploy
control plane. The reference aborts the whole Airflow task on the first
``requests`` hiccup; here every network op is wrapped in one shared
retry helper so a transient flake costs a backoff sleep instead of a
cycle.

Only *transient* failures retry: the ``classify`` predicate decides
(default :func:`is_transient` — connection/timeout error types plus a
name/message heuristic for SDK-wrapped 5xx/throttle errors). A fatal
error (auth failure, 404, programming error) raises immediately —
retrying those only delays the operator's page.

Every retry is on the record: ``retry.attempt`` events carry the op
name, attempt number and error, and ``retry.exhausted`` precedes the
final raise, so "the registry was down for 40 s at 03:12" is a grep,
not a reconstruction.
"""

from __future__ import annotations

import random
import time

from dct_tpu.observability import events as _events

#: Substrings (lowercased ``TypeName: message``) that mark an exception
#: transient when its type alone does not — SDKs wrap timeouts and 5xxs
#: in their own exception classes (mlflow's RestException, requests'
#: wrappers), so the type check cannot be exhaustive.
_TRANSIENT_MARKERS = (
    "timeout", "timed out", "connection", "unavailable", "temporar",
    "reset by peer", "refused", "bad gateway", "too many requests",
    "throttl", "503", "502", "504", "econnreset", "broken pipe",
)


def is_transient(exc: BaseException) -> bool:
    """Default classifier: retry network-ish failures, nothing else."""
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(m in text for m in _TRANSIENT_MARKERS)


class Retrier:
    """A reusable retry policy: ``retrier(fn, op="log_metrics")`` calls
    ``fn()`` up to ``max_attempts`` times with exponential backoff."""

    def __init__(
        self,
        *,
        max_attempts: int = 3,
        backoff_s: float = 0.5,
        backoff_factor: float = 2.0,
        jitter: float = 0.1,
        classify=is_transient,
        sleep_fn=time.sleep,
        rng=random.random,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.jitter = float(jitter)
        self.classify = classify
        self.sleep_fn = sleep_fn
        self.rng = rng

    @classmethod
    def from_env(cls, env=None, **overrides) -> "Retrier":
        """Policy from ``DCT_RETRY_MAX_ATTEMPTS`` / ``DCT_RETRY_BACKOFF_S``
        (for layers without config plumbing, e.g. the tracking client)."""
        import os

        env = env if env is not None else os.environ
        kw = dict(
            max_attempts=int(env.get("DCT_RETRY_MAX_ATTEMPTS") or 3),
            backoff_s=float(env.get("DCT_RETRY_BACKOFF_S") or 0.5),
        )
        kw.update(overrides)
        return cls(**kw)

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based failed attempts)."""
        base = self.backoff_s * self.backoff_factor ** (attempt - 1)
        return base * (1.0 + self.jitter * self.rng())

    def __call__(self, fn, *, op: str = "call"):
        last: BaseException | None = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — classified below
                last = e
                if not self.classify(e) or attempt >= self.max_attempts:
                    if attempt > 1 or self.classify(e):
                        _events.get_default().emit(
                            "retry", "retry.exhausted",
                            op=op, attempts=attempt, error=repr(e),
                        )
                    raise
                pause = self.delay(attempt)
                _events.get_default().emit(
                    "retry", "retry.attempt",
                    op=op, attempt=attempt, backoff_s=round(pause, 3),
                    error=repr(e),
                )
                self.sleep_fn(pause)
        raise last  # unreachable; keeps type-checkers honest


def with_retries(
    fn,
    *,
    op: str = "call",
    max_attempts: int = 3,
    backoff_s: float = 0.5,
    backoff_factor: float = 2.0,
    jitter: float = 0.1,
    classify=is_transient,
    sleep_fn=time.sleep,
):
    """One-shot form: run ``fn()`` under a fresh :class:`Retrier`."""
    return Retrier(
        max_attempts=max_attempts,
        backoff_s=backoff_s,
        backoff_factor=backoff_factor,
        jitter=jitter,
        classify=classify,
        sleep_fn=sleep_fn,
    )(fn, op=op)
