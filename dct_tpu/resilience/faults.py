"""Deterministic fault injection: the ``DCT_FAULT_SPEC`` fault plan.

Every failure mode the supervisor must heal — a crashed rank, a wedged
collective, a NaN'd loss, a save torn mid-write — needs a reproducible
trigger before the healing is testable. The plan is parsed from one env
var so the SAME spec drives a unit test, a launched multi-process rig,
and a chaos job in CI.

Spec grammar (comma-separated clauses)::

    DCT_FAULT_SPEC = clause[,clause...]
    clause         = ACTION[@rankR][:TRIGGER]
    TRIGGER        = epochN | stepN | saveN

Actions and the hook points that consult them:

===========  =========  ====================================================
action       point      behavior when fired
===========  =========  ====================================================
crash        epoch/step ``os._exit(FAULT_CRASH_EXIT)`` — a hard rank death
                        (no atexit, no finally; the launcher sees a nonzero
                        exit). Epoch-trigger crashes first join any pending
                        resume-checkpoint write (the ``pre_exit`` hook) so
                        the resume point is deterministic; use
                        ``crash_save`` to exercise torn-write recovery.
hang         epoch/step sleep forever — the rank stays PID-alive but stops
                        beating, exactly the wedged-collective case the
                        heartbeat monitor (and the supervisor's stall-kill)
                        exists for.
nan          data       the caller poisons the staged batch with NaN, so
                        the loss goes non-finite through the REAL compute
                        path (health.py then warns or halts per policy).
slow_save    save       sleep ``DCT_FAULT_SLEEP_S`` inside the checkpoint
                        write window (tmp written, final not yet renamed) —
                        widens the torn-write window so a test can kill the
                        process mid-save.
crash_save   save       ``os._exit`` inside the same window — the torn
                        save itself: only ``*.tmp`` debris may remain.
slow_epoch   epoch      sleep ``DCT_FAULT_SLEEP_S`` at epoch start — makes
                        "SIGTERM arrives mid-epoch" deterministic in tests.
===========  =========  ====================================================

Trigger semantics: ``epochN`` fires when epoch index N starts; ``stepN``
fires at the first step hook with global step >= N; ``saveN`` fires on
the Nth call of the save hook in this process (both checkpoint tiers
share the counter); no trigger = the first opportunity. ``@rankR``
restricts the clause to one rank (default: every rank). Each clause
fires at most once per process.

Like the rest of the observability plane, the default plan is resolved
lazily from the environment (:func:`get_default`) so layers without
config plumbing (the checkpoint manager) consult the same plan the
trainer installed. An empty/unset spec is a no-op plan.
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass, field

from dct_tpu.observability import events as _events
from dct_tpu.observability.events import _rank_from_env

#: Exit code of an injected ``crash`` — distinct from real failures so
#: the event log names the death as injected; the supervisor still
#: classifies it as an ordinary crash (that is the point of the drill).
FAULT_CRASH_EXIT = 117

#: action -> hook points allowed to fire it.
_ACTION_POINTS = {
    "crash": ("epoch", "step"),
    "hang": ("epoch", "step"),
    "nan": ("data",),
    "slow_save": ("save",),
    "crash_save": ("save",),
    "slow_epoch": ("epoch",),
}

_CLAUSE_RE = re.compile(
    r"^(?P<action>[a-z_]+)"
    r"(?:@rank(?P<rank>\d+))?"
    r"(?::(?P<trigger>epoch|step|save)(?P<at>\d+))?$"
)


@dataclass
class FaultClause:
    action: str
    rank: int | None = None      # None = any rank
    trigger: str | None = None   # epoch | step | save | None (= first)
    at: int | None = None
    raw: str = ""
    fired: bool = False

    def matches(self, point: str, rank: int | None, coords: dict) -> bool:
        if self.fired or point not in _ACTION_POINTS[self.action]:
            return False
        if self.rank is not None and rank is not None and self.rank != rank:
            return False
        if self.trigger is None:
            return True
        value = coords.get(self.trigger)
        if value is None:
            return False
        # step triggers fire on REACHING the step (the exact value may
        # be skipped by accumulation/span granularity); epoch and save
        # ordinals are exact.
        if self.trigger == "step":
            return int(value) >= self.at
        return int(value) == self.at


class FaultPlan:
    """The parsed plan, bound to one rank. ``check`` matches without
    side effects beyond the fired flag + the ``fault.injected`` event;
    ``maybe_fire`` also executes self-executing actions (crash / hang /
    the sleeps)."""

    def __init__(
        self,
        clauses: list[FaultClause] | None = None,
        *,
        rank: int | None = None,
        sleep_s: float = 3.0,
        sleep_fn=time.sleep,
    ):
        self.clauses = list(clauses or [])
        self.rank = rank
        self.sleep_s = float(sleep_s)
        self._sleep = sleep_fn
        self._counts: dict[str, int] = {}

    # -- construction ---------------------------------------------------
    @classmethod
    def parse(
        cls, spec: str, *, rank: int | None = None, sleep_s: float = 3.0
    ) -> "FaultPlan":
        clauses = []
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            m = _CLAUSE_RE.match(part)
            if m is None or m.group("action") not in _ACTION_POINTS:
                raise ValueError(
                    f"Unparseable fault clause {part!r}; grammar: "
                    "ACTION[@rankR][:epochN|stepN|saveN] with ACTION in "
                    f"{sorted(_ACTION_POINTS)}"
                )
            clauses.append(
                FaultClause(
                    action=m.group("action"),
                    rank=int(m.group("rank")) if m.group("rank") else None,
                    trigger=m.group("trigger"),
                    at=int(m.group("at")) if m.group("at") else None,
                    raw=part,
                )
            )
        return cls(clauses, rank=rank, sleep_s=sleep_s)

    @classmethod
    def from_env(cls, env=None) -> "FaultPlan":
        env = env if env is not None else os.environ
        return cls.parse(
            env.get("DCT_FAULT_SPEC", ""),
            rank=_rank_from_env(),
            sleep_s=float(env.get("DCT_FAULT_SLEEP_S") or 3.0),
        )

    @property
    def enabled(self) -> bool:
        return bool(self.clauses)

    @property
    def fired_count(self) -> int:
        return sum(1 for c in self.clauses if c.fired)

    # -- hook surface ---------------------------------------------------
    def check(self, point: str, **coords) -> FaultClause | None:
        """Match (and mark fired) the first armed clause for ``point``.
        ``save`` ordinals are counted here so callers stay stateless."""
        if not self.clauses:
            return None
        if point == "save":
            self._counts["save"] = self._counts.get("save", 0) + 1
            coords.setdefault("save", self._counts["save"])
        for clause in self.clauses:
            if clause.matches(point, self.rank, coords):
                clause.fired = True
                # On the record BEFORE the fault acts: a crash must not
                # be able to outrun its own evidence.
                _events.get_default().emit(
                    "fault", "fault.injected",
                    action=clause.action, point=point, spec=clause.raw,
                    injected_rank=self.rank,
                    **{k: v for k, v in coords.items() if v is not None},
                )
                return clause
        return None

    def maybe_fire(self, point: str, *, pre_exit=None, **coords):
        """``check`` + execute. ``pre_exit`` runs before a ``crash``
        exits (the trainer joins its in-flight resume save so the crash
        leaves a deterministic resume point). Returns the clause for
        caller-executed actions (``nan``), None otherwise."""
        clause = self.check(point, **coords)
        if clause is None:
            return None
        if clause.action in ("crash", "crash_save", "hang"):
            # ``os._exit`` skips atexit and a hang never reaches it:
            # drain buffered telemetry NOW so the fault.injected record
            # (and every record before it) survives the fault it
            # precedes — the buffered-writer durability contract.
            try:
                from dct_tpu.observability.buffered import (
                    flush_all_appenders,
                )

                flush_all_appenders()
            except Exception:  # noqa: BLE001 — the fault must still fire
                pass
        if clause.action == "crash":
            if pre_exit is not None:
                try:
                    pre_exit()
                except Exception:  # noqa: BLE001 — exit anyway: it's a crash
                    pass
            os._exit(FAULT_CRASH_EXIT)
        if clause.action == "crash_save":
            os._exit(FAULT_CRASH_EXIT)
        if clause.action == "hang":
            while True:  # PID-alive, progress-dead: the monitor's case
                self._sleep(60.0)
        if clause.action in ("slow_save", "slow_epoch"):
            self._sleep(self.sleep_s)
            return None
        return clause  # nan: the caller poisons its staged arrays


# ----------------------------------------------------------------------
# Process-default plan, mirroring events.get_default(): the trainer
# installs its config-built plan; layers without config plumbing (the
# checkpoint manager) resolve the same instance so save ordinals and
# fired flags are shared. Standalone processes parse the env lazily.

_explicit: FaultPlan | None = None
_cached: tuple[tuple, FaultPlan] | None = None

_ENV_KEYS = ("DCT_FAULT_SPEC", "DCT_FAULT_SLEEP_S", "DCT_PROCESS_ID", "NODE_RANK")


def set_default(plan: FaultPlan | None) -> None:
    global _explicit
    _explicit = plan


def get_default() -> FaultPlan:
    global _cached
    if _explicit is not None:
        return _explicit
    key = tuple(os.environ.get(k) for k in _ENV_KEYS)
    if _cached is not None and _cached[0] == key:
        return _cached[1]
    try:
        plan = FaultPlan.from_env()
    except ValueError:
        # A malformed ambient spec must not crash layers that merely
        # consult the plan; the trainer's explicit parse stays loud.
        plan = FaultPlan()
    _cached = (key, plan)
    return plan
