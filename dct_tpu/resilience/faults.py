"""Deterministic fault injection: the ``DCT_FAULT_SPEC`` fault plan.

Every failure mode the supervisor must heal — a crashed rank, a wedged
collective, a NaN'd loss, a save torn mid-write — needs a reproducible
trigger before the healing is testable. The plan is parsed from one env
var so the SAME spec drives a unit test, a launched multi-process rig,
and a chaos job in CI.

Spec grammar (comma-separated clauses)::

    DCT_FAULT_SPEC = clause[,clause...]
    clause         = ACTION[@rankR|@procR][:TRIGGER]
    TRIGGER        = epochN | stepN | saveN | reqN | msM

``@procR`` is the serving spelling of ``@rankR`` (a ServerPool child's
pool index rides the same rank slot — the pool exports it as
``DCT_PROCESS_ID`` into each forked worker).

Actions and the hook points that consult them:

===========  =========  ====================================================
action       point      behavior when fired
===========  =========  ====================================================
crash        epoch/step ``os._exit(FAULT_CRASH_EXIT)`` — a hard rank death
                        (no atexit, no finally; the launcher sees a nonzero
                        exit). Epoch-trigger crashes first join any pending
                        resume-checkpoint write (the ``pre_exit`` hook) so
                        the resume point is deterministic; use
                        ``crash_save`` to exercise torn-write recovery.
hang         epoch/step sleep forever — the rank stays PID-alive but stops
                        beating, exactly the wedged-collective case the
                        heartbeat monitor (and the supervisor's stall-kill)
                        exists for.
nan          data       the caller poisons the staged batch with NaN, so
                        the loss goes non-finite through the REAL compute
                        path (health.py then warns or halts per policy).
slow_save    save       sleep ``DCT_FAULT_SLEEP_S`` inside the checkpoint
                        write window (tmp written, final not yet renamed) —
                        widens the torn-write window so a test can kill the
                        process mid-save.
crash_save   save       ``os._exit`` inside the same window — the torn
                        save itself: only ``*.tmp`` debris may remain.
slow_epoch   epoch      sleep ``DCT_FAULT_SLEEP_S`` at epoch start — makes
                        "SIGTERM arrives mid-epoch" deterministic in tests.
crash_worker score      ``os._exit(FAULT_CRASH_EXIT)`` inside the serving
                        micro-batcher's flush path — a serving worker
                        process dying mid-traffic, the case the ServerPool's
                        self-healing respawn exists for (docs/SERVING.md).
                        ``:reqN`` fires at the Nth scored logical request
                        (default: the first). NOTE: in a no-fork in-process
                        server this kills the host process — arm it only
                        against forked pools.
slow_score   score      sleep inside every flush — deterministic overload
                        (the knee moves wherever the test wants it).
                        ``:msM`` sets the per-flush sleep in milliseconds
                        (default ``DCT_FAULT_SLEEP_S``). Unlike every other
                        action this clause REPEATS: it fires on every
                        flush, with ``fault.injected`` emitted only once.
===========  =========  ====================================================

Trigger semantics: ``epochN`` fires when epoch index N starts; ``stepN``
fires at the first step hook with global step >= N; ``saveN`` fires on
the Nth call of the save hook in this process (both checkpoint tiers
share the counter); ``reqN`` fires at the first score hook with
cumulative scored-request count >= N; ``msM`` is a PARAMETER, not a
trigger (the ``slow_score`` sleep in milliseconds); no trigger = the
first opportunity. ``@rankR``/``@procR`` restricts the clause to one
rank / pool worker (default: every one). Each clause fires at most once
per process — except ``slow_score``, which repeats by design (it models
a persistently slow scorer, not a one-shot glitch).

Like the rest of the observability plane, the default plan is resolved
lazily from the environment (:func:`get_default`) so layers without
config plumbing (the checkpoint manager) consult the same plan the
trainer installed. An empty/unset spec is a no-op plan.
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass, field

from dct_tpu.observability import events as _events
from dct_tpu.observability.events import _rank_from_env

#: Exit code of an injected ``crash`` — distinct from real failures so
#: the event log names the death as injected; the supervisor still
#: classifies it as an ordinary crash (that is the point of the drill).
FAULT_CRASH_EXIT = 117

#: action -> hook points allowed to fire it.
_ACTION_POINTS = {
    "crash": ("epoch", "step"),
    "hang": ("epoch", "step"),
    "nan": ("data",),
    "slow_save": ("save",),
    "crash_save": ("save",),
    "slow_epoch": ("epoch",),
    "crash_worker": ("score",),
    "slow_score": ("score",),
}

#: Actions that fire on EVERY matching hook call instead of once.
_REPEATING_ACTIONS = ("slow_score",)

_CLAUSE_RE = re.compile(
    r"^(?P<action>[a-z_]+)"
    r"(?:@(?:rank|proc)(?P<rank>\d+))?"
    r"(?::(?P<trigger>epoch|step|save|req|ms)(?P<at>\d+))?$"
)


@dataclass
class FaultClause:
    action: str
    rank: int | None = None      # None = any rank
    trigger: str | None = None   # epoch | step | save | None (= first)
    at: int | None = None
    raw: str = ""
    fired: bool = False

    @property
    def repeats(self) -> bool:
        return self.action in _REPEATING_ACTIONS

    def matches(self, point: str, rank: int | None, coords: dict) -> bool:
        if point not in _ACTION_POINTS[self.action]:
            return False
        if self.fired and not self.repeats:
            return False
        if self.rank is not None and rank is not None and self.rank != rank:
            return False
        if self.trigger is None or self.trigger == "ms":
            # ``ms`` is the slow_score sleep parameter, not a trigger.
            return True
        value = coords.get(self.trigger)
        if value is None:
            return False
        # step/req triggers fire on REACHING the count (the exact value
        # may be skipped by accumulation/batch granularity); epoch and
        # save ordinals are exact.
        if self.trigger in ("step", "req"):
            return int(value) >= self.at
        return int(value) == self.at


class FaultPlan:
    """The parsed plan, bound to one rank. ``check`` matches without
    side effects beyond the fired flag + the ``fault.injected`` event;
    ``maybe_fire`` also executes self-executing actions (crash / hang /
    the sleeps)."""

    def __init__(
        self,
        clauses: list[FaultClause] | None = None,
        *,
        rank: int | None = None,
        sleep_s: float = 3.0,
        sleep_fn=time.sleep,
    ):
        self.clauses = list(clauses or [])
        self.rank = rank
        self.sleep_s = float(sleep_s)
        self._sleep = sleep_fn
        self._counts: dict[str, int] = {}

    # -- construction ---------------------------------------------------
    @classmethod
    def parse(
        cls, spec: str, *, rank: int | None = None, sleep_s: float = 3.0
    ) -> "FaultPlan":
        clauses = []
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            m = _CLAUSE_RE.match(part)
            if m is None or m.group("action") not in _ACTION_POINTS:
                raise ValueError(
                    f"Unparseable fault clause {part!r}; grammar: "
                    "ACTION[@rankR|@procR][:epochN|stepN|saveN|reqN|msM] "
                    f"with ACTION in {sorted(_ACTION_POINTS)}"
                )
            action, trigger = m.group("action"), m.group("trigger")
            if trigger == "ms" and action != "slow_score":
                raise ValueError(
                    f"Fault clause {part!r}: :msM is the slow_score "
                    "sleep parameter, not a trigger"
                )
            if trigger == "req" and "score" not in _ACTION_POINTS[action]:
                raise ValueError(
                    f"Fault clause {part!r}: :reqN only triggers "
                    "serving-side (score-point) actions"
                )
            clauses.append(
                FaultClause(
                    action=m.group("action"),
                    rank=int(m.group("rank")) if m.group("rank") else None,
                    trigger=m.group("trigger"),
                    at=int(m.group("at")) if m.group("at") else None,
                    raw=part,
                )
            )
        return cls(clauses, rank=rank, sleep_s=sleep_s)

    @classmethod
    def from_env(cls, env=None) -> "FaultPlan":
        env = env if env is not None else os.environ
        return cls.parse(
            env.get("DCT_FAULT_SPEC", ""),
            rank=_rank_from_env(),
            sleep_s=float(env.get("DCT_FAULT_SLEEP_S") or 3.0),
        )

    @property
    def enabled(self) -> bool:
        return bool(self.clauses)

    @property
    def fired_count(self) -> int:
        return sum(1 for c in self.clauses if c.fired)

    # -- hook surface ---------------------------------------------------
    def check(self, point: str, **coords) -> FaultClause | None:
        """Match (and mark fired) the first armed clause for ``point``.
        ``save`` ordinals are counted here so callers stay stateless."""
        if not self.clauses:
            return None
        if point == "save":
            self._counts["save"] = self._counts.get("save", 0) + 1
            coords.setdefault("save", self._counts["save"])
        # A repeating clause (slow_score) matches EVERY call at its
        # point: first-match-wins would permanently shadow any one-shot
        # clause listed after it ("slow_score,crash_worker:req50" would
        # never crash). One-shot matches therefore take priority; the
        # repeating clause covers every call they don't claim.
        matched = [
            c for c in self.clauses
            if c.matches(point, self.rank, coords)
        ]
        if not matched:
            return None
        one_shot = [c for c in matched if not c.repeats]
        clause = (one_shot or matched)[0]
        already_fired = clause.fired
        clause.fired = True
        # On the record BEFORE the fault acts: a crash must not be able
        # to outrun its own evidence. Repeating clauses (slow_score)
        # emit once — a per-flush disk append would itself distort the
        # overload they model.
        if not already_fired:
            _events.get_default().emit(
                "fault", "fault.injected",
                action=clause.action, point=point, spec=clause.raw,
                injected_rank=self.rank,
                **{k: v for k, v in coords.items() if v is not None},
            )
        return clause

    def maybe_fire(self, point: str, *, pre_exit=None, **coords):
        """``check`` + execute. ``pre_exit`` runs before a ``crash``
        exits (the trainer joins its in-flight resume save so the crash
        leaves a deterministic resume point). Returns the clause for
        caller-executed actions (``nan``), None otherwise."""
        clause = self.check(point, **coords)
        if clause is None:
            return None
        if clause.action in ("crash", "crash_save", "crash_worker", "hang"):
            # ``os._exit`` skips atexit and a hang never reaches it:
            # drain buffered telemetry NOW so the fault.injected record
            # (and every record before it) survives the fault it
            # precedes — the buffered-writer durability contract.
            try:
                from dct_tpu.observability.buffered import (
                    flush_all_appenders,
                )

                flush_all_appenders()
            except Exception:  # noqa: BLE001 — the fault must still fire
                pass
        if clause.action == "crash":
            if pre_exit is not None:
                try:
                    pre_exit()
                except Exception:  # noqa: BLE001 — exit anyway: it's a crash
                    pass
            os._exit(FAULT_CRASH_EXIT)
        if clause.action in ("crash_save", "crash_worker"):
            os._exit(FAULT_CRASH_EXIT)
        if clause.action == "hang":
            while True:  # PID-alive, progress-dead: the monitor's case
                self._sleep(60.0)
        if clause.action == "slow_score":
            # :msM parameterizes the per-flush sleep; default falls back
            # to the plan-wide DCT_FAULT_SLEEP_S like the other sleeps.
            self._sleep(
                clause.at / 1e3 if clause.trigger == "ms" and clause.at
                else self.sleep_s
            )
            return None
        if clause.action in ("slow_save", "slow_epoch"):
            self._sleep(self.sleep_s)
            return None
        return clause  # nan: the caller poisons its staged arrays


# ----------------------------------------------------------------------
# Process-default plan, mirroring events.get_default(): the trainer
# installs its config-built plan; layers without config plumbing (the
# checkpoint manager) resolve the same instance so save ordinals and
# fired flags are shared. Standalone processes parse the env lazily.

_explicit: FaultPlan | None = None
_cached: tuple[tuple, FaultPlan] | None = None

_ENV_KEYS = ("DCT_FAULT_SPEC", "DCT_FAULT_SLEEP_S", "DCT_PROCESS_ID", "NODE_RANK")


def set_default(plan: FaultPlan | None) -> None:
    global _explicit
    _explicit = plan


def get_default() -> FaultPlan:
    global _cached
    if _explicit is not None:
        return _explicit
    key = tuple(os.environ.get(k) for k in _ENV_KEYS)
    if _cached is not None and _cached[0] == key:
        return _cached[1]
    try:
        plan = FaultPlan.from_env()
    except ValueError:
        # A malformed ambient spec must not crash layers that merely
        # consult the plan; the trainer's explicit parse stays loud.
        plan = FaultPlan()
    _cached = (key, plan)
    return plan
