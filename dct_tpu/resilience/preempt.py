"""Graceful preemption: the rank-side SIGTERM contract.

TPU fleets reclaim hosts with a SIGTERM and a grace window; the
reference's trainer dies mid-step and the cycle loses everything since
the last manual restart. The contract here:

1. SIGTERM sets a flag (:class:`PreemptionGuard` — the handler does
   NOTHING else: event/span emitters take locks the interrupted main
   thread may hold, so all I/O happens later at a safe point);
2. the trainer finishes the in-flight step/span, makes the resume
   checkpoint durable (joining the async writer), emits
   ``preempt.signal_received`` + ``preempt.checkpoint_saved``, and
   raises :class:`PreemptedError`;
3. the entry point maps that to ``EXIT_PREEMPTED`` (75), which the
   supervisor classifies as resumable-not-failed: relaunch with resume,
   no restart budget consumed.

The guard installs only on the main thread (Python delivers signals
there; workers get a no-op guard that simply never requests) and always
restores the previous handler, so nested rigs (pytest, Airflow workers)
keep their own SIGTERM semantics.
"""

from __future__ import annotations

import signal
import threading
import time


class PreemptedError(RuntimeError):
    """Training stopped cooperatively on SIGTERM with a durable resume
    checkpoint — resumable-not-failed; map to ``EXIT_PREEMPTED``."""


class PreemptionGuard:
    def __init__(self, *, clock=time.time):
        self._clock = clock
        self.requested = False
        self.signal_time: float | None = None
        self._prev = None
        self._installed = False

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self  # signals never arrive here; stay a no-op guard
        try:
            self._prev = signal.signal(signal.SIGTERM, self._handler)
            self._installed = True
        except (ValueError, OSError):
            pass  # embedded interpreter without signal support
        return self

    def _handler(self, signum, frame):
        # Async-signal-safe by construction: two attribute writes, no
        # locks, no I/O. Everything observable happens at the trainer's
        # next safe point.
        self.requested = True
        self.signal_time = self._clock()

    def request(self) -> None:
        """Programmatic preemption — the multi-tenant scheduler's lease
        revocation (dct_tpu.scheduler). Sets the SAME flag the SIGTERM
        handler sets, so the trainer's safe-point contract (finish the
        step, durable snapshot, :class:`PreemptedError`) is identical;
        callable from any thread (plain attribute writes, like the
        handler)."""
        self.requested = True
        self.signal_time = self._clock()

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        try:
            signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
        except (ValueError, OSError):
            pass
