"""The supervisor's failure model: exit-code contract, classifier,
restart policy.

The reference's only failure story is "the Airflow task goes red" —
every nonzero exit looks identical, so the orchestrator cannot tell a
preempted host (relaunch immediately, nothing is wrong) from a NaN'd
run (relaunching re-diverges deterministically) from a broken ssh
control plane (retrying the *training* fixes nothing). The contract
here gives each failure family a distinct exit code, and
:func:`classify_failure` maps a world's exit codes (+ what the launcher
observed: stall-kills, timeouts) to a restart decision.

Exit-code contract (chosen outside the shell's reserved ranges; 75 is
BSD ``EX_TEMPFAIL`` — "temporary failure, retry"):

=======================  ====  ==============================================
constant                 code  meaning
=======================  ====  ==============================================
EXIT_PREEMPTED            75   graceful preemption: the rank saved a resume
                               checkpoint and exited on SIGTERM (resumable,
                               does NOT consume restart budget)
EXIT_HEALTH_HALT          76   training-health halt (NaN/spike under a
                               halting policy): deterministic — relaunching
                               from the same checkpoint re-diverges, so the
                               supervisor gives up immediately
EXIT_INFRA_HEALTHCHECK    21   a host failed the pre-launch healthcheck
                               (launcher scripts) — infra, not training
EXIT_INFRA_CLEANUP        22   the zombie-cleanup exec transport failed
                               (ssh/docker unreachable) — infra
faults.FAULT_CRASH_EXIT  117   an injected ``crash`` — classified as an
                               ordinary crash (that is the point of drills)
=======================  ====  ==============================================

Negative return codes are signal deaths — normally the launcher's own
fail-fast/stall-kill escalation (SIGTERM -> SIGKILL) reaping survivors
of the real failure, so they never dominate classification.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

EXIT_PREEMPTED = 75
EXIT_HEALTH_HALT = 76
EXIT_INFRA_HEALTHCHECK = 21
EXIT_INFRA_CLEANUP = 22

#: Classifications whose failures a supervisor may relaunch-and-resume.
RESUMABLE = ("preempted", "crash", "hang", "infra")

#: Classifications that do not consume the restart budget: routine
#: events (Podracer-style fleets treat preemption as weather, not
#: failure), bounded instead by the supervisor's absolute attempt cap.
FREE_RESTARTS = ("preempted",)


def classify_failure(
    returncodes,
    *,
    stall_killed: bool = False,
    timed_out: bool = False,
) -> str:
    """One world -> one classification.

    Priority: infra > health_halt > hang > crash > preempted. A real
    positive failure code dominates the peers our own escalation killed
    (negative codes) and any rank that managed a graceful 75 on the way
    down — the world crashed, not preempted.
    """
    codes = [int(c) for c in returncodes]
    if codes and all(c == 0 for c in codes):
        return "success"
    if any(c in (EXIT_INFRA_HEALTHCHECK, EXIT_INFRA_CLEANUP) for c in codes):
        return "infra"
    if any(c == EXIT_HEALTH_HALT for c in codes):
        return "health_halt"
    if stall_killed or timed_out:
        return "hang"
    hard = [c for c in codes if c > 0 and c != EXIT_PREEMPTED]
    if hard:
        return "crash"
    if any(c == EXIT_PREEMPTED for c in codes):
        return "preempted"
    # Only signal deaths and no observed cause: treat as a crash (an
    # external OOM-killer / operator kill is a crash from our seat).
    return "crash"


@dataclass
class RestartPolicy:
    """Exponential backoff between supervised relaunches.

    ``delay(n)`` is the pause before the (n+1)-th restart (n = restarts
    already consumed): ``backoff_s * factor**n``, stretched by up to
    ``jitter`` fractional random slack so a fleet of supervisors
    recovering from one fabric event does not thundering-herd the
    coordinator port.
    """

    max_restarts: int = 2
    backoff_s: float = 1.0
    backoff_factor: float = 2.0
    jitter: float = 0.1
    rng: object = field(default=random.random, repr=False)

    def delay(self, restarts_used: int) -> float:
        base = self.backoff_s * self.backoff_factor ** max(0, restarts_used)
        return base * (1.0 + self.jitter * self.rng())

    def allows(self, restarts_used: int, classification: str) -> bool:
        """May the supervisor relaunch after this failure?"""
        if classification not in RESUMABLE:
            return False
        if classification in FREE_RESTARTS:
            return True
        return restarts_used < self.max_restarts
