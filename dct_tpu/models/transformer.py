"""Transformer family: long-context sequence models over the weather stream.

The reference scales only the batch axis of a tabular MLP (SURVEY §2.3); this
family adds the capability its design lacks — sequence models whose context
is sharded over the mesh — built TPU-first:

- attention is pluggable (:mod:`dct_tpu.ops.attention`): dense for short
  contexts, blockwise for long single-chip contexts, ring attention over the
  ``seq`` mesh axis for contexts larger than one chip;
- tensor parallelism is expressed by PARAM NAMES: projection modules are
  named ``qkv_proj`` / ``o_proj`` / ``ffn_in`` / ``ffn_out`` and
  :mod:`dct_tpu.parallel.sharding_rules` maps those names to
  ``PartitionSpec``s over the ``model`` axis (megatron-style column/row
  split — one all-reduce per block, inserted by XLA, riding ICI);
- everything is a pure function of (params, x, rng): same train step, same
  Trainer, same checkpoint/tracking path as the flagship MLP.

``WeatherTransformer`` is the concrete member: a pre-LN encoder over a
window of ``seq_len`` past weather rows, mean-pooled into the same
2-class rain head as the reference's classifier (same loss, same metrics).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from flax import linen as nn

from dct_tpu.models.mlp import TorchStyleDense


def sincos_positions(seq_len: int, d_model: int) -> np.ndarray:
    """Fixed sinusoidal position table [S, D] (no param => nothing to shard)."""
    pos = np.arange(seq_len)[:, None].astype(np.float32)
    i = np.arange(d_model // 2)[None, :].astype(np.float32)
    ang = pos / np.power(10000.0, 2.0 * i / d_model)
    out = np.zeros((seq_len, d_model), np.float32)
    out[:, 0::2] = np.sin(ang)
    out[:, 1::2] = np.cos(ang)
    return out


def rope_tables(seq_len: int, head_dim: int) -> tuple[np.ndarray, np.ndarray]:
    """Rotary-embedding cos/sin tables [S, Dh/2] (RoFormer/Llama-style,
    rotate-half pairing). Static numpy — nothing to shard, and the tables
    bake into the compiled program as constants."""
    half = head_dim // 2
    inv = 1.0 / np.power(10000.0, np.arange(half, dtype=np.float32) / half)
    ang = np.arange(seq_len, dtype=np.float32)[:, None] * inv[None, :]
    return np.cos(ang), np.sin(ang)


def apply_rope(x, cos, sin):
    """Rotate q or k [..., T, Dh] by per-position angles ([T, Dh/2] cos/sin,
    broadcast over batch/head axes). Positions are GLOBAL sequence
    positions, so the rotation composes unchanged with both SP engines
    (it runs on the full array before the seq-sharded attention op) and
    with GQA (k rotates at its grouped head count)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = jnp.asarray(cos, x.dtype)
    sin = jnp.asarray(sin, x.dtype)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )


class MultiHeadAttention(nn.Module):
    """MHA with injected attention kernel. Projections are single fused
    qkv (column-parallel over ``model``) + output (row-parallel).

    ``n_kv_heads`` < ``n_heads`` selects grouped-query attention (GQA):
    K/V carry fewer heads, each serving ``n_heads/n_kv_heads`` query
    heads — the standard KV-bandwidth lever (smaller qkv projection,
    KV HBM reads divided by the group size in the Pallas kernel, smaller
    KV payloads on the SP engines' collectives). The fused output dim is
    laid out GROUP-major ``(G, Hg + 2, Dh)`` (G = kv heads, Hg = q heads
    per group): a ``model``-axis shard of the kernel's output dim is
    GROUP-aligned, so each tensor-parallel shard owns whole groups —
    q heads together with their kv head, no resharding before attention.
    With ``n_kv_heads == n_heads`` this degenerates to exactly the
    classic ``(H, 3, Dh)`` layout, so MHA checkpoints are unchanged."""

    d_model: int
    n_heads: int
    attn_fn: object  # (q [B,H,T,D], k/v [B,G,T,D]) -> [B,H,T,D]
    dtype: jnp.dtype = jnp.float32
    n_kv_heads: int | None = None
    rope: bool = False

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        head_dim = self.d_model // self.n_heads
        g = self.n_kv_heads or self.n_heads
        if self.n_heads % g:
            raise ValueError(
                f"n_kv_heads ({g}) must divide n_heads ({self.n_heads})"
            )
        hg = self.n_heads // g
        qkv = TorchStyleDense(
            (self.n_heads + 2 * g) * head_dim, dtype=self.dtype,
            name="qkv_proj",
        )(x)
        qkv = qkv.reshape(b, t, g, hg + 2, head_dim)
        # [B, T, G, Hg+2, Dh]: per group, Hg q heads then one k and one v.
        q = qkv[:, :, :, :hg].reshape(b, t, self.n_heads, head_dim)
        q = jnp.swapaxes(q, 1, 2)  # [B, H, T, Dh]
        k = jnp.swapaxes(qkv[:, :, :, hg], 1, 2)  # [B, G, T, Dh]
        v = jnp.swapaxes(qkv[:, :, :, hg + 1], 1, 2)
        if self.rope:
            if head_dim % 2:
                raise ValueError(
                    f"rope needs an even head_dim (got {head_dim})"
                )
            cos, sin = rope_tables(t, head_dim)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        o = self.attn_fn(q, k, v)  # [B, H, T, D]
        o = jnp.moveaxis(o, 1, 2).reshape(b, t, self.d_model)
        return TorchStyleDense(self.d_model, dtype=self.dtype, name="o_proj")(o)


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dropout: float
    attn_fn: object
    dtype: jnp.dtype = jnp.float32
    n_kv_heads: int | None = None
    rope: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        # ``train`` is positional-or-keyword (not kw-only) so nn.remat's
        # static_argnums can reach it (WeatherTransformer's remat path).
        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        h = MultiHeadAttention(
            self.d_model, self.n_heads, self.attn_fn, dtype=self.dtype,
            n_kv_heads=self.n_kv_heads, rope=self.rope, name="attn",
        )(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype, name="ln_ffn")(x)
        h = TorchStyleDense(self.d_ff, dtype=self.dtype, name="ffn_in")(h)
        h = nn.gelu(h)
        h = TorchStyleDense(self.d_model, dtype=self.dtype, name="ffn_out")(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        return x + h


class _StageBlocks(nn.Module):
    """One pipeline stage: ``layers_per_stage`` identical pre-LN blocks.

    Deterministic (no dropout): the PP family applies dropout OUTSIDE the
    pipelined region so stages need no rng threading through shard_map.
    """

    d_model: int
    n_heads: int
    d_ff: int
    layers_per_stage: int
    attn_fn: object
    dtype: jnp.dtype = jnp.float32
    remat: bool = False
    n_kv_heads: int | None = None
    rope: bool = False

    @nn.compact
    def __call__(self, h):
        block_cls = (
            nn.remat(TransformerBlock, static_argnums=(2,))
            if self.remat
            else TransformerBlock
        )
        for i in range(self.layers_per_stage):
            h = block_cls(
                self.d_model, self.n_heads, self.d_ff, 0.0, self.attn_fn,
                dtype=self.dtype, n_kv_heads=self.n_kv_heads,
                rope=self.rope, name=f"block_{i}",
            )(h, False)
        return h


class WeatherTransformerPP(nn.Module):
    """Pipeline-parallel transformer: ``n_layers`` grouped into
    ``n_stages`` homogeneous stages streamed GPipe-style over the mesh's
    ``pipe`` axis (:func:`dct_tpu.parallel.pipeline.pipeline_apply`).

    Stage params live in ONE stacked pytree param named ``pp_stages``
    (leading dim = stage), which the sharding rules place
    ``P("pipe", <TP name-rule spec>)`` — each pipeline device holds one
    stage, and the stage's projection kernels keep their megatron-style
    ``model``-axis split. Composes with DP (microbatch rows shard over
    ``data``) AND TP: pipeline_apply's shard_map is manual only over
    pipe/data, so the model axis stays auto and the compiler inserts the
    per-block TP collectives inside each stage. Attention is the
    single-shard dense/blockwise/flash path (no seq axis). Embedding,
    dropout, final LN and the classifier head run outside the pipelined
    region (replicated).

    Without a mesh (or ``pipe`` = 1, or the batch-1 flax init trace) the
    stages apply sequentially — the same function, used by tests as the
    pipeline oracle.
    """

    input_dim: int
    seq_len: int
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    num_classes: int = 2
    dropout: float = 0.1
    n_stages: int = 2
    n_microbatches: int | None = None
    attn_fn: object = None
    mesh: object = None
    remat: bool = False
    compute_dtype: jnp.dtype = jnp.float32
    n_kv_heads: int | None = None
    pos_embed: str = "sincos"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        from dct_tpu.ops.attention import make_attention_fn
        from dct_tpu.parallel.pipeline import pipeline_apply

        if self.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers={self.n_layers} must divide into "
                f"n_stages={self.n_stages} homogeneous stages"
            )
        attn_fn = self.attn_fn or make_attention_fn(None)
        ct = self.compute_dtype
        stage_mod = _StageBlocks(
            self.d_model, self.n_heads, self.d_ff,
            self.n_layers // self.n_stages, attn_fn, dtype=ct,
            remat=self.remat, n_kv_heads=self.n_kv_heads,
            rope=self.pos_embed == "rope",
        )

        def init_stages(rng):
            zeros = jnp.zeros((1, self.seq_len, self.d_model), ct)
            rngs = jax.random.split(rng, self.n_stages)
            return jax.vmap(
                lambda r: stage_mod.init(r, zeros)["params"]
            )(rngs)

        stacked = self.param("pp_stages", init_stages)

        x = jnp.asarray(x, ct)
        h = TorchStyleDense(self.d_model, dtype=ct, name="in_proj")(x)
        if self.pos_embed != "rope":  # rope rotates q/k inside attention
            h = h + jnp.asarray(
                sincos_positions(self.seq_len, self.d_model), ct
            )
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)

        mesh = self.mesh
        b = h.shape[0]
        pipe = mesh.shape.get("pipe", 1) if mesh is not None else 1
        m = self.n_microbatches or max(pipe, 1)
        dp = mesh.shape.get("data", 1) if mesh is not None else 1
        if pipe > 1 and b % m == 0 and (b // m) % dp == 0:
            from dct_tpu.parallel.shard_map_compat import (
                PARTIAL_AUTO_SHARD_MAP,
            )

            if PARTIAL_AUTO_SHARD_MAP:
                h = pipeline_apply(
                    lambda p, a: stage_mod.apply({"params": p}, a),
                    stacked, h, mesh=mesh, n_microbatches=m,
                    data_axis="data" if dp > 1 else None,
                )
            else:
                # jax 0.4.x: partial-manual shard_map cannot lower — run
                # the SAME tick schedule as a vmapped GSPMD scan (the
                # stage dim stays a real array axis sharded P('pipe')).
                from dct_tpu.parallel.pipeline import gpipe_tick_apply

                h = gpipe_tick_apply(
                    lambda p, a: stage_mod.apply({"params": p}, a),
                    stacked, h, n_microbatches=m,
                )
        elif pipe > 1 and b >= m * dp:
            # A real batch that cannot tile the configured pipeline is a
            # sizing bug: running the sequential path with P('pipe')
            # params would all-gather every stage each step and silently
            # discard the pipelining the user configured.
            raise ValueError(
                f"batch {b} does not tile n_microbatches={m} x data={dp} "
                f"for the pipe={pipe} mesh; adjust batch_size or "
                "n_microbatches"
            )
        else:
            # Sequential oracle: batch-1 init trace or pipe=1.
            for i in range(self.n_stages):
                p_i = jax.tree.map(lambda a, i=i: a[i], stacked)
                h = stage_mod.apply({"params": p_i}, h)

        h = nn.LayerNorm(dtype=ct, name="ln_out")(h)
        pooled = h.mean(axis=1)
        logits = TorchStyleDense(self.num_classes, dtype=ct, name="head")(
            pooled
        )
        return jnp.asarray(logits, jnp.float32)


class WeatherTransformer(nn.Module):
    """Encoder over [B, S, F] windows -> [B, num_classes] rain logits.

    ``per_position``: decoder-style per-position head — [B, S, classes]
    logits, one next-step forecast per position (pair with a CAUSAL
    ``attn_fn`` so position t sees only rows <= t; the causal family in
    the registry wires both). ``horizon`` > 1 widens that head to DIRECT
    multi-horizon forecasting: [B, S, horizon, classes] logits, position
    t predicting steps t+1..t+horizon in one pass."""

    input_dim: int
    seq_len: int
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    num_classes: int = 2
    dropout: float = 0.1
    attn_fn: object = None  # default set in __call__ (dense/blockwise)
    per_position: bool = False
    horizon: int = 1
    remat: bool = False
    compute_dtype: jnp.dtype = jnp.float32
    n_kv_heads: int | None = None
    pos_embed: str = "sincos"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        from dct_tpu.ops.attention import make_attention_fn

        if self.d_model % 2 or self.d_model % self.n_heads:
            raise ValueError(
                f"d_model={self.d_model} must be even (sinusoidal positions)"
                f" and divisible by n_heads={self.n_heads}"
            )
        attn_fn = self.attn_fn or make_attention_fn(None)
        x = jnp.asarray(x, self.compute_dtype)
        h = TorchStyleDense(self.d_model, dtype=self.compute_dtype, name="in_proj")(x)
        if self.pos_embed != "rope":  # rope rotates q/k inside attention
            h = h + jnp.asarray(
                sincos_positions(self.seq_len, self.d_model),
                self.compute_dtype,
            )
        # Activation rematerialization: store only block BOUNDARIES on the
        # forward pass and recompute block internals in backward — the
        # HBM-for-FLOPs trade that unlocks long sequences (activation
        # memory drops from O(layers * seq * d_ff) to O(layers * seq *
        # d_model)). Param tree and math are identical (static_argnums=2
        # is ``train``; self counts as 0 in flax's indexing).
        block_cls = (
            nn.remat(TransformerBlock, static_argnums=(2,))
            if self.remat
            else TransformerBlock
        )
        for i in range(self.n_layers):
            h = block_cls(
                self.d_model,
                self.n_heads,
                self.d_ff,
                self.dropout,
                attn_fn,
                dtype=self.compute_dtype,
                n_kv_heads=self.n_kv_heads,
                rope=self.pos_embed == "rope",
                name=f"block_{i}",
            )(h, train)
        h = nn.LayerNorm(dtype=self.compute_dtype, name="ln_out")(h)
        if self.per_position and self.horizon > 1:
            logits = TorchStyleDense(
                self.num_classes * self.horizon, dtype=self.compute_dtype,
                name="head",
            )(h).reshape(*h.shape[:-1], self.horizon, self.num_classes)
        elif self.per_position:
            logits = TorchStyleDense(
                self.num_classes, dtype=self.compute_dtype, name="head"
            )(h)  # [B, S, classes]
        else:
            pooled = h.mean(axis=1)
            logits = TorchStyleDense(
                self.num_classes, dtype=self.compute_dtype, name="head"
            )(pooled)
        return jnp.asarray(logits, jnp.float32)
