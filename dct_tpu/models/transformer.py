"""Transformer family: long-context sequence models over the weather stream.

The reference scales only the batch axis of a tabular MLP (SURVEY §2.3); this
family adds the capability its design lacks — sequence models whose context
is sharded over the mesh — built TPU-first:

- attention is pluggable (:mod:`dct_tpu.ops.attention`): dense for short
  contexts, blockwise for long single-chip contexts, ring attention over the
  ``seq`` mesh axis for contexts larger than one chip;
- tensor parallelism is expressed by PARAM NAMES: projection modules are
  named ``qkv_proj`` / ``o_proj`` / ``ffn_in`` / ``ffn_out`` and
  :mod:`dct_tpu.parallel.sharding_rules` maps those names to
  ``PartitionSpec``s over the ``model`` axis (megatron-style column/row
  split — one all-reduce per block, inserted by XLA, riding ICI);
- everything is a pure function of (params, x, rng): same train step, same
  Trainer, same checkpoint/tracking path as the flagship MLP.

``WeatherTransformer`` is the concrete member: a pre-LN encoder over a
window of ``seq_len`` past weather rows, mean-pooled into the same
2-class rain head as the reference's classifier (same loss, same metrics).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from flax import linen as nn

from dct_tpu.models.mlp import TorchStyleDense


def sincos_positions(seq_len: int, d_model: int) -> np.ndarray:
    """Fixed sinusoidal position table [S, D] (no param => nothing to shard)."""
    pos = np.arange(seq_len)[:, None].astype(np.float32)
    i = np.arange(d_model // 2)[None, :].astype(np.float32)
    ang = pos / np.power(10000.0, 2.0 * i / d_model)
    out = np.zeros((seq_len, d_model), np.float32)
    out[:, 0::2] = np.sin(ang)
    out[:, 1::2] = np.cos(ang)
    return out


class MultiHeadAttention(nn.Module):
    """MHA with injected attention kernel. Projections are single fused
    qkv (column-parallel over ``model``) + output (row-parallel)."""

    d_model: int
    n_heads: int
    attn_fn: object  # (q, k, v) [B,H,T,D] -> [B,H,T,D]
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        head_dim = self.d_model // self.n_heads
        qkv = TorchStyleDense(3 * self.d_model, dtype=self.dtype, name="qkv_proj")(x)
        # Fused output dim is laid out (H, 3, Dh) so a ``model``-axis shard
        # of the kernel's output dim is HEAD-aligned: each tensor-parallel
        # shard owns whole heads' q,k,v — no cross-shard resharding before
        # attention.
        qkv = qkv.reshape(b, t, self.n_heads, 3, head_dim)
        # [B, T, H, 3, Dh] -> 3 x [B, H, T, Dh]
        q, k, v = (jnp.swapaxes(qkv[:, :, :, j], 1, 2) for j in range(3))
        o = self.attn_fn(q, k, v)  # [B, H, T, D]
        o = jnp.moveaxis(o, 1, 2).reshape(b, t, self.d_model)
        return TorchStyleDense(self.d_model, dtype=self.dtype, name="o_proj")(o)


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dropout: float
    attn_fn: object
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool):
        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        h = MultiHeadAttention(
            self.d_model, self.n_heads, self.attn_fn, dtype=self.dtype,
            name="attn",
        )(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype, name="ln_ffn")(x)
        h = TorchStyleDense(self.d_ff, dtype=self.dtype, name="ffn_in")(h)
        h = nn.gelu(h)
        h = TorchStyleDense(self.d_model, dtype=self.dtype, name="ffn_out")(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        return x + h


class WeatherTransformer(nn.Module):
    """Encoder over [B, S, F] windows -> [B, num_classes] rain logits."""

    input_dim: int
    seq_len: int
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    num_classes: int = 2
    dropout: float = 0.1
    attn_fn: object = None  # default set in __call__ (dense/blockwise)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        from dct_tpu.ops.attention import make_attention_fn

        if self.d_model % 2 or self.d_model % self.n_heads:
            raise ValueError(
                f"d_model={self.d_model} must be even (sinusoidal positions)"
                f" and divisible by n_heads={self.n_heads}"
            )
        attn_fn = self.attn_fn or make_attention_fn(None)
        x = jnp.asarray(x, self.compute_dtype)
        h = TorchStyleDense(self.d_model, dtype=self.compute_dtype, name="in_proj")(x)
        h = h + jnp.asarray(
            sincos_positions(self.seq_len, self.d_model), self.compute_dtype
        )
        for i in range(self.n_layers):
            h = TransformerBlock(
                self.d_model,
                self.n_heads,
                self.d_ff,
                self.dropout,
                attn_fn,
                dtype=self.compute_dtype,
                name=f"block_{i}",
            )(h, train=train)
        h = nn.LayerNorm(dtype=self.compute_dtype, name="ln_out")(h)
        pooled = h.mean(axis=1)
        logits = TorchStyleDense(
            self.num_classes, dtype=self.compute_dtype, name="head"
        )(pooled)
        return jnp.asarray(logits, jnp.float32)
