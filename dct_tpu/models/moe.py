"""Mixture-of-Experts family: switch-routed FFN with expert parallelism.

The reference has exactly one dense MLP and one parallelism axis (2-rank
DDP, SURVEY §2.3); this family completes the mesh's parallelism matrix —
experts shard over the ``model`` axis (expert parallelism), composing with
batch DP and attention TP/SP in the same jitted step.

TPU-first routing: no ragged tensors, no data-dependent shapes. Two
dispatch engines share one router and one capacity policy:

- ``einsum`` — dense one-hot dispatch/combine einsums with a STATIC
  per-expert capacity:

      dispatch [N_tokens, E, C]  (one-hot: token -> (expert, slot))
      expert_in = einsum('nec,nd->ecd', dispatch, tokens)
      expert_out = per-expert FFN batched over E      <- MXU batched GEMMs
      out = einsum('nec,ecd->nd', dispatch, expert_out) * gate

  Exact arrival-order capacity semantics, but the dispatch tensors are
  O(N·E·C) — it stops scaling once E·C outgrows a few hundred.

- ``sorted`` — segment-based dispatch with the same static shapes and
  O(N log N + N·D) cost: stable-sort tokens by expert, rank them within
  their expert (bincount prefix sums), scatter the first ``capacity``
  of each into a [E, C, D] expert buffer, run the batched GEMMs, gather
  back and unsort. Under expert parallelism the buffer is exchanged with
  an EXPLICIT ``lax.all_to_all`` over the ``model`` axis inside a
  shard_map: each model-rank routes its 1/ep slice of the local tokens
  (so expert compute is sharded, not replicated), sends per-destination
  slots, computes its own experts, reverses the exchange, and
  all-gathers the combined outputs — the canonical MoE a2a pipeline,
  visible as ``all-to-all`` in the compiled HLO (asserted by tests).

Tokens over capacity are dropped (their dispatch row is zero); the block's
residual connection passes them through unchanged — standard switch
behavior. Expert weights are [E, D, F] tensors named ``experts_in`` /
``experts_out``; the sharding rules place them ``P("model", None, None)``.

A load-balance auxiliary loss (Switch Transformer's f·P dot) is returned
via ``self.sow("aux_loss", ...)``; the train step folds every sown
``aux_loss`` into the objective, weighted by ``router_aux_weight``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dct_tpu.parallel.shard_map_compat import shard_map
from jax import lax
from jax.sharding import PartitionSpec as P
from flax import linen as nn

from dct_tpu.models.mlp import TorchStyleDense, torch_linear_init
from dct_tpu.models.transformer import MultiHeadAttention, sincos_positions


def _expert_ffn(batch, w_in, b_in, w_out, b_out):
    """Batched per-expert GEMMs: [..., E, C, D] x [E, D, F] — the MXU hot
    path shared by both dispatch engines."""
    h = jnp.einsum("...ecd,edf->...ecf", batch, w_in)
    h = nn.gelu(h + b_in[:, None, :])
    out = jnp.einsum("...ecf,efd->...ecd", h, w_out)
    return out + b_out[:, None, :]


def _sorted_moe(tokens, expert_idx, gate, w_in, b_in, w_out, b_out, *,
                e_total: int, capacity: int, ep_axis: str | None = None):
    """Segment-based switch dispatch on LOCAL arrays.

    tokens [N, D] (compute dtype), expert_idx [N] int32, gate [N]
    (compute dtype); expert weights are the LOCAL shard [E_local, ...]
    (E_local == e_total when not expert-parallel). With ``ep_axis`` the
    [e_total, C, D] buffer is reshaped [ep, E_local, C, D] and exchanged
    with ``lax.all_to_all`` so each rank computes only its own experts.
    """
    n, d = tokens.shape
    e_local = w_in.shape[0]
    ep = e_total // e_local

    order = jnp.argsort(expert_idx)  # stable: preserves arrival order
    sorted_e = expert_idx[order]
    counts = jnp.bincount(expert_idx, length=e_total)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(n) - starts[sorted_e]  # rank within expert
    keep = pos < capacity
    # Row e*C+c of the buffer is (expert e, slot c); dropped tokens all
    # target the sentinel row, which is sliced off before compute.
    dst = jnp.where(keep, sorted_e * capacity + pos, e_total * capacity)
    buf = jnp.zeros((e_total * capacity + 1, d), tokens.dtype)
    buf = buf.at[dst].set(tokens[order])
    expert_in = buf[:-1].reshape(e_total, capacity, d)

    if ep_axis is not None and ep > 1:
        z = expert_in.reshape(ep, e_local, capacity, d)
        # tiled=False all_to_all REMOVES the split axis and INSERTS the
        # source axis at concat_axis: [dst, le, C, d] -> [src, le, C, d]
        # (each rank keeps only its own experts' slots, one per source).
        z = lax.all_to_all(z, ep_axis, split_axis=0, concat_axis=0)
        out_e = _expert_ffn(z, w_in, b_in, w_out, b_out)
        # Same exchange returns results to their source rank; the [owner,
        # le] leading dims then flatten to global-expert order.
        out_e = lax.all_to_all(out_e, ep_axis, split_axis=0, concat_axis=0)
        out_e = out_e.reshape(e_total, capacity, d)
    else:
        out_e = _expert_ffn(expert_in, w_in, b_in, w_out, b_out)

    out_flat = jnp.concatenate(
        [out_e.reshape(e_total * capacity, d), jnp.zeros((1, d), out_e.dtype)]
    )
    out_sorted = out_flat[dst] * keep[:, None].astype(out_e.dtype)
    out = out_sorted[jnp.argsort(order)]  # unsort
    return out * gate[:, None]


class MoEFFN(nn.Module):
    """Switch (top-1) mixture of expert FFNs over flattened tokens.

    ``dispatch``: 'einsum' | 'sorted' | 'auto' (module docstring); 'auto'
    picks sorted once the one-hot dispatch tensors would dominate.
    ``mesh`` routes the sorted engine through its shard_map/all_to_all
    path when the ``model`` (expert) axis — or any token axis — is
    populated; without a mesh the engine runs single-shard.

    ``top_k``: 1 = switch routing (raw top prob as gate); k > 1 =
    GShard-style top-k — each token goes to its k best experts with
    gates normalized over the k choices, expressed as k*N dispatch
    entries ordered choice-major so first choices win capacity slots
    before any second choice. Capacity scales with k
    (``cf * k * N / E``).
    """

    d_model: int
    d_ff: int
    n_experts: int
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    dtype: jnp.dtype = jnp.float32
    dispatch: str = "auto"
    mesh: object = None
    top_k: int = 1
    auto_threshold: int = 1 << 21

    @nn.compact
    def __call__(self, x):  # [B, S, D] -> [B, S, D]
        b, s, d = x.shape
        n = b * s
        e = self.n_experts
        k = self.top_k
        if not 1 <= k <= e:
            raise ValueError(f"top_k={k} must be in [1, n_experts={e}]")
        if self.dispatch not in ("auto", "sorted", "einsum"):
            raise ValueError(
                f"moe_dispatch={self.dispatch!r} must be "
                "'auto' | 'sorted' | 'einsum'"
            )
        capacity = max(1, int(self.capacity_factor * k * n / e))
        tokens = x.reshape(n, d)

        logits = TorchStyleDense(e, dtype=jnp.float32, name="router")(
            jnp.asarray(tokens, jnp.float32)
        )  # [N, E] — routing in f32: tiny matmul, decides everything
        probs = jax.nn.softmax(logits, axis=-1)
        if k == 1:
            expert_choice = jnp.argmax(probs, axis=-1)[None, :]  # [1, N]
            gate_choice = jnp.max(probs, axis=-1)[None, :]
        else:
            topv, topi = jax.lax.top_k(probs, k)  # [N, k]
            gates = topv / jnp.maximum(
                topv.sum(axis=-1, keepdims=True), 1e-9
            )
            expert_choice = topi.T  # [k, N], choice-major
            gate_choice = gates.T
        expert_idx = expert_choice[0]  # first choice: aux loss + einsum path
        gate = gate_choice[0]

        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [N, E]

        # Switch load-balance loss: E * sum_e(frac_tokens_e * mean_prob_e),
        # sown pre-weighted — the train step adds every aux_loss leaf as-is.
        frac = onehot.mean(axis=0)
        mean_prob = probs.mean(axis=0)
        self.sow(
            "aux_loss",
            "load_balance",
            self.aux_weight * e * jnp.sum(frac * mean_prob),
        )

        w_in = self.param(
            "experts_in_kernel",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(k, sh, dt, fan_in=d),
            (e, d, self.d_ff),
            jnp.float32,
        )
        b_in = self.param(
            "experts_in_bias",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(k, sh, dt, fan_in=d),
            (e, self.d_ff),
            jnp.float32,
        )
        w_out = self.param(
            "experts_out_kernel",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(
                k, sh, dt, fan_in=self.d_ff
            ),
            (e, self.d_ff, d),
            jnp.float32,
        )
        b_out = self.param(
            "experts_out_bias",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(
                k, sh, dt, fan_in=self.d_ff
            ),
            (e, d),
            jnp.float32,
        )

        ct = self.dtype
        wi, bi = jnp.asarray(w_in, ct), jnp.asarray(b_in, ct)
        wo, bo = jnp.asarray(w_out, ct), jnp.asarray(b_out, ct)

        # Flat dispatch entries, choice-major ([all 1st choices; all 2nd
        # choices; ...]): a stable sort / cumsum over this order gives
        # first choices capacity priority, the GShard convention.
        flat_idx = expert_choice.reshape(k * n).astype(jnp.int32)
        flat_gate = jnp.asarray(gate_choice.reshape(k * n), ct)

        engine = self.dispatch
        if engine == "auto":
            # One-hot dispatch materializes [kN, E, C] twice; past
            # ``auto_threshold`` (elements of that tensor) the sort-based
            # engine wins on both memory and time. Default ~2^21; set
            # DCT_MOE_AUTO_THRESHOLD (-> ModelConfig.moe_auto_threshold)
            # once measured on the target chip (bench.py's scaled_moe
            # section gives the crossover data).
            engine = (
                "sorted"
                if k * n * e * capacity >= self.auto_threshold
                else "einsum"
            )
        mesh = self.mesh
        if engine == "sorted" and mesh is not None:
            dp = mesh.shape.get("data", 1)
            sp = mesh.shape.get("seq", 1)
            ep = mesh.shape.get("model", 1)
            sharded = dp > 1 or sp > 1 or ep > 1
            ok = (
                b % dp == 0 and s % sp == 0 and e % ep == 0
                and ((b // dp) * (s // sp)) % ep == 0
            )
            if sharded and not ok:
                if b < dp:
                    # The batch-1 flax init trace cannot tile the data
                    # axis (same escape as ring_attention's dense path);
                    # the einsum engine creates identical params.
                    engine = "einsum"
                elif self.dispatch == "sorted":
                    raise ValueError(
                        f"sorted MoE dispatch cannot tile tokens [B={b}, "
                        f"S={s}] experts E={e} over mesh data={dp}, "
                        f"seq={sp}, model={ep}"
                    )
                else:
                    engine = "einsum"  # auto: fall back rather than fail
            elif sharded:
                out = self._sorted_sharded(
                    jnp.asarray(x, ct),
                    expert_choice.reshape(k, b, s),
                    jnp.asarray(gate_choice, ct).reshape(k, b, s),
                    wi, bi, wo, bo, mesh=mesh, dp=dp, sp=sp, ep=ep,
                )
                return out

        toks_ct = jnp.asarray(tokens, ct)
        if engine == "sorted":
            flat_tokens = jnp.tile(toks_ct, (k, 1)) if k > 1 else toks_ct
            out2 = _sorted_moe(
                flat_tokens, flat_idx, flat_gate, wi, bi, wo, bo,
                e_total=e, capacity=capacity,
            )
            out = out2.reshape(k, n, d).sum(axis=0) if k > 1 else out2
            return out.reshape(b, s, d)

        # Slot of each entry within its expert (arrival order over the
        # choice-major flat entries).
        onehot_f = jax.nn.one_hot(flat_idx, e, dtype=jnp.float32)
        position = jnp.cumsum(onehot_f, axis=0) - onehot_f  # [kN, E]
        keep = (position < capacity).astype(jnp.float32) * onehot_f
        slot = jax.nn.one_hot(
            jnp.sum(position * onehot_f, axis=-1).astype(jnp.int32),
            capacity,
            dtype=jnp.float32,
        )  # [kN, C]
        dispatch = keep[:, :, None] * slot[:, None, :]  # [kN, E, C]

        disp = jnp.asarray(dispatch, ct)
        toks = jnp.tile(toks_ct, (k, 1)) if k > 1 else toks_ct
        expert_in = jnp.einsum("nec,nd->ecd", disp, toks)  # [E, C, D]
        h = jnp.einsum("ecd,edf->ecf", expert_in, wi)
        h = nn.gelu(h + bi[:, None, :])
        out_e = jnp.einsum("ecf,efd->ecd", h, wo)
        out_e = out_e + bo[:, None, :]
        out2 = jnp.einsum("nec,ecd->nd", disp, out_e)
        out2 = out2 * flat_gate[:, None]
        out = out2.reshape(k, n, d).sum(axis=0) if k > 1 else out2
        return out.reshape(b, s, d)

    def _sorted_sharded(self, x, expert_choice, gate_choice, wi, bi, wo,
                        bo, *, mesh, dp: int, sp: int, ep: int):
        """Sorted dispatch under the mesh: shard_map over (data, seq,
        model). Each model-rank routes its 1/ep slice of the local tokens
        (expert compute is SHARDED, not replicated), exchanges expert
        buffers with lax.all_to_all, and all-gathers the combined outputs
        back to replicated-over-model activations. ``expert_choice`` /
        ``gate_choice`` are [k, B, S] (k routing choices per token)."""
        b, s, d = x.shape
        e = self.n_experts
        k = expert_choice.shape[0]
        n_local = (b // dp) * (s // sp)
        chunk = n_local // ep
        cap = max(1, int(self.capacity_factor * k * chunk / e))

        def body(xb, ei, gt, wi, bi, wo, bo):
            toks = xb.reshape(-1, d)
            ei = ei.reshape(k, -1).astype(jnp.int32)
            gt = gt.reshape(k, -1)
            r = lax.axis_index("model")
            tok_my = lax.dynamic_slice_in_dim(toks, r * chunk, chunk, 0)
            ei_my = lax.dynamic_slice_in_dim(ei, r * chunk, chunk, 1)
            gt_my = lax.dynamic_slice_in_dim(gt, r * chunk, chunk, 1)
            flat_tokens = (
                jnp.tile(tok_my, (k, 1)) if k > 1 else tok_my
            )
            out2 = _sorted_moe(
                flat_tokens, ei_my.reshape(k * chunk),
                gt_my.reshape(k * chunk), wi, bi, wo, bo,
                e_total=e, capacity=cap, ep_axis="model",
            )
            out_my = (
                out2.reshape(k, chunk, d).sum(axis=0) if k > 1 else out2
            )
            out = lax.all_gather(out_my, "model", axis=0, tiled=True)
            return out.reshape(xb.shape)

        # check_vma=False: the closing all_gather makes the output
        # replicated over ``model``, but the vma type system cannot prove
        # value-equality after a collective; numerics are pinned against
        # the single-shard engine by tests.
        return shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P("data", "seq", None),
                P(None, "data", "seq"), P(None, "data", "seq"),
                P("model", None, None), P("model", None),
                P("model", None, None), P("model", None),
            ),
            out_specs=P("data", "seq", None),
            check_vma=False,
        )(x, expert_choice, gate_choice, wi, bi, wo, bo)


class MoEBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    n_experts: int
    capacity_factor: float
    dropout: float
    attn_fn: object
    aux_weight: float = 0.01
    dtype: jnp.dtype = jnp.float32
    dispatch: str = "auto"
    mesh: object = None
    top_k: int = 1
    auto_threshold: int = 1 << 21
    n_kv_heads: int | None = None
    rope: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool):
        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        h = MultiHeadAttention(
            self.d_model, self.n_heads, self.attn_fn, dtype=self.dtype,
            n_kv_heads=self.n_kv_heads, rope=self.rope, name="attn",
        )(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype, name="ln_ffn")(x)
        h = MoEFFN(
            self.d_model, self.d_ff, self.n_experts, self.capacity_factor,
            aux_weight=self.aux_weight, dtype=self.dtype,
            dispatch=self.dispatch, mesh=self.mesh, top_k=self.top_k,
            auto_threshold=self.auto_threshold,
            name="moe",
        )(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        return x + h


class WeatherMoE(nn.Module):
    """MoE encoder over [B, S, F] windows -> [B, num_classes] rain logits."""

    input_dim: int
    seq_len: int
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    n_experts: int = 4
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    num_classes: int = 2
    dropout: float = 0.1
    attn_fn: object = None
    compute_dtype: jnp.dtype = jnp.float32
    dispatch: str = "auto"
    mesh: object = None
    top_k: int = 1
    auto_threshold: int = 1 << 21
    n_kv_heads: int | None = None
    pos_embed: str = "sincos"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        from dct_tpu.ops.attention import make_attention_fn

        attn_fn = self.attn_fn or make_attention_fn(None)
        x = jnp.asarray(x, self.compute_dtype)
        h = TorchStyleDense(self.d_model, dtype=self.compute_dtype, name="in_proj")(x)
        if self.pos_embed != "rope":  # rope rotates q/k inside attention
            h = h + jnp.asarray(
                sincos_positions(self.seq_len, self.d_model),
                self.compute_dtype,
            )
        for i in range(self.n_layers):
            h = MoEBlock(
                self.d_model,
                self.n_heads,
                self.d_ff,
                self.n_experts,
                self.capacity_factor,
                self.dropout,
                attn_fn,
                aux_weight=self.router_aux_weight,
                dtype=self.compute_dtype,
                dispatch=self.dispatch,
                mesh=self.mesh,
                top_k=self.top_k,
                auto_threshold=self.auto_threshold,
                n_kv_heads=self.n_kv_heads,
                rope=self.pos_embed == "rope",
                name=f"block_{i}",
            )(h, train=train)
        h = nn.LayerNorm(dtype=self.compute_dtype, name="ln_out")(h)
        pooled = h.mean(axis=1)
        logits = TorchStyleDense(
            self.num_classes, dtype=self.compute_dtype, name="head"
        )(pooled)
        return jnp.asarray(logits, jnp.float32)
