"""Mixture-of-Experts family: switch-routed FFN with expert parallelism.

The reference has exactly one dense MLP and one parallelism axis (2-rank
DDP, SURVEY §2.3); this family completes the mesh's parallelism matrix —
experts shard over the ``model`` axis (expert parallelism), composing with
batch DP and attention TP/SP in the same jitted step.

TPU-first routing: no ragged tensors, no data-dependent shapes. Top-1
(switch) routing is expressed as dense one-hot dispatch/combine einsums
with a STATIC per-expert capacity:

    dispatch [N_tokens, E, C]  (one-hot: token -> (expert, slot))
    expert_in = einsum('nec,nd->ecd', dispatch, tokens)
    expert_out = per-expert FFN batched over E      <- MXU batched GEMMs
    out = einsum('nec,ecd->nd', dispatch, expert_out) * gate

Tokens over capacity are dropped (their dispatch row is zero); the block's
residual connection passes them through unchanged — standard switch
behavior. Expert weights are [E, D, F] tensors named ``experts_in`` /
``experts_out``; the sharding rules place them ``P("model", None, None)``,
so each expert-parallel shard owns E/shards whole experts and XLA inserts
the token all-to-all implied by the dispatch einsum.

A load-balance auxiliary loss (Switch Transformer's f·P dot) is returned
via ``self.sow("aux_loss", ...)``; the train step folds every sown
``aux_loss`` into the objective, weighted by ``router_aux_weight``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from dct_tpu.models.mlp import TorchStyleDense, torch_linear_init
from dct_tpu.models.transformer import MultiHeadAttention, sincos_positions


class MoEFFN(nn.Module):
    """Switch (top-1) mixture of expert FFNs over flattened tokens."""

    d_model: int
    d_ff: int
    n_experts: int
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):  # [B, S, D] -> [B, S, D]
        b, s, d = x.shape
        n = b * s
        e = self.n_experts
        capacity = max(1, int(self.capacity_factor * n / e))
        tokens = x.reshape(n, d)

        logits = TorchStyleDense(e, dtype=jnp.float32, name="router")(
            jnp.asarray(tokens, jnp.float32)
        )  # [N, E] — routing in f32: tiny matmul, decides everything
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)  # [N]
        gate = jnp.max(probs, axis=-1)  # [N]

        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [N, E]
        # Slot of each token within its expert (arrival order).
        position = jnp.cumsum(onehot, axis=0) - onehot  # [N, E]
        keep = (position < capacity).astype(jnp.float32) * onehot
        slot = jax.nn.one_hot(
            jnp.sum(position * onehot, axis=-1).astype(jnp.int32),
            capacity,
            dtype=jnp.float32,
        )  # [N, C]
        dispatch = keep[:, :, None] * slot[:, None, :]  # [N, E, C]

        # Switch load-balance loss: E * sum_e(frac_tokens_e * mean_prob_e),
        # sown pre-weighted — the train step adds every aux_loss leaf as-is.
        frac = onehot.mean(axis=0)
        mean_prob = probs.mean(axis=0)
        self.sow(
            "aux_loss",
            "load_balance",
            self.aux_weight * e * jnp.sum(frac * mean_prob),
        )

        w_in = self.param(
            "experts_in_kernel",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(k, sh, dt, fan_in=d),
            (e, d, self.d_ff),
            jnp.float32,
        )
        b_in = self.param(
            "experts_in_bias",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(k, sh, dt, fan_in=d),
            (e, self.d_ff),
            jnp.float32,
        )
        w_out = self.param(
            "experts_out_kernel",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(
                k, sh, dt, fan_in=self.d_ff
            ),
            (e, self.d_ff, d),
            jnp.float32,
        )
        b_out = self.param(
            "experts_out_bias",
            lambda k, sh, dt=jnp.float32: torch_linear_init()(
                k, sh, dt, fan_in=self.d_ff
            ),
            (e, d),
            jnp.float32,
        )

        ct = self.dtype
        disp = jnp.asarray(dispatch, ct)
        toks = jnp.asarray(tokens, ct)
        expert_in = jnp.einsum("nec,nd->ecd", disp, toks)  # [E, C, D]
        h = jnp.einsum("ecd,edf->ecf", expert_in, jnp.asarray(w_in, ct))
        h = nn.gelu(h + jnp.asarray(b_in, ct)[:, None, :])
        out_e = jnp.einsum("ecf,efd->ecd", h, jnp.asarray(w_out, ct))
        out_e = out_e + jnp.asarray(b_out, ct)[:, None, :]
        out = jnp.einsum("nec,ecd->nd", disp, out_e)
        out = out * jnp.asarray(gate, ct)[:, None]
        return out.reshape(b, s, d)


class MoEBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    n_experts: int
    capacity_factor: float
    dropout: float
    attn_fn: object
    aux_weight: float = 0.01
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool):
        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        h = MultiHeadAttention(
            self.d_model, self.n_heads, self.attn_fn, dtype=self.dtype,
            name="attn",
        )(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype, name="ln_ffn")(x)
        h = MoEFFN(
            self.d_model, self.d_ff, self.n_experts, self.capacity_factor,
            aux_weight=self.aux_weight, dtype=self.dtype, name="moe",
        )(h)
        h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        return x + h


class WeatherMoE(nn.Module):
    """MoE encoder over [B, S, F] windows -> [B, num_classes] rain logits."""

    input_dim: int
    seq_len: int
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    n_experts: int = 4
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    num_classes: int = 2
    dropout: float = 0.1
    attn_fn: object = None
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        from dct_tpu.ops.attention import make_attention_fn

        attn_fn = self.attn_fn or make_attention_fn(None)
        x = jnp.asarray(x, self.compute_dtype)
        h = TorchStyleDense(self.d_model, dtype=self.compute_dtype, name="in_proj")(x)
        h = h + jnp.asarray(
            sincos_positions(self.seq_len, self.d_model), self.compute_dtype
        )
        for i in range(self.n_layers):
            h = MoEBlock(
                self.d_model,
                self.n_heads,
                self.d_ff,
                self.n_experts,
                self.capacity_factor,
                self.dropout,
                attn_fn,
                aux_weight=self.router_aux_weight,
                dtype=self.compute_dtype,
                name=f"block_{i}",
            )(h, train=train)
        h = nn.LayerNorm(dtype=self.compute_dtype, name="ln_out")(h)
        pooled = h.mean(axis=1)
        logits = TorchStyleDense(
            self.num_classes, dtype=self.compute_dtype, name="head"
        )(pooled)
        return jnp.asarray(logits, jnp.float32)
