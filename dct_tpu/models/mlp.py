"""The flagship rain-classifier MLP, TPU-native.

Capability parity with the reference's ``WeatherClassifier``
(jobs/train_lightning_ddp.py:51-88): Linear(input_dim, hidden) -> ReLU ->
Dropout(p) -> Linear(hidden, num_classes), trained with cross entropy.

Differences by design:
- a pure ``flax.linen`` module: parameters are an explicit pytree, dropout
  randomness is an explicit rng — no module-held mutable state, so the whole
  train step jits and shards;
- compute dtype is configurable (bf16 on the MXU; params stay f32);
- initialization matches torch ``nn.Linear`` defaults
  (U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for both kernel and bias) so the loss
  trajectory starts in the same band as the reference for parity checks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


def torch_linear_init(scale_by_fan_in: bool = True):
    """torch nn.Linear default init: kaiming_uniform(a=sqrt(5)) on the kernel
    reduces to U(-1/sqrt(fan_in), 1/sqrt(fan_in)); bias uses the same bound."""

    def init(key, shape, dtype=jnp.float32, fan_in: int | None = None):
        # flax kernel shape is (fan_in, fan_out); bias callers pass fan_in.
        f = fan_in if fan_in is not None else shape[0]
        bound = 1.0 / jnp.sqrt(jnp.asarray(f, jnp.float32))
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


class TorchStyleDense(nn.Module):
    """Dense layer with torch nn.Linear's default initialization."""

    features: int
    dtype: jnp.dtype | None = None

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        kernel = self.param(
            "kernel", torch_linear_init(), (fan_in, self.features), jnp.float32
        )
        bias = self.param(
            "bias",
            lambda k, s, d=jnp.float32: torch_linear_init()(k, s, d, fan_in=fan_in),
            (self.features,),
            jnp.float32,
        )
        dtype = self.dtype or x.dtype
        return jnp.asarray(x, dtype) @ jnp.asarray(kernel, dtype) + jnp.asarray(
            bias, dtype
        )


class WeatherMLP(nn.Module):
    """MLP rain classifier; logits are always returned in float32."""

    input_dim: int
    hidden_dim: int = 64
    num_classes: int = 2
    dropout: float = 0.2
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = jnp.asarray(x, self.compute_dtype)
        x = TorchStyleDense(self.hidden_dim, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(rate=self.dropout, deterministic=not train)(x)
        x = TorchStyleDense(self.num_classes, dtype=self.compute_dtype)(x)
        return jnp.asarray(x, jnp.float32)
