from dct_tpu.models.mlp import WeatherMLP  # noqa: F401
from dct_tpu.models.registry import get_model, register_model, MODEL_REGISTRY  # noqa: F401
