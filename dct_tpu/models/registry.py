"""Model registry.

The reference hardcodes its single model class inline in the training script
(jobs/train_lightning_ddp.py:51, re-declared again inside the generated
score.py at dags/azure_manual_deploy.py:59-77). Here models are registered by
name so the trainer, the serving package, and the DAGs all resolve the same
definition from config — no copy-pasted architectures.
"""

from __future__ import annotations

from typing import Callable

from flax import linen as nn

from dct_tpu.config import ModelConfig

MODEL_REGISTRY: dict[str, Callable[..., nn.Module]] = {}
# Models that consume [B, S, F] windows instead of [B, F] rows; the Trainer
# switches the data path (make_windows) and init shape on this trait.
SEQUENCE_MODELS: set[str] = set()
# Causal per-position families: windows carry [N, S] next-step labels and
# the model emits [B, S, classes] logits.
CAUSAL_MODELS: set[str] = set()


def register_model(name: str, *, sequence: bool = False, causal: bool = False):
    def deco(builder: Callable[..., nn.Module]):
        MODEL_REGISTRY[name] = builder
        if sequence:
            SEQUENCE_MODELS.add(name)
        if causal:
            CAUSAL_MODELS.add(name)
        return builder

    return deco


def is_sequence_model(name: str) -> bool:
    return name in SEQUENCE_MODELS


def is_causal_model(name: str) -> bool:
    return name in CAUSAL_MODELS


def get_model(cfg: ModelConfig, *, input_dim: int | None = None, **kwargs) -> nn.Module:
    if cfg.name not in MODEL_REGISTRY:
        raise KeyError(
            f"Unknown model '{cfg.name}'. Registered: {sorted(MODEL_REGISTRY)}"
        )
    if cfg.pos_embed not in ("sincos", "rope"):
        # Loud, like the other attention knobs: a typo ("Rope", "rotary")
        # would otherwise silently train with sincos while the operator
        # believes RoPE is on — and serving would mirror the mistake.
        raise ValueError(
            f"pos_embed={cfg.pos_embed!r} must be 'sincos' or 'rope'"
        )
    dim = cfg.input_dim if input_dim is None else input_dim
    if dim is None:
        raise ValueError("input_dim must be provided (inferred from data)")
    return MODEL_REGISTRY[cfg.name](cfg, input_dim=dim, **kwargs)


@register_model("weather_mlp")
def _build_mlp(cfg: ModelConfig, *, input_dim: int, compute_dtype=None):
    import jax.numpy as jnp

    from dct_tpu.models.mlp import WeatherMLP

    return WeatherMLP(
        input_dim=input_dim,
        hidden_dim=cfg.hidden_dim,
        num_classes=cfg.num_classes,
        dropout=cfg.dropout,
        compute_dtype=compute_dtype or jnp.float32,
    )


@register_model("weather_gru", sequence=True)
def _build_gru(
    cfg: ModelConfig, *, input_dim: int, compute_dtype=None, attn_fn=None,
    mesh=None,
):
    # attn_fn/mesh are part of the sequence-model builder interface (the
    # Trainer supplies a mesh-aware attention kernel and the device mesh);
    # recurrence has no use for either.
    del attn_fn, mesh
    import jax.numpy as jnp

    from dct_tpu.models.gru import WeatherGRU

    return WeatherGRU(
        input_dim=input_dim,
        hidden_dim=cfg.hidden_dim,
        n_layers=cfg.n_layers,
        num_classes=cfg.num_classes,
        dropout=cfg.dropout,
        compute_dtype=compute_dtype or jnp.float32,
    )


@register_model("weather_moe", sequence=True)
def _build_moe(
    cfg: ModelConfig, *, input_dim: int, compute_dtype=None, attn_fn=None,
    mesh=None,
):
    import jax.numpy as jnp

    from dct_tpu.models.moe import WeatherMoE

    return WeatherMoE(
        input_dim=input_dim,
        seq_len=cfg.seq_len,
        d_model=cfg.d_model,
        n_heads=cfg.n_heads,
        n_layers=cfg.n_layers,
        d_ff=cfg.d_ff,
        n_experts=cfg.n_experts,
        capacity_factor=cfg.capacity_factor,
        router_aux_weight=cfg.router_aux_weight,
        num_classes=cfg.num_classes,
        dropout=cfg.dropout,
        attn_fn=attn_fn,
        compute_dtype=compute_dtype or jnp.float32,
        dispatch=cfg.moe_dispatch,
        mesh=mesh,
        top_k=cfg.router_top_k,
        auto_threshold=cfg.moe_auto_threshold,
        n_kv_heads=cfg.n_kv_heads if cfg.n_kv_heads > 0 else None,
        pos_embed=cfg.pos_embed,
    )


@register_model("weather_transformer_causal", sequence=True, causal=True)
def _build_transformer_causal(
    cfg: ModelConfig, *, input_dim: int, compute_dtype=None, attn_fn=None,
    mesh=None,
):
    """Decoder-style causal forecaster: per-position next-step supervision
    through CAUSAL attention — the product path for the causal flash
    kernel and the causal ring (the non-causal families never exercise
    them). The Trainer-supplied attn_fn is non-causal, so this builder
    constructs its own from the mesh."""
    del attn_fn
    import jax.numpy as jnp

    from dct_tpu.models.transformer import WeatherTransformer
    from dct_tpu.ops.attention import make_attention_fn

    return WeatherTransformer(
        input_dim=input_dim,
        seq_len=cfg.seq_len,
        d_model=cfg.d_model,
        n_heads=cfg.n_heads,
        n_layers=cfg.n_layers,
        d_ff=cfg.d_ff,
        num_classes=cfg.num_classes,
        dropout=cfg.dropout,
        attn_fn=make_attention_fn(
            mesh, causal=True,
            window=cfg.attn_window if cfg.attn_window > 0 else None,
        ),
        per_position=True,
        horizon=cfg.horizon,
        remat=cfg.remat,
        compute_dtype=compute_dtype or jnp.float32,
        n_kv_heads=cfg.n_kv_heads if cfg.n_kv_heads > 0 else None,
        pos_embed=cfg.pos_embed,
    )


@register_model("weather_transformer_pp", sequence=True)
def _build_transformer_pp(
    cfg: ModelConfig, *, input_dim: int, compute_dtype=None, attn_fn=None,
    mesh=None,
):
    # The passed attn_fn may be mesh-bound (ring over ``seq``); stages run
    # inside the pipeline shard_map where nesting it is illegal — the PP
    # family always uses the single-shard dense/blockwise/flash path.
    del attn_fn
    import jax.numpy as jnp

    from dct_tpu.models.transformer import WeatherTransformerPP
    from dct_tpu.ops.attention import make_attention_fn

    return WeatherTransformerPP(
        input_dim=input_dim,
        seq_len=cfg.seq_len,
        d_model=cfg.d_model,
        n_heads=cfg.n_heads,
        n_layers=cfg.n_layers,
        d_ff=cfg.d_ff,
        num_classes=cfg.num_classes,
        dropout=cfg.dropout,
        n_stages=cfg.n_stages,
        n_microbatches=cfg.n_microbatches,
        attn_fn=make_attention_fn(None),
        mesh=mesh,
        remat=cfg.remat,
        compute_dtype=compute_dtype or jnp.float32,
        n_kv_heads=cfg.n_kv_heads if cfg.n_kv_heads > 0 else None,
        pos_embed=cfg.pos_embed,
    )


@register_model("weather_transformer", sequence=True)
def _build_transformer(
    cfg: ModelConfig, *, input_dim: int, compute_dtype=None, attn_fn=None,
    mesh=None,
):
    del mesh  # attention distribution arrives pre-bound in attn_fn
    import jax.numpy as jnp

    from dct_tpu.models.transformer import WeatherTransformer

    return WeatherTransformer(
        input_dim=input_dim,
        seq_len=cfg.seq_len,
        d_model=cfg.d_model,
        n_heads=cfg.n_heads,
        n_layers=cfg.n_layers,
        d_ff=cfg.d_ff,
        num_classes=cfg.num_classes,
        dropout=cfg.dropout,
        attn_fn=attn_fn,
        remat=cfg.remat,
        compute_dtype=compute_dtype or jnp.float32,
        n_kv_heads=cfg.n_kv_heads if cfg.n_kv_heads > 0 else None,
        pos_embed=cfg.pos_embed,
    )
