"""Recurrent family: GRU over weather windows, shaped for the TPU.

The reference has a single tabular MLP (jobs/train_lightning_ddp.py:51-88);
this family adds recurrent sequence modeling on the same windowed data path
as the transformer. TPU-first structure:

- the input-to-gate projections for ALL timesteps are one large fused
  matmul ([B, S, F] x [F, 3H]) executed before the recurrence — the MXU
  sees a big batched GEMM instead of S small ones;
- only the hidden-to-gate product lives inside the ``lax.scan`` over time
  (the irreducibly sequential part), so the compiled loop body is one
  [B, H] x [H, 3H] matmul plus elementwise gates — static shapes, no
  Python-level stepping;
- gate math follows torch.nn.GRU semantics (reset gate applied to the
  hidden gate pre-activation including its bias), so a torch GRU with the
  same weights is a drop-in numerical oracle for tests.

Parameters use the same TorchStyleDense naming scheme as the other
families; no tensor-parallel name rules match, so the GRU shards
data-parallel with replicated params — same layout as the flagship MLP.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from dct_tpu.models.mlp import TorchStyleDense


class GRULayer(nn.Module):
    """One GRU layer: [B, S, D_in] -> (outputs [B, S, H], last state [B, H])."""

    hidden: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, xs):
        b = xs.shape[0]
        # Fused input projections for every timestep at once: [B, S, 3H]
        # laid out as (r, z, n) gate blocks.
        x_gates = TorchStyleDense(3 * self.hidden, dtype=self.dtype,
                                  name="x_gates")(xs)
        wh = self.param(
            "h_kernel",
            nn.initializers.lecun_normal(),
            (self.hidden, 3 * self.hidden),
            jnp.float32,
        )
        bh = self.param(
            "h_bias", nn.initializers.zeros, (3 * self.hidden,), jnp.float32
        )
        wh_c = jnp.asarray(wh, self.dtype)
        bh_c = jnp.asarray(bh, self.dtype)
        h_dim = self.hidden

        def step(h, xg):
            hg = h @ wh_c + bh_c  # [B, 3H]
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            # torch.nn.GRU applies the reset gate to the full hidden gate
            # pre-activation (including its bias): n = tanh(xn + r*(Wh h + b)).
            n = jnp.tanh(xn + r * hn)
            h_new = (1.0 - z) * n + z * h
            return h_new, h_new

        h0 = jnp.zeros((b, h_dim), self.dtype)
        last, outs = jax.lax.scan(step, h0, jnp.swapaxes(x_gates, 0, 1))
        return jnp.swapaxes(outs, 0, 1), last


class WeatherGRU(nn.Module):
    """Stacked GRU over [B, S, F] windows -> [B, num_classes] rain logits."""

    input_dim: int
    hidden_dim: int = 64
    n_layers: int = 2
    num_classes: int = 2
    dropout: float = 0.2
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        h = jnp.asarray(x, self.compute_dtype)
        last = None
        for i in range(self.n_layers):
            h, last = GRULayer(
                self.hidden_dim, dtype=self.compute_dtype, name=f"gru_{i}"
            )(h)
            if i < self.n_layers - 1:
                h = nn.Dropout(rate=self.dropout, deterministic=not train)(h)
        pooled = nn.Dropout(rate=self.dropout, deterministic=not train)(last)
        logits = TorchStyleDense(
            self.num_classes, dtype=self.compute_dtype, name="head"
        )(pooled)
        return jnp.asarray(logits, jnp.float32)
