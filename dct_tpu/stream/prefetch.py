"""Host-side span prefetch off the consumer.

The PR 5 trainer already double-buffers spans: an ``epoch-prefetch``
executor assembles the NEXT span's stacked host arrays while the
devices dispatch the current one. In stream mode the rows feeding that
span come off the event log, and reading + CRC-checking + JSON-decoding
them is host work that would otherwise serialize into the ETL pass.
:class:`StreamPrefetcher` moves it off the critical path: a background
thread tails the consumer group and stages the next uncommitted span
in memory, so when the ingest watcher's next pass fires, the records
are already decoded and the pass goes straight to transform + publish —
the log read overlaps the trainer's pipelined dispatch instead of
delaying the next generation.

Exactly-once is untouched: staging is in-memory read-ahead only.
Offsets advance durably ONLY via the ETL pass's commit; on any crash
the staged span evaporates and the pass replays from the committed
vector. ``take()`` hands a span to the pass only when it exactly
continues the committed vector (a replay or external commit discards
the stage and re-seeks).
"""

from __future__ import annotations

import threading
import time

from dct_tpu.stream.consumer import ConsumerGroup, committed_offsets


class StreamPrefetcher:
    """Background staging of the next span of records for one group.

    Owns a private :class:`ConsumerGroup` cursor over the same durable
    group (commits are the ETL pass's job); ``take()`` is called from
    the watcher thread, staging happens on the daemon thread.
    """

    def __init__(
        self,
        log,
        group: str = "etl",
        *,
        span_records: int = 8192,
        poll_s: float = 0.2,
        clock=time.time,
    ):
        self.log = log
        self.group = group
        self.span_records = max(1, int(span_records))
        self.poll_s = float(poll_s)
        self._clock = clock
        self._cursor = ConsumerGroup(log, group, clock=clock)
        self._lock = threading.Lock()
        self._staged: list[tuple[int, int, dict]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.staged_spans = 0
        self.hits = 0
        self.misses = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "StreamPrefetcher":
        self._thread = threading.Thread(
            target=self._run, name="stream-prefetch", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- staging thread ------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._fill()
            except Exception:  # noqa: BLE001 — read-ahead must never
                pass  # kill the watcher; the pass falls back to poll()
            self._stop.wait(self.poll_s)

    def _fill(self) -> None:
        with self._lock:
            budget = self.span_records - len(self._staged)
        if budget <= 0:
            return
        got = self._cursor.poll(budget)
        if not got:
            return
        with self._lock:
            self._staged.extend(got)
            self.staged_spans += 1

    # -- the watcher-side handoff --------------------------------------
    def take(self, max_records: int) -> list[tuple[int, int, dict]] | None:
        """The staged span prefix (up to ``max_records``) if it exactly
        continues the group's committed vector; None on a miss (the
        stage is discarded and the cursor re-seeked — the caller polls
        directly)."""
        committed = committed_offsets(
            self.log.offsets_dir, self.group, self.log.n_partitions
        )
        with self._lock:
            staged = self._staged
            first: dict[int, int] = {}
            for k, off, _rec in staged:
                first[k] = min(first.get(k, off), off)
            if not staged or any(first[k] != committed[k] for k in first):
                # Stale stage (replay, or a commit this stager did not
                # make): drop it and restart from the durable vector.
                self._staged = []
                self._cursor.seek_committed()
                if staged:
                    self.misses += 1
                return None
            span = staged[:max_records]
            self._staged = staged[max_records:]
        self.hits += 1
        return span
