"""Streaming ingest data plane (ISSUE 19, ROADMAP item 1).

Per-tenant append-only partitioned event logs, consumer groups with
durable atomically-committed offsets, and an exactly-once streaming
delta ETL that reuses the PR 10 frozen z-score basis machinery — the
substrate that replaces stat-polling a staging CSV with consuming a
partitioned log at event rates, while keeping the trainer's data
contract (the Spark-style parquet snapshot + ``etl_state.json``)
byte-for-byte unchanged.

Modules:

- :mod:`~dct_tpu.stream.log` — topics -> partitions -> CRC-framed
  segment files; single-writer producer with batched appends, watermark
  sidecars, tmp+``os.replace`` segment seals, crash-safe torn-tail
  truncation on reopen, and lag-budget backpressure (block or shed).
- :mod:`~dct_tpu.stream.consumer` — consumer groups: a resumable
  iterator over the partition set, durable offset commits (the offset
  vector rides into checkpoint meta exactly like ``data_generation``),
  and per-group lag accounting in records and seconds behind the
  producer watermark.
- :mod:`~dct_tpu.stream.stream_etl` — one committed offset range ->
  one idempotent offset-range-named parquet part under the frozen
  basis; a crash between transform and commit replays without
  duplicate rows.
- :mod:`~dct_tpu.stream.prefetch` — background staging of the next
  uncommitted span off the consumer, overlapping log reads and JSON
  decode with the trainer's pipelined dispatch.

Wiring lives where the consumers are: ``DCT_INGEST_MODE=stream`` flips
the continuous loop's watcher (:mod:`dct_tpu.continuous.ingest`), the
SLO freshness spec (:mod:`dct_tpu.observability.slo`) to consumer lag,
and the scheduler's tenants to one stream per workload.
"""

from dct_tpu.stream.log import PartitionedEventLog, StreamProducer
from dct_tpu.stream.consumer import ConsumerGroup

__all__ = ["PartitionedEventLog", "StreamProducer", "ConsumerGroup"]
