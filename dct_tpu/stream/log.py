"""Append-only partitioned event log: the streaming ingest substrate.

Layout (one tree per tenant; ``DCT_STREAM_DIR`` is the root)::

    <root>/<topic>/p<k>/segment-<base>.log        sealed (immutable)
    <root>/<topic>/p<k>/segment-<base>.log.tmp    active (append-only)
    <root>/<topic>/p<k>/watermark.json            producer watermark
    <root>/<topic>/p<k>/segments.json             sealed-segment lineage
    <root>/<topic>/offsets/<group>.json           consumer-group commits

Records are CRC-framed: an 8-byte little-endian header (payload length
+ crc32) followed by the JSON payload. Offsets are per-partition record
indices; a segment file's name carries the offset of its first record,
so the partition's end offset is derivable by scanning ONE file.

Durability contract, per the atomic-publish lint's taxonomy:

- the ACTIVE segment is append-mode writes to a tmp-flavored name —
  in-progress state that readers must tolerate mid-write (the CRC
  framing makes a torn tail detectable, never consumable);
- sealing is ``os.replace`` of the full tmp file onto its final
  ``segment-<base>.log`` name — the atomic publish;
- reopening after a crash scans the active segment and TRUNCATES at
  the first bad frame (torn tail from a killed producer), so appends
  resume at exactly the last durable record;
- the watermark sidecar (end offset + newest/oldest event timestamps)
  is published tmp-then-replace after every append batch, so lag
  accounting never reads a half-written JSON.

Single-writer per partition by design (the CSV staging writer's
contract, kept): one producer process owns appends; consumer groups
are read-only over the same tree.

Backpressure (:class:`StreamProducer`): when the slowest registered
consumer group falls more than ``lag_budget`` records behind, the
producer either BLOCKS (bounded by ``block_timeout_s``, then sheds —
lag stays bounded even against a dead consumer) or SHEDS the batch
outright, counting every action on the ``dct_stream_backpressure_total``
counter and the event log. Unbounded lag is a config error this class
refuses to express.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib

#: Record frame header: <payload length, crc32(payload)>.
_HDR = struct.Struct("<II")

#: Sealed-segment name (base = offset of the segment's first record).
_SEGMENT_FMT = "segment-{base:020d}.log"
#: The active segment appends under a tmp-flavored name until sealed.
_ACTIVE_SUFFIX = ".log.tmp"

WATERMARK_NAME = "watermark.json"
SEGMENTS_NAME = "segments.json"

#: Reserved record key carrying the event's arrival timestamp (event
#: time, not append time) — the freshness plane's source of truth.
TS_KEY = "_ts"


def _frame(payload: bytes) -> bytes:
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def _scan_frames(path: str) -> tuple[int, int, bytes | None]:
    """-> (record count, valid byte length, last payload). Stops at the
    first torn/corrupt frame: everything after it is not data."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return 0, 0, None
    pos = count = 0
    last = None
    n = len(data)
    while pos + _HDR.size <= n:
        length, crc = _HDR.unpack_from(data, pos)
        end = pos + _HDR.size + length
        if end > n:
            break
        payload = data[pos + _HDR.size:end]
        if zlib.crc32(payload) != crc:
            break
        pos, count, last = end, count + 1, payload
    return count, pos, last


def _iter_frames(path: str):
    """Yield payload bytes per valid frame (same torn-tail stop rule)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return
    pos = 0
    n = len(data)
    while pos + _HDR.size <= n:
        length, crc = _HDR.unpack_from(data, pos)
        end = pos + _HDR.size + length
        if end > n:
            return
        payload = data[pos + _HDR.size:end]
        if zlib.crc32(payload) != crc:
            return
        yield payload
        pos = end


def _atomic_json(path: str, obj: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return {}
    return obj if isinstance(obj, dict) else {}


def _parse_base(name: str) -> int | None:
    if not name.startswith("segment-"):
        return None
    stem = name[len("segment-"):]
    for suffix in (_ACTIVE_SUFFIX, ".log"):
        if stem.endswith(suffix):
            try:
                return int(stem[: -len(suffix)])
            except ValueError:
                return None
    return None


class _Partition:
    """One partition's files. Producer-side state (handle, counters) is
    built on first append; the read path re-lists the directory every
    call so a consumer process sees concurrent seals/appends."""

    def __init__(
        self,
        pdir: str,
        *,
        topic: str,
        index: int,
        segment_records: int,
        segment_bytes: int,
        readonly: bool,
        clock,
        emit,
    ):
        self.dir = pdir
        self.topic = topic
        self.index = index
        self.segment_records = max(1, int(segment_records))
        self.segment_bytes = max(1, int(segment_bytes))
        self.readonly = readonly
        self._clock = clock
        self._emit = emit or (lambda *a, **k: None)
        self._fh = None
        self._active_bytes = 0
        self._first_ts: float | None = None
        self._last_ts: float | None = None
        if not readonly:
            os.makedirs(pdir, exist_ok=True)
        self._recover()

    # -- recovery ------------------------------------------------------
    def _recover(self) -> None:
        """Establish (base, count) of the active position; truncate a
        torn tail left by a killed producer (write mode only)."""
        self.base = 0
        self.count = 0
        segs = self._list_segments()
        if not segs:
            wm = _read_json(os.path.join(self.dir, WATERMARK_NAME))
            self._first_ts = wm.get("first_ts")
            self._last_ts = wm.get("ts")
            return
        base, path, active = segs[-1]
        count, valid, last = _scan_frames(path)
        if active:
            self.base, self.count = base, count
            try:
                torn = os.path.getsize(path) - valid
            except OSError:
                torn = 0
            if torn > 0 and not self.readonly:
                with open(path, "rb+") as f:
                    f.truncate(valid)
                self._emit(
                    "stream", "stream.truncated",
                    topic=self.topic, partition=self.index,
                    bytes=torn, end_offset=base + count,
                )
            self._active_bytes = valid
        else:
            # No active file: the next append starts a new segment
            # right after the last sealed one.
            self.base, self.count = base + count, 0
        wm = _read_json(os.path.join(self.dir, WATERMARK_NAME))
        self._first_ts = wm.get("first_ts")
        self._last_ts = wm.get("ts")
        if last is not None and wm.get("end_offset", 0) > self.end_offset:
            # The sidecar outran the truncated tail: re-derive the
            # watermark from the last DURABLE record.
            try:
                self._last_ts = json.loads(last).get(TS_KEY)
            except ValueError:
                pass
            if not self.readonly:
                self._publish_watermark()

    def _list_segments(self) -> list[tuple[int, str, bool]]:
        """Sorted (base, path, is_active) — fresh from the directory,
        so read-side callers observe concurrent producer activity."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            base = _parse_base(name)
            if base is None:
                continue
            out.append((
                base, os.path.join(self.dir, name),
                name.endswith(_ACTIVE_SUFFIX),
            ))
        out.sort()
        return out

    # -- producer side -------------------------------------------------
    @property
    def end_offset(self) -> int:
        return self.base + self.count

    def _active_path(self) -> str:
        return os.path.join(
            self.dir, f"segment-{self.base:020d}{_ACTIVE_SUFFIX}"
        )

    def append(self, payloads: list[bytes], ts: float | None) -> tuple[int, int]:
        """Append one framed batch; returns [start, end) offsets."""
        if self.readonly:
            raise RuntimeError("partition opened readonly")
        if not payloads:
            return self.end_offset, self.end_offset
        if self._fh is None:
            self._fh = open(self._active_path(), "ab")
        buf = bytearray()
        for p in payloads:
            buf += _frame(p)
        self._fh.write(buf)
        self._fh.flush()
        start = self.end_offset
        self.count += len(payloads)
        self._active_bytes += len(buf)
        ts = self._clock() if ts is None else float(ts)
        if self._first_ts is None:
            self._first_ts = ts
        self._last_ts = ts
        self._publish_watermark()
        if (
            self.count >= self.segment_records
            or self._active_bytes >= self.segment_bytes
        ):
            self._seal()
        return start, self.end_offset

    def _publish_watermark(self) -> None:
        _atomic_json(os.path.join(self.dir, WATERMARK_NAME), {
            "end_offset": self.end_offset,
            "ts": self._last_ts,
            "first_ts": self._first_ts,
            "published_ts": round(self._clock(), 6),
        })

    def _seal(self) -> None:
        """Atomic publish of the active segment onto its final name;
        the sealed file becomes a ``stream_segment`` lineage node."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        active = self._active_path()
        final = os.path.join(self.dir, _SEGMENT_FMT.format(base=self.base))
        records = self.count
        os.replace(active, final)
        nid = self._record_segment_lineage(final, records)
        self._emit(
            "stream", "stream.seal",
            topic=self.topic, partition=self.index,
            base_offset=self.base, records=records,
            bytes=self._active_bytes, lineage_node=nid,
        )
        self.base += records
        self.count = 0
        self._active_bytes = 0

    def _record_segment_lineage(self, final: str, records: int) -> str | None:
        from dct_tpu.observability import lineage as _lineage

        lin = _lineage.get_default()
        if not lin.enabled:
            return None
        nid = lin.node(
            "stream_segment", path=final,
            attrs={
                "topic": self.topic, "partition": self.index,
                "base_offset": self.base, "records": records,
            },
        )
        if nid:
            # The seal-time sidecar lets a consumer process link its
            # offset commits to the segments they covered without
            # re-hashing the log.
            spath = os.path.join(self.dir, SEGMENTS_NAME)
            manifest = _read_json(spath)
            manifest[os.path.basename(final)] = {
                "nid": nid, "base": self.base, "records": records,
            }
            _atomic_json(spath, manifest)
        return nid

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- read side -----------------------------------------------------
    def read_from(self, offset: int, max_records: int) -> list[tuple[int, dict]]:
        """Records from ``offset`` onward, capped at ``max_records`` —
        (offset, record) pairs across segment boundaries. A torn tail
        (concurrent producer mid-write) simply ends the scan."""
        out: list[tuple[int, dict]] = []
        segs = self._list_segments()
        for i, (base, path, _active) in enumerate(segs):
            nxt = segs[i + 1][0] if i + 1 < len(segs) else None
            if nxt is not None and nxt <= offset:
                continue  # entirely below the requested offset
            off = base
            for payload in _iter_frames(path):
                if off >= offset:
                    try:
                        out.append((off, json.loads(payload)))
                    except ValueError:
                        return out  # corrupt mid-log: stop, don't skip
                    if len(out) >= max_records:
                        return out
                off += 1
        return out

    def end_offset_fresh(self) -> int:
        """End offset from the directory (consumer-side; the producer's
        in-memory counter is not visible cross-process). The watermark
        sidecar is the cheap source; a missing/stale one falls back to
        scanning the newest segment."""
        wm = _read_json(os.path.join(self.dir, WATERMARK_NAME))
        segs = self._list_segments()
        if not segs:
            return int(wm.get("end_offset") or 0)
        base, path, _ = segs[-1]
        if isinstance(wm.get("end_offset"), int) and wm["end_offset"] >= base:
            return wm["end_offset"]
        count, _, _ = _scan_frames(path)
        return base + count

    def watermark(self) -> dict:
        return _read_json(os.path.join(self.dir, WATERMARK_NAME))

    def segment_lineage(self) -> dict:
        return _read_json(os.path.join(self.dir, SEGMENTS_NAME))


class PartitionedEventLog:
    """One topic's partition set under ``<root>/<topic>/``.

    ``partitions=0`` discovers the partition count from the directory
    (a consumer opening a producer's tree); writers must pass the
    count explicitly. ``readonly=True`` never creates files and never
    truncates — the consumer-group mode.
    """

    def __init__(
        self,
        root: str,
        topic: str = "events",
        *,
        partitions: int = 0,
        segment_records: int = 4096,
        segment_bytes: int = 1 << 22,
        readonly: bool = False,
        emit=None,
        clock=time.time,
    ):
        self.root = root
        self.topic = topic
        self.topic_dir = os.path.join(root, topic)
        self._emit = emit
        self._clock = clock
        if partitions <= 0:
            found = 0
            try:
                for name in os.listdir(self.topic_dir):
                    if name.startswith("p") and name[1:].isdigit():
                        found = max(found, int(name[1:]) + 1)
            except OSError:
                pass
            partitions = max(1, found)
        self.partitions = [
            _Partition(
                os.path.join(self.topic_dir, f"p{k}"),
                topic=topic, index=k,
                segment_records=segment_records,
                segment_bytes=segment_bytes,
                readonly=readonly, clock=clock, emit=emit,
            )
            for k in range(partitions)
        ]

    @property
    def n_partitions(self) -> int:
        return len(self.partitions)

    @property
    def offsets_dir(self) -> str:
        return os.path.join(self.topic_dir, "offsets")

    def append(
        self, partition: int, records: list[dict], *, ts: float | None = None
    ) -> tuple[int, int]:
        """Batched append of JSON records to one partition; returns the
        [start, end) offset range. ``ts`` stamps the batch watermark
        (defaults to the newest ``_ts`` in the batch, else now)."""
        if ts is None:
            stamps = [
                r[TS_KEY] for r in records
                if isinstance(r.get(TS_KEY), (int, float))
            ]
            ts = max(stamps) if stamps else None
        payloads = [
            json.dumps(r, separators=(",", ":")).encode() for r in records
        ]
        return self.partitions[partition].append(payloads, ts)

    def read(
        self, partition: int, offset: int, *, max_records: int = 1024
    ) -> list[tuple[int, dict]]:
        return self.partitions[partition].read_from(offset, max_records)

    def end_offsets(self, *, fresh: bool = False) -> list[int]:
        if fresh:
            return [p.end_offset_fresh() for p in self.partitions]
        return [p.end_offset for p in self.partitions]

    def watermark(self) -> dict:
        """Producer watermark across partitions: newest/oldest event
        timestamps plus the per-partition end offsets."""
        ts = first = None
        ends = []
        for p in self.partitions:
            wm = p.watermark()
            ends.append(int(wm.get("end_offset") or 0))
            t = wm.get("ts")
            if isinstance(t, (int, float)):
                ts = t if ts is None else max(ts, t)
            f = wm.get("first_ts")
            if isinstance(f, (int, float)):
                first = f if first is None else min(first, f)
        return {"ts": ts, "first_ts": first, "end_offsets": ends}

    def close(self) -> None:
        for p in self.partitions:
            p.close()


class StreamProducer:
    """Batched producer with lag-budget backpressure.

    ``produce()`` buffers; ``flush()`` appends one batch per partition
    after consulting every registered consumer group's record lag:
    over-budget means BLOCK (poll until the slowest group catches up,
    bounded by ``block_timeout_s``, then shed the batch — a dead
    consumer must not grow the log unboundedly) or SHED immediately.
    Counters: ``produced`` / ``shed`` / ``blocks`` / ``blocked_s``.
    """

    def __init__(
        self,
        log: PartitionedEventLog,
        *,
        groups: tuple[str, ...] = ("etl",),
        backpressure: str = "block",
        lag_budget: int = 50000,
        block_timeout_s: float = 30.0,
        batch_records: int = 256,
        emit=None,
        clock=time.time,
        sleep=time.sleep,
        registry=None,
    ):
        if backpressure not in ("block", "shed", "off"):
            raise ValueError(
                f"backpressure must be block|shed|off, got {backpressure!r}"
            )
        self.log = log
        self.groups = tuple(groups)
        self.backpressure = backpressure
        self.lag_budget = max(1, int(lag_budget))
        self.block_timeout_s = float(block_timeout_s)
        self.batch_records = max(1, int(batch_records))
        self._emit = emit or (lambda *a, **k: None)
        self._clock = clock
        self._sleep = sleep
        self._buffers: list[list[dict]] = [
            [] for _ in range(log.n_partitions)
        ]
        self._buffered = 0
        self._rr = 0
        self.produced = 0
        self.shed = 0
        self.blocks = 0
        self.blocked_s = 0.0
        self._produced_c = self._bp_c = self._wm_g = None
        if registry is not None:
            self._produced_c = registry.counter(
                "dct_stream_produced_total",
                "Records appended to the partitioned event log.",
            )
            self._bp_c = registry.counter(
                "dct_stream_backpressure_total",
                "Producer backpressure actions (label: action=block|shed).",
            )
            self._wm_g = registry.gauge(
                "dct_stream_watermark_ts",
                "Newest event timestamp appended per topic.", agg="max",
            )

    def produce(
        self, record: dict, *, partition: int | None = None,
        ts: float | None = None,
    ) -> None:
        """Buffer one record (round-robin partitioning by default);
        stamps ``_ts`` = event arrival time when absent."""
        if TS_KEY not in record:
            record = {**record, TS_KEY: round(
                self._clock() if ts is None else ts, 6
            )}
        if partition is None:
            partition = self._rr % self.log.n_partitions
            self._rr += 1
        self._buffers[partition].append(record)
        self._buffered += 1
        if self._buffered >= self.batch_records:
            self.flush()

    def lag_records(self) -> int:
        """The SLOWEST registered group's record lag (0 when no group
        has committed yet AND nothing was produced)."""
        from dct_tpu.stream.consumer import committed_offsets

        ends = self.log.end_offsets()
        total = sum(ends)
        worst = 0
        for group in self.groups:
            committed = committed_offsets(
                self.log.offsets_dir, group, self.log.n_partitions
            )
            worst = max(worst, total - sum(committed))
        return worst

    def _admit(self, n_pending: int) -> bool:
        """Backpressure gate for one flush; False = shed the batch."""
        if self.backpressure == "off" or not self.groups:
            return True
        lag = self.lag_records()
        if lag + n_pending <= self.lag_budget:
            return True
        if self.backpressure == "shed":
            self._note_backpressure("shed", lag)
            return False
        t0 = self._clock()
        self.blocks += 1
        self._note_backpressure("block", lag)
        while self._clock() - t0 < self.block_timeout_s:
            self._sleep(0.05)
            lag = self.lag_records()
            if lag + n_pending <= self.lag_budget:
                self.blocked_s += self._clock() - t0
                return True
        self.blocked_s += self._clock() - t0
        # Block timed out: the consumer is dead or wedged. Shedding is
        # the only way the lag bound survives — never append anyway.
        self._note_backpressure("shed", lag)
        return False

    def _note_backpressure(self, action: str, lag: int) -> None:
        if action == "shed":
            self.shed += self._buffered
        if self._bp_c is not None:
            self._bp_c.inc(labels={"action": action})
        self._emit(
            "stream", "stream.backpressure",
            action=action, lag_records=lag,
            lag_budget=self.lag_budget, pending=self._buffered,
        )

    def flush(self) -> int:
        """Append every buffered record (or shed the lot under
        backpressure); returns the number of records appended."""
        if self._buffered == 0:
            return 0
        if not self._admit(self._buffered):
            for buf in self._buffers:
                buf.clear()
            self._buffered = 0
            return 0
        appended = 0
        wm_ts = None
        for k, buf in enumerate(self._buffers):
            if not buf:
                continue
            self.log.append(k, buf)
            appended += len(buf)
            stamps = [r.get(TS_KEY) for r in buf]
            stamps = [t for t in stamps if isinstance(t, (int, float))]
            if stamps:
                wm_ts = max(stamps) if wm_ts is None else max(
                    wm_ts, max(stamps)
                )
            buf.clear()
        self._buffered = 0
        self.produced += appended
        if self._produced_c is not None:
            self._produced_c.inc(appended, labels={"topic": self.log.topic})
        if self._wm_g is not None and wm_ts is not None:
            self._wm_g.set(wm_ts, labels={"topic": self.log.topic})
        return appended

    def close(self) -> None:
        self.flush()
        self.log.close()
