"""Consumer groups over the partitioned event log.

A group is a durable offset vector (one next-offset per partition)
committed atomically to ``<topic>/offsets/<group>.json``. ``poll()``
is the resumable iterator: it reads from the in-memory position
(seeded from the last commit), round-robin across partitions;
``commit()`` makes a position durable together with arbitrary
``meta`` — the stream ETL parks its whole ``etl_state`` payload there,
which is what makes the commit the exactly-once transaction boundary
(a crash after the parquet part but before the commit replays the
same records; a crash after the commit heals the state file FROM the
commit).

Lag accounting, both units the freshness plane needs:

- ``records``: producer end offsets minus the committed vector;
- ``seconds``: producer watermark timestamp minus the committed
  watermark timestamp (event time, so it measures how old the newest
  TRAINABLE event is relative to the newest ARRIVED event).

Each commit becomes an ``offset_commit`` lineage node with
``consumed`` edges to the sealed segments the committed range covered
(via the seal-time sidecar — no segment re-hash).
"""

from __future__ import annotations

import json
import os
import time

from dct_tpu.stream.log import (
    TS_KEY,
    PartitionedEventLog,
    _atomic_json,
    _read_json,
)

COMMIT_VERSION = 1


def _commit_path(offsets_dir: str, group: str) -> str:
    return os.path.join(offsets_dir, f"{group}.json")


def read_commit(offsets_dir: str, group: str) -> dict:
    """The group's last durable commit record ({} when none/torn)."""
    rec = _read_json(_commit_path(offsets_dir, group))
    if rec.get("version") != COMMIT_VERSION:
        return {}
    return rec


def committed_offsets(
    offsets_dir: str, group: str, n_partitions: int
) -> list[int]:
    """The committed next-offset vector, zero-padded to the partition
    count (a group that never committed is at the log's beginning)."""
    rec = read_commit(offsets_dir, group)
    offsets = [int(o) for o in (rec.get("offsets") or [])]
    while len(offsets) < n_partitions:
        offsets.append(0)
    return offsets[:n_partitions]


class ConsumerGroup:
    """One group's resumable cursor over a :class:`PartitionedEventLog`
    (opened readonly by the caller — consumers never create or truncate
    log files)."""

    def __init__(
        self,
        log: PartitionedEventLog,
        group: str = "etl",
        *,
        emit=None,
        clock=time.time,
        registry=None,
    ):
        self.log = log
        self.group = group
        self._emit = emit or (lambda *a, **k: None)
        self._clock = clock
        self.consumed = 0
        self.commits = 0
        self._consumed_c = self._commits_c = None
        self._lag_rec_g = self._lag_sec_g = None
        if registry is not None:
            self._consumed_c = registry.counter(
                "dct_stream_consumed_total",
                "Records polled off the event log per consumer group.",
            )
            self._commits_c = registry.counter(
                "dct_stream_commits_total",
                "Durable offset commits per consumer group.",
            )
            self._lag_rec_g = registry.gauge(
                "dct_stream_lag_records",
                "Records behind the producer end offsets per group.",
                agg="max",
            )
            self._lag_sec_g = registry.gauge(
                "dct_stream_lag_seconds",
                "Seconds the newest trainable event trails the newest "
                "arrived event (event time) per group.", agg="max",
            )
        self.positions = committed_offsets(
            log.offsets_dir, group, log.n_partitions
        )

    # -- cursor --------------------------------------------------------
    def seek_committed(self) -> list[int]:
        """Reset the in-memory cursor to the last durable commit (the
        replay entry point after any failed pass)."""
        self.positions = committed_offsets(
            self.log.offsets_dir, self.group, self.log.n_partitions
        )
        return list(self.positions)

    def poll(self, max_records: int = 1024) -> list[tuple[int, int, dict]]:
        """Up to ``max_records`` (partition, offset, record) triples
        from the current position, advancing it (in memory only —
        nothing is durable until :meth:`commit`). Partition order is
        fixed p0..pN so a replay from the same committed vector reads
        the same prefix in the same order."""
        out: list[tuple[int, int, dict]] = []
        for k in range(self.log.n_partitions):
            budget = max_records - len(out)
            if budget <= 0:
                break
            got = self.log.read(k, self.positions[k], max_records=budget)
            for off, rec in got:
                out.append((k, off, rec))
            if got:
                self.positions[k] = got[-1][0] + 1
        self.consumed += len(out)
        if self._consumed_c is not None and out:
            self._consumed_c.inc(len(out), labels={"group": self.group})
        return out

    # -- durability ----------------------------------------------------
    def commit(
        self,
        offsets: list[int] | None = None,
        *,
        watermark_ts: float | None = None,
        meta: dict | None = None,
    ) -> dict:
        """Atomically publish the offset vector (+ the committed
        watermark timestamp and the caller's ``meta`` payload). Returns
        the commit record, with its lineage node id under
        ``lineage_node`` when the ledger is armed."""
        offsets = list(self.positions if offsets is None else offsets)
        os.makedirs(self.log.offsets_dir, exist_ok=True)
        rec = {
            "version": COMMIT_VERSION,
            "group": self.group,
            "offsets": offsets,
            "watermark_ts": watermark_ts,
            "committed_ts": round(self._clock(), 6),
            "meta": meta or {},
        }
        rec["lineage_node"] = self._record_commit_lineage(rec)
        _atomic_json(_commit_path(self.log.offsets_dir, self.group), rec)
        self.positions = list(offsets)
        self.commits += 1
        if self._commits_c is not None:
            self._commits_c.inc(labels={"group": self.group})
        return rec

    def _record_commit_lineage(self, rec: dict) -> str | None:
        """offset_commit node (content-addressed from the group +
        vector) with ``consumed`` edges to every sealed segment the
        committed range covers."""
        from dct_tpu.observability import lineage as _lineage

        lin = _lineage.get_default()
        if not lin.enabled:
            return None
        nid = lin.node(
            "offset_commit",
            content={"group": self.group, "offsets": rec["offsets"]},
            attrs={
                "group": self.group,
                "offsets": rec["offsets"],
                "watermark_ts": rec["watermark_ts"],
            },
        )
        for k, part in enumerate(self.log.partitions):
            for info in part.segment_lineage().values():
                base = int(info.get("base") or 0)
                if base < rec["offsets"][k] and info.get("nid"):
                    lin.edge("consumed", nid, info["nid"])
        return nid

    # -- lag -----------------------------------------------------------
    def lag(self) -> dict:
        """{"records", "seconds"} behind the producer (event time).
        ``seconds`` falls back to the log's OLDEST event timestamp for
        a group that never committed — pending data is late data."""
        ends = self.log.end_offsets(fresh=True)
        committed = committed_offsets(
            self.log.offsets_dir, self.group, self.log.n_partitions
        )
        records = max(0, sum(ends) - sum(committed))
        seconds = 0.0
        if records > 0:
            wm = self.log.watermark()
            newest = wm.get("ts")
            rec = read_commit(self.log.offsets_dir, self.group)
            floor = rec.get("watermark_ts")
            if floor is None:
                floor = wm.get("first_ts")
            if isinstance(newest, (int, float)) and isinstance(
                floor, (int, float)
            ):
                seconds = max(0.0, float(newest) - float(floor))
        if self._lag_rec_g is not None:
            self._lag_rec_g.set(records, labels={"group": self.group})
        if self._lag_sec_g is not None:
            self._lag_sec_g.set(seconds, labels={"group": self.group})
        return {"records": records, "seconds": round(seconds, 6)}


def group_lag_seconds(
    stream_dir: str, topic: str, group: str
) -> float | None:
    """Event-time lag of ``group`` behind the producer watermark, from
    the on-disk tree alone (no producer/consumer object needed) — the
    SLO freshness plane's stream source. None when the topic has no
    data yet (no evidence is not an alert)."""
    topic_dir = os.path.join(stream_dir, topic)
    if not os.path.isdir(topic_dir):
        return None
    log = PartitionedEventLog(stream_dir, topic, readonly=True)
    wm = log.watermark()
    newest = wm.get("ts")
    if not isinstance(newest, (int, float)):
        return None
    ends = log.end_offsets(fresh=True)
    committed = committed_offsets(
        log.offsets_dir, group, log.n_partitions
    )
    if sum(ends) <= sum(committed):
        return 0.0
    rec = read_commit(log.offsets_dir, group)
    floor = rec.get("watermark_ts")
    if floor is None:
        floor = wm.get("first_ts")
    if not isinstance(floor, (int, float)):
        return None
    return max(0.0, float(newest) - float(floor))


__all__ = [
    "ConsumerGroup",
    "read_commit",
    "committed_offsets",
    "group_lag_seconds",
    "TS_KEY",
]
